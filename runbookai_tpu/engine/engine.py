"""Continuous-batching serving engine (host loop) over the paged JAX model.

The TPU-native replacement for the reference's hosted-LLM HTTP calls
(``src/model/llm.ts``): requests are admitted mid-flight, prompts prefill in
fixed-size chunks, and all live sequences share one compiled decode step over
a fixed batch of slots (static shapes — the same XLA program every step).

Scheduling policy per :meth:`EngineCore.step`:

1. admit waiting requests while decode slots + KV pages allow;
2. run one prefill chunk for the oldest prefilling request (prefill and
   decode interleave so TTFT of new requests doesn't starve running decodes);
3. run one batched decode step for every decoding request;
4. finish/evict sequences (stop tokens, budgets, grammar end), free pages.

Preemption: if the page pool is exhausted mid-decode the *youngest* request is
preempted by recompute (pages freed, generated tokens folded into its prompt,
re-queued) — forward progress for the rest is preserved.

Static-shape tricks:

- decode always runs with ``B = max_batch_slots``; empty slots carry a null
  page table and ``ctx_len = 0`` (fully masked attention).
- prefill chunks are right-padded to ``prefill_chunk``; pad tokens write their
  K/V into the reserved null page (page 0) via an extra "trash" page-table
  column at logical position ``max_pages``, so they can never corrupt live
  cache state.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, fields
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from runbookai_tpu.engine.flight_recorder import FlightRecorder
from runbookai_tpu.engine.kv_cache import KVCacheManager, hash_blocks
from runbookai_tpu.engine.request import (
    EngineOutput,
    EngineRequest,
    FinishReason,
    RequestState,
)
from runbookai_tpu.models.llama import (
    LlamaConfig,
    forward_impl,
    forward_ragged_impl,
)
from runbookai_tpu.ops.sampling import sample_tokens
from runbookai_tpu.sched import class_label, class_name
from runbookai_tpu.utils import metrics as metrics_mod
from runbookai_tpu.utils.trace import annotate, get_tracer


@dataclass
class EngineConfig:
    page_size: int = 16
    num_pages: int = 2048
    max_batch_slots: int = 8
    prefill_chunk: int = 256
    max_seq_len: int = 8192
    block_pages: int = 32
    kv_dtype: Any = jnp.bfloat16
    # Reserve this many pages of headroom per admitted sequence so decode can
    # proceed a while before needing new allocations.
    admit_headroom_tokens: int = 64
    # Max decode tokens sampled per device dispatch (amortizes the host sync;
    # clamped to powers of two to bound compile count). Guided requests force 1.
    decode_steps_per_dispatch: int = 8
    # Decode attention implementation: "xla" (portable) | "pallas" (TPU kernel).
    attn_impl: str = "xla"
    # Quantized-matmul implementation for int8 weights: "pallas" streams the
    # int8 tiles through ops/qmm_pallas.py at decode/verify shapes (half the
    # bf16 HBM bytes by construction); "xla" trusts the compiler to fuse the
    # widen into the dot. Single-model-shard only (forward_impl downgrades
    # under a TP mesh); unquantized weights ignore it.
    qmm_impl: str = "xla"
    # Sequences whose prefill chunks run in ONE batched dispatch per step.
    # Under N concurrent submissions, prefill wall-clock drops ~N× vs the
    # one-sequence-per-step serialization (VERDICT r1 weak #5); rows are
    # padded to powers of two to bound distinct compiled programs.
    prefill_batch: int = 4
    # Prompt-lookup speculative decoding (greedy requests): draft the tokens
    # that followed the last occurrence of the trailing n-gram in the
    # sequence's own history, verify all of them in ONE T=K forward (a
    # parallel MXU matmul instead of K sequential decode steps). Agent
    # workloads repeat heavily (tool names, JSON keys, service ids), so
    # acceptance rates are high; a miss still yields one token per dispatch.
    speculative: bool = True
    spec_ngram: int = 3
    # Grammar fast-forward for guided requests: emit mask-forced token runs
    # without per-token decode dispatches by folding them into a prefill
    # chunk. A win where dispatch latency dominates (the tunneled TPU pays
    # ~70ms per host sync regardless of T); a LOSS on CPU, where compute
    # scales with the padded chunk length — None = auto (on for tpu/axon).
    grammar_fast_forward: Optional[bool] = None
    # Overlapped decode pipeline (one-step lag): the sampled token buffer
    # stays device-resident and feeds the next dispatch directly, while a
    # window's tokens are copied to host asynchronously and consumed when
    # the NEXT window is already in flight — detokenization, stop scans and
    # stream emission run behind the device step instead of serializing it.
    # Stop conditions therefore fire one window late (emit-then-truncate:
    # the overshoot window's tokens are discarded, its KV pages reclaimed
    # on finish). Guided/logprob batches, spec verify, preemption and the
    # context-limit boundary force a synchronous drain first, so token
    # streams are byte-identical to ``False`` (forced-sync) mode.
    overlap_decode: bool = True
    # Max rounds to skip re-probing speculation after rounds that produced
    # no usable drafts. Draft construction needs the host-current history,
    # so each probe drains the overlapped window; backing off (1, 2, 4, …
    # up to this cap per consecutive miss) keeps the lag pipeline hot on
    # non-repetitive traffic while repetitive traffic re-enters
    # speculation within a couple of rounds.
    spec_backoff_rounds: int = 8
    # Unified mixed prefill+decode dispatch: whenever prompts and decodes
    # coexist, ONE ragged forward serves every live decode slot (1 token
    # each) plus the oldest prefill chunk(s), and a prefill row completing
    # its prompt samples its first token in the same dispatch — the 2
    # dispatches/step a prompt burst used to cost become 1 (the tunneled
    # TPU pays ~70ms per host sync regardless of T). None = auto: on for
    # tpu/axon where dispatch latency dominates, off on CPU where compute
    # scales with the padded ragged buffer — the same policy and rationale
    # as grammar_fast_forward. Guided/logprob requests and kv-page-split
    # meshes keep the classic split path (forced-sync semantics).
    mixed_dispatch: Optional[bool] = None
    # Per-step token budget of a mixed dispatch: decode slots (1 token
    # each) + prefill chunk tokens. None = prefill_chunk + max_batch_slots.
    mixed_token_budget: Optional[int] = None
    # Data-parallel engine fleet (engine/fleet.py): construct this many
    # EngineCore replicas, each pinned to a disjoint device slice of the
    # dp axis, behind a prefix-affinity router with a least-loaded
    # tiebreak. 1 = the classic single engine; >1 makes JaxTpuClient (and
    # every surface behind it — OpenAI server, MCP, agent runtime, eval
    # suite) serve through an AsyncFleet. Slots/pages in this config are
    # PER REPLICA. On CPU tier-1 the replicas land on the virtual mesh's
    # devices; on a pod each host builds replicas over its local slice
    # (parallel/multihost.local_replica_range).
    dp_replicas: int = 1
    # Flight recorder (engine/flight_recorder.py): retain the last N
    # per-step records (dispatch kind, tokens, occupancy, queue depth,
    # KV pressure, wall split) in a preallocated ring — O(1) append off
    # the hot path, surfaced via GET /debug/steps and bench's
    # flight_summary. 0 disables recording entirely.
    flight_recorder_steps: int = 512
    # Host-RAM spill tier (engine/kv_cache.HostSpillTier): retain up to
    # this many evicted prefix-cache pages in host memory so a re-sent
    # prompt re-admits them (one upload) instead of re-prefilling. Spill
    # capture runs on the admission/prefill path only — never inside the
    # decode loop. 0 disables the tier. Budgeted by
    # memory_plan.ServingPlan.host_spill_bytes against host RAM, not HBM.
    kv_spill_pages: int = 0
    # Waiting-queue policy (runbookai_tpu/sched/): "wdrr" interleaves
    # priority classes by weighted-deficit stride — a batch flood cannot
    # starve interactive admits AND interactive load cannot starve batch
    # (FCFS within a class; single-class traffic is plain FIFO either
    # way). "priority" keeps the classic strict priority-then-FCFS sort.
    sched_policy: str = "wdrr"
    # Priority class -> admission-share weight (wdrr only). None = the
    # package default {batch: 1, interactive: 8}.
    sched_weights: Optional[dict] = None

    @classmethod
    def from_plan(cls, engine_block: dict, *, default_kv_dtype: Any = None,
                  **overrides) -> "EngineConfig":
        """Construct from a serving-plan artifact's ``engine`` block
        (:mod:`runbookai_tpu.autotune.plan`) — the autotuner's output is
        a first-class config input, not YAML to be re-typed.

        ``engine_block`` keys map 1:1 onto fields; ``kv_dtype`` travels
        as a plan string ("auto"/"bf16"/"fp8"/"int8" — "auto" resolves to
        ``default_kv_dtype``, the activation dtype, exactly the
        ``llm.kv_cache_dtype`` contract). ``overrides`` win over the plan
        (explicit config beats artifact). Unknown keys raise: a plan from
        a newer schema must fail loudly, never half-apply.
        """
        names = {f.name for f in fields(cls)}
        unknown = sorted(set(engine_block) - names - {"kv_dtype"})
        if unknown:
            raise ValueError(
                f"plan engine block has unknown keys: {', '.join(unknown)}")
        kw = {k: v for k, v in engine_block.items() if k != "kv_dtype"}
        name = engine_block.get("kv_dtype")
        if name is not None:
            kw["kv_dtype"] = resolve_kv_dtype(
                name, default_kv_dtype if default_kv_dtype is not None
                else jnp.bfloat16)
        for key in ("attn_impl", "qmm_impl"):
            # EngineConfig serves literal impls only — "auto" is a
            # deployment-time decision (backend, weight width) the caller
            # must make; passing it through would compare false against
            # "pallas" everywhere and silently serve the XLA path.
            if kw.get(key) == "auto" and key not in overrides:
                raise ValueError(
                    f"plan {key} 'auto' must be resolved by the caller "
                    f"(pass {key}=... for the deployment backend)")
        kw.update(overrides)
        return cls(**kw)


def resolve_kv_dtype(name: Optional[str], default: Any) -> Any:
    """The ONE resolver for every kv-dtype spelling a plan or config can
    carry: ``bench --plan``, :meth:`EngineConfig.from_plan` and
    ``from_config`` must allocate the same pool for the same string.
    "auto"/empty/None follow ``default`` (the activation dtype); "bf16"
    pins a bfloat16 pool even on float32 activations; unknown names
    raise instead of silently serving the activation width."""
    if name in (None, "", "auto"):
        return default
    resolved = {"bf16": jnp.bfloat16, "fp8": jnp.float8_e4m3fn,
                "int8": jnp.int8}.get(name)
    if resolved is None:
        raise ValueError(
            f"kv_dtype {name!r} not one of auto/bf16/fp8/int8")
    return resolved


@partial(jax.jit, static_argnames=("cfg", "page_size", "block_pages", "attn_impl",
                                   "mesh", "qmm_impl"),
         donate_argnums=(4, 5, 14))
def _decode_step(
    params, cfg: LlamaConfig, tokens, positions, kv_k, kv_v, tables, ctx_lens,
    temps, top_ps, top_ks, key, mask, adapter_ids, counts=None, pres=None,
    freq=None, seeds=None, bias=None, *, page_size: int,
    block_pages: int, attn_impl: str = "xla", mesh=None, qmm_impl: str = "xla",
):
    logits, kv_k, kv_v = forward_impl(
        params, cfg, tokens, positions, kv_k, kv_v, tables, ctx_lens,
        page_size=page_size, block_pages=block_pages, attn_impl=attn_impl,
        mesh=mesh, adapter_ids=adapter_ids, qmm_impl=qmm_impl,
    )
    tok = sample_tokens(logits[:, -1], key, temps, top_ps, mask, top_ks,
                        counts=counts, presence=pres, frequency=freq,
                        seeds=seeds, positions=ctx_lens, bias=bias)
    if counts is not None:
        counts = counts.at[jnp.arange(tok.shape[0]), tok].add(1)
    return tok, logits[:, -1], kv_k, kv_v, counts


@partial(jax.jit,
         static_argnames=("cfg", "page_size", "block_pages", "k_steps", "attn_impl",
                          "mesh", "qmm_impl"),
         donate_argnums=(4, 5, 13))
def _decode_multi(
    params, cfg: LlamaConfig, tokens, positions, kv_k, kv_v, tables, ctx_lens,
    temps, top_ps, top_ks, key, adapter_ids, counts=None, pres=None,
    freq=None, seeds=None, bias=None, *, page_size: int, block_pages: int,
    k_steps: int, attn_impl: str = "xla", mesh=None, qmm_impl: str = "xla",
):
    """K autoregressive decode steps in ONE dispatch (on-device sampling).

    Host→device round trips dominate per-step latency on tunneled setups
    (~70ms per sync observed), so the engine amortizes one token fetch over
    ``k_steps`` tokens. Pages for ctx+K must be pre-allocated; per-sequence
    stop conditions are applied host-side after the fetch (tokens past a stop
    are discarded — their KV writes are position-addressed, so accepted tokens
    simply overwrite them later). Penalty ``counts`` and per-request
    ``seeds`` ride the scan carry, so penalized/seeded sampling keeps the
    multi-token amortization.
    """

    def step(carry, _):
        tokens, positions, kv_k, kv_v, ctx_lens, key, counts = carry
        logits, kv_k, kv_v = forward_impl(
            params, cfg, tokens, positions, kv_k, kv_v, tables, ctx_lens,
            page_size=page_size, block_pages=block_pages, attn_impl=attn_impl,
            mesh=mesh, adapter_ids=adapter_ids, qmm_impl=qmm_impl,
        )
        key, sub = jax.random.split(key)
        tok = sample_tokens(logits[:, -1], sub, temps, top_ps, None, top_ks,
                            counts=counts, presence=pres, frequency=freq,
                            seeds=seeds, positions=ctx_lens, bias=bias)
        if counts is not None:
            counts = counts.at[jnp.arange(tok.shape[0]), tok].add(1)
        carry = (tok[:, None], positions + 1, kv_k, kv_v, ctx_lens + 1, key,
                 counts)
        return carry, tok

    (_, _, kv_k, kv_v, _, _, counts), toks = jax.lax.scan(
        step, (tokens, positions, kv_k, kv_v, ctx_lens, key, counts), None,
        length=k_steps,
    )
    return toks.T, kv_k, kv_v, counts  # [B, K]


@partial(jax.jit, static_argnames=("cfg", "page_size", "block_pages", "attn_impl",
                                   "mesh", "qmm_impl"),
         donate_argnums=(4, 5))
def _decode_spec(
    params, cfg: LlamaConfig, tokens, positions, kv_k, kv_v, tables, ctx_lens,
    adapter_ids, page_size: int, block_pages: int, attn_impl: str = "xla",
    mesh=None, qmm_impl: str = "xla",
):
    """Verify a speculated chunk: one T=K forward, greedy argmax per position.

    ``tokens[:, 0]`` is each sequence's real last sampled token; the rest are
    drafts. Causal masking inside :func:`forward_impl` makes position i's
    logits depend only on tokens ≤ i, so the host can accept the longest
    prefix where the model's own argmax agrees with the draft. Rejected
    positions leave garbage K/V exactly like multi-step decode does —
    position-addressed writes are overwritten when the real tokens arrive.

    With ``attn_impl="pallas"`` the T>1 verify forward runs the Pallas chunk
    kernel (``paged_chunk_attention``) — positions are contiguous from
    ``ctx-1``, satisfying the kernel's contiguity contract.
    """
    logits, kv_k, kv_v = forward_impl(
        params, cfg, tokens, positions, kv_k, kv_v, tables, ctx_lens,
        page_size=page_size, block_pages=block_pages, attn_impl=attn_impl,
        mesh=mesh, adapter_ids=adapter_ids, qmm_impl=qmm_impl,
    )
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), kv_k, kv_v  # [B, K]


@partial(jax.jit, static_argnames=("cfg", "page_size", "block_pages", "attn_impl",
                                   "mesh", "qmm_impl"),
         donate_argnums=(3, 4))
def _prefill_step(
    params, cfg: LlamaConfig, tokens, kv_k, kv_v, positions, tables, ctx_lens,
    last_idx, adapter_ids, page_size: int, block_pages: int,
    attn_impl: str = "xla", mesh=None, qmm_impl: str = "xla",
):
    """Prefill one chunk for a BATCH of sequences; returns each row's final
    real-token logits ([B, vocab])."""
    logits, kv_k, kv_v = forward_impl(
        params, cfg, tokens, positions, kv_k, kv_v, tables, ctx_lens,
        page_size=page_size, block_pages=block_pages, attn_impl=attn_impl,
        mesh=mesh, adapter_ids=adapter_ids, qmm_impl=qmm_impl,
    )
    rows = jnp.arange(logits.shape[0])
    return logits[rows, last_idx], kv_k, kv_v


# Row-run alignment of the mixed ragged token buffer: every row's token run
# starts at a multiple of this, so each aligned block belongs to exactly one
# row and the ragged forward collapses to a chunked one with per-block
# gathered tables (ops/attention.ragged_paged_attention's layout contract).
_RAGGED_BLOCK = 8


@partial(jax.jit, static_argnames=("cfg", "page_size", "block_pages",
                                   "attn_impl", "mesh", "qmm_impl",
                                   "ragged_block"),
         donate_argnums=(7, 8, 23))
def _mixed_step(
    params, cfg: LlamaConfig, tokens, feed_toks, dec_idx, positions, row_ids,
    kv_k, kv_v, tables, ctx_lens, adapter_rows, pf_last_idx, temps, top_ps,
    top_ks, key, pf_temps, pf_top_ps, pf_top_ks, pf_slot_map, pf_live,
    dec_live=None, counts=None, pres=None, freq=None, seeds=None, bias=None,
    pf_pres=None, pf_freq=None, pf_seeds=None, pf_bias=None, *,
    page_size: int, block_pages: int, attn_impl: str = "xla", mesh=None,
    qmm_impl: str = "xla", ragged_block: int = _RAGGED_BLOCK,
):
    """ONE unified mixed prefill+decode dispatch (the ragged forward).

    ``tokens`` is the flat ragged buffer with prefill chunks host-filled
    and zeros at the decode positions; each slot's device-resident last
    token (``feed_toks``) is scattered in at ``dec_idx`` so decode inputs
    never visit the host. The forward returns last-token logits for every
    decode slot AND every prefill row; decode rows sample exactly like
    :func:`_decode_step` (feeding the overlap pipeline), and a prefill row
    that completed its prompt samples its FIRST token in this same
    dispatch (``pf_slot_map`` scatters it into the decode feed — TTFT
    loses a whole dispatch). ``pf_slot_map`` rows for non-completing /
    pad prefill rows point out of bounds and drop.

    Penalty counts update in-dispatch for both groups; the rows are
    disjoint (decode slots vs freshly assigned slots) so order is
    irrelevant, matching the split path's semantics. The decode-side add
    is masked by ``dec_live`` (1 = slot holds a live decoder): free
    slots' rows sample garbage logits here, and — unlike the split path,
    where a row is always re-seeded AFTER any such drift and before its
    first read — a prompt completing in THIS dispatch had its row seeded
    pre-dispatch, so an unmasked add would pollute it before the
    first-token gather below reads it.
    """
    b = feed_toks.shape[0]
    tokens = tokens.at[dec_idx].set(feed_toks)
    sel_idx = jnp.concatenate([dec_idx, pf_last_idx])
    logits, kv_k, kv_v = forward_ragged_impl(
        params, cfg, tokens, positions, row_ids, kv_k, kv_v, tables,
        ctx_lens, sel_idx, page_size=page_size, block_pages=block_pages,
        attn_impl=attn_impl, mesh=mesh, adapter_ids=adapter_rows,
        qmm_impl=qmm_impl, ragged_block=ragged_block,
    )
    dec_logits, pf_logits = logits[:b], logits[b:]
    key_dec, key_pf = jax.random.split(key)
    dec_tok = sample_tokens(dec_logits, key_dec, temps, top_ps, None, top_ks,
                            counts=counts, presence=pres, frequency=freq,
                            seeds=seeds, positions=ctx_lens[:b], bias=bias)
    if counts is not None:
        counts = counts.at[jnp.arange(b), dec_tok].add(dec_live)
    pf_counts = (jnp.take(counts, jnp.clip(pf_slot_map, 0, b - 1), axis=0)
                 if counts is not None else None)
    pf_tok = sample_tokens(pf_logits, key_pf, pf_temps, pf_top_ps, None,
                           pf_top_ks, counts=pf_counts, presence=pf_pres,
                           frequency=pf_freq, seeds=pf_seeds,
                           positions=ctx_lens[b:b + pf_temps.shape[0]],
                           bias=pf_bias)
    if counts is not None:
        counts = counts.at[pf_slot_map, pf_tok].add(pf_live, mode="drop")
    feed_new = dec_tok.at[pf_slot_map].set(pf_tok, mode="drop")
    return dec_tok[:, None], pf_tok, feed_new, kv_k, kv_v, counts


@functools.lru_cache(maxsize=8)
def _probe_pallas_attn_cached(backend: str, n_kv: int, n_q: int,
                              head_dim: int, page_size: int,
                              kv_dtype_name: str, act_dtype_name: str,
                              kv_split: bool = False) -> bool:
    """Tiny compiles of the attention kernels that will ACTUALLY run, at
    the engine's real grouping/dtypes, prove (or disprove) Mosaic support
    before real traffic hits them. Representative matters: serving
    dispatches the chunk kernel first (prefill, t>1), then decode with
    the model's true GQA group and activation dtype — and on a page-split
    mesh the PARTIAL kernel with per-shard head counts; a probe narrower
    than that can pass while the first real dispatch crashes. Callers
    pass PER-SHARD n_kv/n_q (the shard_map-local shapes). Cached per
    process — tests build many engines."""
    try:
        from runbookai_tpu.ops.paged_attention_pallas import (
            paged_chunk_attention,
            paged_decode_attention,
        )

        kv_dtype = jnp.dtype(kv_dtype_name)
        act_dtype = jnp.dtype(act_dtype_name)
        interp = backend == "cpu"
        kv = jnp.zeros((2 * page_size, n_kv, head_dim), kv_dtype)
        tables = jnp.zeros((1, 2), jnp.int32)

        q1 = jnp.zeros((1, n_q, head_dim), act_dtype)
        out = paged_decode_attention(q1, kv, kv, tables,
                                     jnp.ones((1,), jnp.int32),
                                     page_size=page_size, interpret=interp)
        # runbook: noqa[RBK002] — probe barrier: the compile/execute must
        # finish (or raise) before serving trusts the decode kernel.
        jax.block_until_ready(out)

        t = 4
        qt = jnp.zeros((1, t, n_q, head_dim), act_dtype)
        positions = jnp.arange(t, dtype=jnp.int32)[None]
        out = paged_chunk_attention(qt, kv, kv, tables,
                                    jnp.full((1,), t, jnp.int32), positions,
                                    page_size=page_size, interpret=interp)
        # runbook: noqa[RBK002] — probe barrier: chunk-kernel lowering must
        # prove out before prefill dispatches it.
        jax.block_until_ready(out)
        if kv_split:
            # The page-split mesh dispatches the PARTIAL kernel (extra
            # outputs, SMEM shard scalar, clamped index maps) — probing
            # only the full-pool kernel would not cover the program that
            # actually runs.
            from runbookai_tpu.ops.paged_attention_pallas import (
                paged_decode_attention_partial,
            )

            out = paged_decode_attention_partial(
                q1, kv, kv, tables, jnp.ones((1,), jnp.int32),
                jnp.int32(0), page_size=page_size, pages_local=1,
                interpret=interp)
            # runbook: noqa[RBK002] — probe barrier: the PARTIAL kernel is
            # the program a page-split mesh actually runs; prove it here.
            jax.block_until_ready(out)
        return True
    except Exception:  # noqa: BLE001 — any Mosaic/lowering failure
        return False


def _probe_pallas_attn(model_cfg, ecfg, act_dtype, mesh=None) -> bool:
    from runbookai_tpu.parallel.mesh import MODEL_AXIS, SEQ_AXIS

    kv_split = mesh is not None and mesh.shape.get(SEQ_AXIS, 1) > 1
    # shard_map runs the kernels at PER-SHARD head counts — probe those.
    kv_sh = mesh.shape.get(MODEL_AXIS, 1) if mesh is not None else 1
    kv_sh = max(1, min(kv_sh, model_cfg.n_kv_heads))
    if model_cfg.n_kv_heads % kv_sh or model_cfg.n_heads % kv_sh:
        kv_sh = 1  # unshardable heads replicate; kernel sees full shapes
    return _probe_pallas_attn_cached(jax.default_backend(),
                                     model_cfg.n_kv_heads // kv_sh,
                                     model_cfg.n_heads // kv_sh,
                                     model_cfg.head_dim, ecfg.page_size,
                                     jnp.dtype(ecfg.kv_dtype).name,
                                     jnp.dtype(act_dtype).name,
                                     kv_split=kv_split)


@functools.lru_cache(maxsize=8)
def _probe_pallas_attn_int8_cached(backend: str, n_kv: int, n_q: int,
                                   head_dim: int, page_size: int,
                                   act_dtype_name: str) -> bool:
    """One compile of the int8-scaled decode kernel (tuple pool: int8
    values + f32 per-token scales) proves the Mosaic lowering — the
    extra rank-3 scale blocks and the widen-multiply — before serving
    relies on it. Decode only: chunked prefill routes to XLA for int8."""
    try:
        from runbookai_tpu.ops.paged_attention_pallas import (
            paged_decode_attention,
        )

        kv_vals = jnp.zeros((2 * page_size, n_kv, head_dim), jnp.int8)
        kv_scales = jnp.zeros((2 * page_size, n_kv), jnp.float32)
        tables = jnp.zeros((1, 2), jnp.int32)
        q1 = jnp.zeros((1, n_q, head_dim), jnp.dtype(act_dtype_name))
        out = paged_decode_attention(
            q1, (kv_vals, kv_scales), (kv_vals, kv_scales), tables,
            jnp.ones((1,), jnp.int32), page_size=page_size,
            interpret=backend == "cpu")
        # runbook: noqa[RBK002] — probe barrier: int8 widen-multiply must
        # lower (or raise) before serving reads int8 pages through it.
        jax.block_until_ready(out)
        return True
    except Exception:  # noqa: BLE001 — any Mosaic/lowering failure
        return False


def _probe_pallas_attn_int8(model_cfg, ecfg, act_dtype) -> bool:
    return _probe_pallas_attn_int8_cached(
        jax.default_backend(), model_cfg.n_kv_heads, model_cfg.n_heads,
        model_cfg.head_dim, ecfg.page_size, jnp.dtype(act_dtype).name)


@functools.lru_cache(maxsize=8)
def _probe_qmm_pallas_cached(backend: str, m: int, k: int, n: int,
                             act_dtype_name: str, mesh=None) -> bool:
    """One compile of the int8 qmm kernel at the model's real (K, N)
    proves the Mosaic int8 widen+dot lowering before serving relies on
    it. One shape is representative: the lowering concern is the int8
    load/convert pattern, not a particular multiple-of-128 tile count.

    With a multi-device ``mesh`` the operands are committed replicated on
    it first, so the probe exercises the same GSPMD partitioning of the
    Mosaic custom call that the engine's compiled steps will — a DP-only
    mesh keeps qmm_impl="pallas" (llama.forward_paged only downgrades for
    MODEL>1 / kv-split), and a partitioning failure must surface here,
    not at the first real dispatch."""
    try:
        from runbookai_tpu.ops.qmm_pallas import qmm_pallas

        x = jnp.zeros((m, k), jnp.dtype(act_dtype_name))
        q = jnp.zeros((k, n), jnp.int8)
        s = jnp.zeros((1, n), jnp.float32)
        if mesh is not None and mesh.size > 1:
            from runbookai_tpu.parallel.mesh import replicated

            rep = replicated(mesh)
            x, q, s = (jax.device_put(a, rep) for a in (x, q, s))
        # runbook: noqa[RBK002] — probe barrier: one qmm compile at the real
        # (K, N) proves the Mosaic int8 dot before the first live dispatch.
        jax.block_until_ready(
            qmm_pallas(x, q, s, interpret=backend == "cpu"))
        return True
    except Exception:  # noqa: BLE001
        return False


def _probe_qmm_pallas(model_cfg, ecfg, act_dtype, mesh=None) -> bool:
    from runbookai_tpu.ops.qmm_pallas import qmm_pallas_eligible

    m = ecfg.max_batch_slots
    k, n = model_cfg.dim, model_cfg.ffn_dim
    if not qmm_pallas_eligible(m, k, n):
        # The kernel would never engage on this model's main matmuls —
        # qmm falls back per-shape, so there is nothing to probe.
        return True
    if mesh is not None and mesh.size <= 1:
        mesh = None  # single-device mesh == no mesh for partitioning
    return _probe_qmm_pallas_cached(jax.default_backend(), m, k, n,
                                    jnp.dtype(act_dtype).name, mesh=mesh)


@functools.lru_cache(maxsize=8)
def _probe_pallas_ragged_cached(backend: str, n_kv: int, n_q: int,
                                head_dim: int, page_size: int,
                                kv_dtype_name: str,
                                act_dtype_name: str) -> bool:
    """One compile of the ragged mixed-dispatch kernel path
    (``paged_ragged_attention`` — the chunk kernel at the blocked ragged
    layout with per-block gathered tables) at a representative 2-row mix
    (one decode-shaped row, one chunk-shaped row) proves the lowering
    before the engine routes live mixed traffic through it."""
    try:
        from runbookai_tpu.ops.paged_attention_pallas import (
            paged_ragged_attention,
        )

        rq = _RAGGED_BLOCK
        kv = jnp.zeros((2 * page_size, n_kv, head_dim),
                       jnp.dtype(kv_dtype_name))
        tables = jnp.zeros((2, 2), jnp.int32)
        q = jnp.zeros((2 * rq, n_q, head_dim), jnp.dtype(act_dtype_name))
        row_ids = jnp.repeat(jnp.arange(2, dtype=jnp.int32), rq)
        q_pos = jnp.concatenate(
            [jnp.zeros((rq,), jnp.int32), jnp.arange(rq, dtype=jnp.int32)])
        out = paged_ragged_attention(
            q, kv, kv, tables, jnp.asarray([1, rq], jnp.int32), q_pos,
            row_ids, page_size=page_size, ragged_block=rq,
            interpret=backend == "cpu")
        # runbook: noqa[RBK002] — probe barrier: the ragged mixed-dispatch
        # kernel must lower (or raise) before mixed traffic relies on it.
        jax.block_until_ready(out)
        return True
    except Exception:  # noqa: BLE001 — any Mosaic/lowering failure
        return False


def _probe_pallas_ragged(model_cfg, ecfg, act_dtype) -> bool:
    return _probe_pallas_ragged_cached(
        jax.default_backend(), model_cfg.n_kv_heads, model_cfg.n_heads,
        model_cfg.head_dim, ecfg.page_size, jnp.dtype(ecfg.kv_dtype).name,
        jnp.dtype(act_dtype).name)


@partial(jax.jit, donate_argnums=(0,))
def _seed_count_row(counts, row, ids, n):
    """Reset one slot's penalty-count row to the histogram of ``ids[:n]``
    (ids padded to a power of two host-side to bound compile count).
    Used on RE-admission after preemption, where the generated-so-far
    history must be restored; fresh assignments batch-zero instead."""
    live = (jnp.arange(ids.shape[0]) < n).astype(jnp.int32)
    hist = jnp.zeros((counts.shape[1],), jnp.int32).at[ids].add(live)
    return counts.at[row].set(hist)


@partial(jax.jit, donate_argnums=(0,))
def _reset_count_rows(counts, row_mask):
    """Zero every row where ``row_mask`` — ONE dispatch for a whole
    prefill batch of fresh penalized assignments."""
    return jnp.where(row_mask[:, None], 0, counts)


@partial(jax.jit, donate_argnums=(0,))
def _bump_counts_batch(counts, rows, toks, live):
    """counts[rows[i], toks[i]] += live[i] — ONE dispatch for the whole
    first-token batch (live masks out unpenalized/pad rows)."""
    return counts.at[rows, toks].add(live.astype(jnp.int32))


# The legacy step-counter dict keys re-exported as Prometheus counters via
# scrape-time callbacks: (metrics-dict key, metric name, help). Module-level
# so the fleet can re-bind the same names to cross-replica sums — one table,
# no drift between single-engine and fleet exports.
LEGACY_COUNTER_EXPORTS: tuple[tuple[str, str, str], ...] = (
    ("decode_tokens", "runbook_decode_tokens_total",
     "Tokens sampled by decode dispatches"),
    ("decode_steps", "runbook_decode_steps_total",
     "Decode dispatches"),
    ("prefill_tokens", "runbook_prefill_tokens_total",
     "Prompt tokens prefilled"),
    ("preemptions", "runbook_preemptions_total",
     "Requests preempted by recompute under pool pressure"),
    ("cached_prefix_tokens", "runbook_cached_prefix_tokens_total",
     "Prompt tokens served from the prefix cache"),
    ("spec_drafted", "runbook_spec_drafted_total",
     "Speculative tokens drafted"),
    ("spec_accepted", "runbook_spec_accepted_total",
     "Speculative tokens accepted"),
    ("grammar_forced_tokens", "runbook_grammar_forced_tokens_total",
     "Tokens emitted by grammar fast-forward without a dispatch"),
    ("decode_time_s", "runbook_decode_time_seconds_total",
     "Wall-clock spent in decode dispatches"),
    ("prefill_time_s", "runbook_prefill_time_seconds_total",
     "Wall-clock spent in prefill dispatches"),
    ("decode_dispatch_time_s", "runbook_decode_dispatch_seconds_total",
     "Decode wall-clock blocked on device work (dispatch issue + "
     "token egress wait)"),
    ("decode_host_time_s", "runbook_decode_host_overhead_seconds",
     "Decode wall-clock spent on host work (input prep, "
     "detokenization, stop scans, stream emission)"),
    ("decode_host_overlap_s",
     "runbook_decode_host_overlapped_seconds_total",
     "Host decode work that ran while a dispatch was in flight"),
    ("prefill_steps", "runbook_prefill_dispatch_total",
     "Pure prefill dispatches"),
    ("decode_dispatches", "runbook_decode_dispatch_total",
     "Pure decode dispatches (single, multi-step, and spec-verify)"),
    ("mixed_steps", "runbook_mixed_dispatch_total",
     "Unified mixed prefill+decode dispatches (one ragged forward "
     "serving both phases)"),
    ("mixed_tokens", "runbook_mixed_tokens_total",
     "Real tokens processed by mixed dispatches"),
    ("mixed_time_s", "runbook_mixed_time_seconds_total",
     "Wall-clock spent building and issuing mixed dispatches"),
)


_TOPK_LOGPROBS = 20  # OpenAI's top_logprobs ceiling; one compiled shape


@partial(jax.jit, static_argnames=())
def _token_logprobs(logits, toks):
    """Per-row logprob of the sampled token + top-K alternatives, computed
    on device so only [B, K+1] floats cross the host link (fetching the
    full [B, vocab] row per token would dwarf the decode step itself)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    rows = jnp.arange(logp.shape[0])
    chosen = logp[rows, toks]
    top_lp, top_ids = jax.lax.top_k(logp, _TOPK_LOGPROBS)
    return chosen, top_ids, top_lp


@dataclass
class _PendingDecode:
    """One in-flight decode window awaiting host consumption.

    ``toks_dev`` is the [B, K] device token buffer of the issued dispatch
    (its last column is already wired into the next dispatch's feed); the
    host copy is started asynchronously at issue time and consumed by
    :meth:`EngineCore._drain` one scheduler round later. ``reqs`` snapshots
    (request, slot) at dispatch time so a slot reassigned before the drain
    can never misroute tokens."""

    toks_dev: jax.Array  # [B, K]
    reqs: list[tuple[EngineRequest, int]]
    req_ids: frozenset[str]
    k: int


@dataclass
class _SlotInputs:
    """Epoch-cached device inputs for a decode dispatch.

    Everything here is a pure function of the slot→request mapping (the
    scheduler epoch, bumped on admit/finish/preempt) and of the sequences'
    page lists (the KV manager's table version, bumped on growth), so a
    steady-state decode step reuses the uploaded arrays and does zero
    O(B·pages) page-table or O(B·vocab) bias rebuild work."""

    key: tuple[int, int]  # (scheduler epoch, kv table version)
    tables: jax.Array  # [B, max_pages + 1] int32, device
    adapters: jax.Array  # [B] int32, device
    temps: jax.Array
    top_ps: jax.Array
    top_ks: jax.Array
    pres: jax.Array
    freq: jax.Array
    seeds: jax.Array
    bias: Optional[jax.Array]
    use_pen: bool
    use_seed: bool
    use_bias: bool


class EngineCore:
    """Synchronous stepping core. Drive with :meth:`step` until idle."""

    def __init__(
        self,
        model_cfg: LlamaConfig,
        params: Any,
        tokenizer: Any,
        engine_cfg: Optional[EngineConfig] = None,
        mask_fn: Optional[Callable[[EngineRequest], Optional[np.ndarray]]] = None,
        advance_fn: Optional[Callable[[EngineRequest, int], bool]] = None,
        seed: int = 0,
        tracer=None,
        mesh=None,
        lora_registry=None,
        draft_worker=None,
        replica_idx: Optional[int] = None,
    ):
        self.cfg = model_cfg
        self.ecfg = engine_cfg or EngineConfig()
        # Fleet membership (engine/fleet.py): replica ``i`` namespaces every
        # admitted request id with ``r{i}-`` so two replicas admitting the
        # same caller id can never collide in the shared Tracer/registry,
        # and stamps its index on trace records. None = standalone engine.
        self.replica_idx = replica_idx
        self._rid_prefix = f"r{replica_idx}-" if replica_idx is not None else ""
        self.params = params
        # Multi-LoRA: the stacked adapter pytree rides inside params so the
        # compiled steps see one tree; per-dispatch adapter_ids rows select
        # each sequence's adapter (models/lora.py).
        self.lora = lora_registry
        if lora_registry is not None:
            self.params = dict(params)
            self.params["lora"] = lora_registry.stacked()
        self.tokenizer = tokenizer
        # Draft-model speculation (engine/draft.py): the worker drafts k-1
        # tokens per spec round; prompt-lookup remains the fallback for
        # requests it cannot cover.
        self.draft = draft_worker
        self.tracer = tracer if tracer is not None else get_tracer()
        # Guided decoding hooks: mask_fn returns the allowed-token mask for a
        # request (or None), advance_fn feeds a sampled token to the grammar
        # automaton and returns True when the grammar has completed.
        self.mask_fn = mask_fn
        self.advance_fn = advance_fn
        # fp8 KV halves pool bytes (double the pooled tokens per chip) at
        # ~1e-2 relative K/V error. The Pallas kernels read fp8 pages
        # directly (widened in-VMEM on load); on accelerator backends a
        # tiny probe compile proves Mosaic accepts the fp8 convert before
        # the first real dispatch — an actual failure downgrades to the
        # XLA gather path with a warning instead of crashing serving. The
        # caller's config is copied, not mutated.
        act_dtype = self.params["embed"].dtype
        from runbookai_tpu.parallel.mesh import SEQ_AXIS as _SEQ

        _kv_split_mesh = mesh is not None and mesh.shape.get(_SEQ, 1) > 1
        # int8 KV (values + per-token absmax scales, ops/attention.py):
        # the DECODE kernel reads int8 pages + scales directly (probe-
        # gated like fp8); chunked prefill runs the XLA gather path, the
        # per-head-shard shard_map path has no scale plumbing (mesh
        # model>1 serves via XLA), and the page-split layout refuses.
        _kv_int8 = jnp.dtype(self.ecfg.kv_dtype) == jnp.int8
        if _kv_int8:
            from runbookai_tpu.parallel.mesh import MODEL_AXIS as _MODEL

            if _kv_split_mesh:
                raise ValueError(
                    "kv_dtype=int8 is not supported on a KV page-split "
                    "mesh (seq axis > 1); use fp8 KV for split serving")
            _model_tp = mesh.shape.get(_MODEL, 1) if mesh is not None else 1
            if (self.ecfg.attn_impl == "pallas"
                    and (_model_tp > 1
                         or not _probe_pallas_attn_int8(model_cfg,
                                                        self.ecfg,
                                                        act_dtype))):
                import dataclasses as _dc
                import logging

                logging.getLogger(__name__).warning(
                    "kv_dtype=int8: serving attention via the XLA path "
                    "(%s)", "TP mesh" if _model_tp > 1
                    else "Mosaic rejected the int8 decode kernel probe")
                self.ecfg = _dc.replace(self.ecfg, attn_impl="xla")
        # Probe whenever the dispatched kernels include constructs newer
        # than the proven baseline: sub-byte KV loads (fp8) and/or the
        # page-split PARTIAL kernel (clamped index maps, SMEM shard
        # scalar, multi-output finalize).
        if (self.ecfg.attn_impl == "pallas" and not _kv_int8
                and (jnp.dtype(self.ecfg.kv_dtype).itemsize == 1
                     or _kv_split_mesh)
                and not _probe_pallas_attn(model_cfg, self.ecfg, act_dtype,
                                           mesh=mesh)):
            import dataclasses as _dc
            import logging

            logging.getLogger(__name__).warning(
                "Mosaic rejected the Pallas attention probe for this "
                "config (kv_dtype=%s, kv_split=%s); serving via the XLA "
                "path", jnp.dtype(self.ecfg.kv_dtype).name, _kv_split_mesh)
            self.ecfg = _dc.replace(self.ecfg, attn_impl="xla")
        # Same guard for the int8 qmm kernel: a Mosaic rejection downgrades
        # to the mathematically identical XLA expression instead of
        # crashing the first dispatch.
        if self.ecfg.qmm_impl == "pallas":
            from runbookai_tpu.models.quant import is_quantized

            has_q = any(is_quantized(v)
                        for v in self.params["layers"].values())
            if has_q and not _probe_qmm_pallas(model_cfg, self.ecfg,
                                               act_dtype, mesh=mesh):
                import dataclasses as _dc
                import logging

                logging.getLogger(__name__).warning(
                    "int8 weights: Mosaic rejected the Pallas qmm probe "
                    "on this backend; using the XLA matmul expression")
                self.ecfg = _dc.replace(self.ecfg, qmm_impl="xla")

        # Unified mixed prefill+decode dispatch: resolve the auto policy
        # (on where dispatch latency dominates, off on CPU where compute
        # scales with the padded ragged buffer) and probe the ragged
        # kernel path like the other Pallas programs. The kv page-split
        # mesh keeps the classic split path — the ragged layout has no
        # page-shard plumbing. int8 KV needs no ragged probe: mixed steps
        # are T>1 chunks, which int8 pools serve via the XLA gather path.
        mixed = self.ecfg.mixed_dispatch
        if mixed is None:
            mixed = jax.default_backend() in ("tpu", "axon")
        if mixed and _kv_split_mesh:
            mixed = False
        if (mixed and self.ecfg.attn_impl == "pallas" and not _kv_int8
                and not _probe_pallas_ragged(model_cfg, self.ecfg,
                                             act_dtype)):
            import logging

            logging.getLogger(__name__).warning(
                "Mosaic rejected the ragged mixed-dispatch probe; serving "
                "with split prefill/decode dispatches")
            mixed = False
        self._mixed = bool(mixed)
        # Mixed-batch geometry (fixed shapes → one compiled mixed program
        # in steady state): decode section = one aligned block per slot,
        # prefill section = the chunk token budget rounded up to blocks,
        # plus one reserved null row for padding blocks.
        budget = (self.ecfg.mixed_token_budget
                  or (self.ecfg.prefill_chunk + self.ecfg.max_batch_slots))
        pf_budget = max(_RAGGED_BLOCK,
                        budget - self.ecfg.max_batch_slots)
        self._mix_pf_tokens = -(-pf_budget // _RAGGED_BLOCK) * _RAGGED_BLOCK
        self._mix_pf_rows = max(1, self.ecfg.prefill_batch)
        self._mix_rows = (self.ecfg.max_batch_slots + self._mix_pf_rows
                          + 1)

        # Sharded serving: with a mesh, the KV pool shards its kv-head axis
        # over the TP (``model``) axis alongside the Megatron param shardings
        # (``params`` must already be device_put by the caller — see
        # JaxTpuClient.from_config). Page tables / tokens stay host-built and
        # replicated; XLA inserts the collectives inside the compiled steps.
        self.mesh = mesh
        kv_sharding = None
        if mesh is not None:
            from runbookai_tpu.parallel.sharding import kv_pool_sharding

            kv_sharding = kv_pool_sharding(model_cfg, mesh)

        self.kv = KVCacheManager(
            n_layers=model_cfg.n_layers,
            num_pages=self.ecfg.num_pages,
            page_size=self.ecfg.page_size,
            n_kv_heads=model_cfg.n_kv_heads,
            head_dim=model_cfg.head_dim,
            max_seq_len=self.ecfg.max_seq_len,
            dtype=self.ecfg.kv_dtype,
            sharding=kv_sharding,
            spill_pages=self.ecfg.kv_spill_pages,
        )
        self._kv_k = self.kv.pool.kv_k
        self._kv_v = self.kv.pool.kv_v
        self.seed = seed  # recorded so an online rebuild replays it
        self._key = jax.random.PRNGKey(seed)

        # OpenAI repetition penalties: device-resident per-slot token
        # counts, seeded at slot assignment from the (folded) prompt and
        # updated inside the decode dispatches — zero per-step host
        # traffic. Rows for unpenalized requests drift and are never
        # read; each assignment re-seeds its row.
        self._tok_counts = jnp.zeros(
            (self.ecfg.max_batch_slots, model_cfg.vocab_size), jnp.int32)

        self.waiting: list[EngineRequest] = []
        self.prefilling: list[EngineRequest] = []
        self.decoding: list[EngineRequest] = []
        self.finished: list[EngineRequest] = []
        # Admission-order policy (sched/wdrr.py): stride interleave of
        # priority classes, or None for the classic strict-priority sort.
        self._sched = None
        if self.ecfg.sched_policy == "wdrr":
            from runbookai_tpu.sched.wdrr import WeightedDeficitScheduler

            self._sched = WeightedDeficitScheduler(self.ecfg.sched_weights)
        elif self.ecfg.sched_policy != "priority":
            raise ValueError(
                f"sched_policy {self.ecfg.sched_policy!r} not one of "
                f"wdrr/priority")
        # SLO feedback controller (sched/feedback.py), attached by the
        # client when llm.sched.feedback is on; None = no behavior change.
        self.feedback = None
        self._slots: list[Optional[EngineRequest]] = [None] * self.ecfg.max_batch_slots
        self._last_token: dict[str, int] = {}
        # Overlapped decode pipeline state: the device-resident feed of each
        # slot's last sampled token (input side — no host round-trip), the
        # in-flight window awaiting async egress, the scheduler epoch that
        # keys the cached dispatch inputs, and the speculation re-probe
        # backoff (each probe costs a drain).
        self._feed_toks = jnp.zeros((self.ecfg.max_batch_slots,), jnp.int32)
        self._pending: Optional[_PendingDecode] = None
        self._sched_epoch = 0
        self._slot_cache: Optional[_SlotInputs] = None
        self._spec_backoff = 0
        self._spec_miss_streak = 0
        # Wall-clock already booked by nested drains (lets _run_decode add
        # only its own un-booked time to decode_time_s — no double count).
        self._drain_time_acc = 0.0
        # Serving metrics (BASELINE.md contract: TTFT + tokens/sec/chip).
        # This dict stays the single source of truth for the step counters
        # (/healthz contract, bench resets, tests); the registry re-exports
        # it via scrape-time callbacks in _install_metrics.
        # decode_time_s remains the total decode wall; the dispatch/host/
        # overlap components split it so the pipeline's win is attributable
        # (host emission used to be silently booked as decode time).
        # mixed_* split: a mixed step books its wall under mixed_time_s
        # (NOT prefill_time_s/decode_time_s — those keep their pure-step
        # semantics for the /healthz and PromQL contracts); the drained
        # decode window's egress/emission stays booked as decode_* like
        # any other window. prefill_steps / decode_dispatches /
        # mixed_steps count DISPATCHES, making the 2-dispatches→1 win of
        # mixed steps directly observable.
        # kv_pages_imported/exported count location-addressed page moves
        # (cross-replica pulls, prefill→decode handoffs, spill readmits);
        # kv_spill_readmits is the subset that came back from the host
        # spill tier.
        self.metrics = {"decode_tokens": 0, "decode_steps": 0, "prefill_tokens": 0,
                        "preemptions": 0, "decode_time_s": 0.0, "prefill_time_s": 0.0,
                        "cached_prefix_tokens": 0, "spec_drafted": 0, "spec_accepted": 0,
                        "decode_dispatch_time_s": 0.0, "decode_host_time_s": 0.0,
                        "decode_host_overlap_s": 0.0, "prefill_steps": 0,
                        "decode_dispatches": 0, "mixed_steps": 0,
                        "mixed_tokens": 0, "mixed_time_s": 0.0,
                        "kv_pages_imported": 0, "kv_pages_exported": 0,
                        "kv_spill_readmits": 0}
        # Flight-recorder mark for page transfers: imports/exports happen
        # BETWEEN steps (under the engine lock, not inside step()), so the
        # per-step record reports the delta since the last recorded step
        # rather than an intra-step delta that would always read 0.
        self._flight_kv_mark = (0, 0)
        # Workload-fingerprint tap (runbookai_tpu/obs): called once per
        # finishing request from _observe_finish with the EngineRequest.
        # None = no observer; the callee appends to a bounded deque — one
        # O(1) call off the dispatch path, never inside a dispatch.
        self.workload_tap = None
        # Fault-injection seam (runbookai_tpu/chaos): called at the TOP
        # of step(), under the AsyncEngine lock, before any pool
        # mutation. A hook may raise (replica crash — the loop's
        # _fail_live_requests path runs) or stall (replica wedge); hooks
        # are one-shot and clear themselves. None (the default) costs
        # one attribute check per step.
        self.chaos_hook = None
        self.registry = metrics_mod.get_registry()
        # Flight recorder: one bounded record per step (what was the
        # engine DOING on the slow steps?). The step thread is the only
        # writer; /debug/steps snapshots under the AsyncEngine lock.
        self.flight = FlightRecorder(self.ecfg.flight_recorder_steps)
        self._install_metrics()

    def _install_metrics(self) -> None:
        """Register the engine's Prometheus-facing metrics.

        Per-request latency histograms are observed directly at the
        scheduling points (admission, first token, finish); live-state
        gauges and the legacy step counters are scrape-time callbacks, so
        there is exactly one source of truth and zero per-step overhead.
        Registration is get-or-create and ``set_function`` replaces the
        previous callback, so rebuilding an engine in-process (tests,
        bench children) re-binds the gauges to the newest core. A
        standalone engine also clears any per-replica labeled callbacks a
        previous FLEET left behind (fleet.py's ``_install_metrics``
        re-binds them when a fleet is current): without this, falling
        back from dp>1 to a single engine would keep scraping the dead
        replicas' cores — and pinning their params — forever.
        """
        reg, m = self.registry, metrics_mod
        if self.replica_idx is None:
            for name in ("runbook_replica_running_requests",
                         "runbook_replica_waiting_requests",
                         "runbook_replica_kv_pool_utilization",
                         "runbook_replica_decode_tokens_total",
                         "runbook_router_imbalance_ratio",
                         # Multi-model rollups (fleet/multimodel.py):
                         # falling back to one engine must release the
                         # dead groups' cores exactly like the replica
                         # gauges above.
                         "runbook_model_running_requests",
                         "runbook_model_waiting_requests",
                         "runbook_model_kv_pool_utilization",
                         "runbook_model_decode_tokens_total"):
                stale = reg.get(name)
                if stale is not None:
                    stale.clear_functions()
        self.hist_ttft = reg.histogram(
            "runbook_ttft_seconds", "Time to first token per request",
            buckets=m.TTFT_BUCKETS)
        self.hist_tpot = reg.histogram(
            "runbook_tpot_seconds",
            "Per-token decode latency (e2e minus TTFT over generated-1)",
            buckets=m.TPOT_BUCKETS)
        self.hist_e2e = reg.histogram(
            "runbook_e2e_seconds", "Request end-to-end latency",
            buckets=m.E2E_BUCKETS)
        self.hist_queue_wait = reg.histogram(
            "runbook_queue_wait_seconds",
            "Submission-to-admission wait (first admission only)",
            buckets=m.QUEUE_WAIT_BUCKETS)
        # Per-class scheduling surface (sched/): queue-wait and admit
        # counts by priority class — the starvation signal the WDRR
        # policy is judged on (docs/observability.md PromQL).
        self.hist_class_queue_wait = reg.histogram(
            "runbook_sched_queue_wait_seconds",
            "Submission-to-admission wait per priority class (first "
            "admission only)", labels=("cls",),
            buckets=m.QUEUE_WAIT_BUCKETS)
        self._m_class_admits = reg.counter(
            "runbook_sched_admits_total",
            "Requests admitted to prefill, per priority class",
            labels=("cls",))
        self.hist_mixed_tokens = reg.histogram(
            "runbook_mixed_tokens_per_dispatch",
            "Real (unpadded) tokens per unified mixed prefill+decode "
            "dispatch", buckets=m.MIXED_TOKENS_BUCKETS)
        # Live scheduler/pool state: plain attribute reads, safe from the
        # scrape thread without the step lock (at worst one step stale).
        reg.gauge("runbook_running_requests",
                  "Requests holding a decode slot"
                  ).set_function(lambda: len(self.decoding))
        reg.gauge("runbook_waiting_requests",
                  "Requests queued or prefilling"
                  ).set_function(lambda: len(self.waiting)
                                 + len(self.prefilling))
        g_cls_wait = reg.gauge(
            "runbook_sched_waiting_requests",
            "Requests queued or prefilling, per priority class",
            labels=("cls",))
        g_cls_wait.clear_functions()
        for label in ("interactive", "batch", "other"):
            g_cls_wait.labels(cls=label).set_function(
                lambda lb=label: float(sum(
                    1 for r in list(self.waiting) + list(self.prefilling)
                    if class_label(r.priority) == lb)))
        reg.gauge("runbook_kv_pages_total", "KV pool size in pages"
                  ).set_function(lambda: self.kv.allocator.num_pages)
        reg.gauge("runbook_kv_pages_in_use",
                  "KV pages referenced by live sequences"
                  ).set_function(lambda: self.kv.pages_in_use)
        reg.gauge("runbook_kv_pages_cached",
                  "Retired-but-resident prefix-cache pages"
                  ).set_function(lambda: self.kv.allocator.cached_pages)
        # Host spill tier (0s when disabled): captures vs LRU drops — the
        # difference is how much evicted prefix KV stays readmittable.
        reg.counter("runbook_kv_spill_pages_total",
                    "KV pages captured into the host spill tier at "
                    "eviction time").set_function(
            lambda: float(self.kv.spill.pages_spilled
                          if self.kv.spill else 0))
        reg.counter("runbook_kv_spill_evictions_total",
                    "Spill-tier pages dropped by its LRU bound"
                    ).set_function(
            lambda: float(self.kv.spill.evictions if self.kv.spill else 0))
        reg.gauge("runbook_kv_pool_utilization",
                  "Fraction of allocatable KV pages held by live sequences"
                  ).set_function(self.kv.utilization)
        reg.gauge("runbook_prefix_cache_hit_ratio",
                  "Cached prompt tokens / (cached + prefilled) since start"
                  ).set_function(self._prefix_hit_ratio)
        for key, name, help_text in LEGACY_COUNTER_EXPORTS:
            reg.counter(name, help_text).set_function(
                lambda k=key: float(self.metrics.get(k, 0)))
        reg.gauge("runbook_decode_overlap_ratio",
                  "Fraction of host decode work hidden behind device "
                  "execution by the lagged pipeline (0 in forced-sync mode)"
                  ).set_function(self._overlap_ratio)

    def _overlap_ratio(self) -> float:
        host = self.metrics.get("decode_host_time_s", 0.0)
        return (self.metrics.get("decode_host_overlap_s", 0.0) / host
                if host > 0 else 0.0)

    def _prefix_hit_ratio(self) -> float:
        cached = self.metrics.get("cached_prefix_tokens", 0)
        total = cached + self.metrics.get("prefill_tokens", 0)
        return cached / total if total else 0.0

    # ------------------------------------------------------------------ API

    def refresh_lora(self) -> None:
        """Pick up adapters registered after engine construction."""
        if self.lora is not None:
            self.params = dict(self.params)
            self.params["lora"] = self.lora.stacked()

    def submit(self, req: EngineRequest) -> None:
        if self._rid_prefix and not req.request_id.startswith(self._rid_prefix):
            # Replica namespace: the engine-internal id gains the r{idx}-
            # prefix (tracer JSONL, KV seq ids, abort lookups); the
            # caller's x-request-id travels separately as trace_id and is
            # echoed unchanged.
            req.request_id = self._rid_prefix + req.request_id
        if not req.prompt_ids:
            req.prompt_ids = [self.tokenizer.bos_id]
        if req.adapter is not None:
            if self.lora is None:
                raise ValueError(
                    f"request names adapter {req.adapter!r} but the engine "
                    f"has no LoRA registry")
            req.adapter_idx = self.lora.index_of(req.adapter)
            # Hot-loaded adapter: the registry knows the name before the
            # params tree has its row. An out-of-range gather would CLAMP
            # inside jit and silently serve the wrong adapter — refresh
            # here instead (submit runs under the same lock as step()).
            rows = next(iter(self.params["lora"].values()))["A"].shape[1]
            if req.adapter_idx >= rows:
                self.refresh_lora()
        if req.guided_state is None and req.sampling.guided and self.mask_fn:
            pass  # guided_state initialized lazily by the mask provider
        req.state = RequestState.WAITING
        self.waiting.append(req)
        if self.tracer.enabled:
            # Timeline anchor: the enqueue event opens the request's span
            # tree (`runbook timeline`); engine.admit and engine.request
            # close the queue-wait and lifetime edges against it.
            meta = {"request": req.request_id,
                    "prompt_tokens": len(req.prompt_ids)}
            if self.replica_idx is not None:
                meta["replica"] = self.replica_idx
            if req.trace_id is not None:
                meta["trace_id"] = req.trace_id
            self.tracer.event("engine.enqueue", **meta)

    @property
    def has_work(self) -> bool:
        # An in-flight lagged window counts as work: its tokens still need
        # host consumption even if every owning request already finished.
        return bool(self.waiting or self.prefilling or self.decoding
                    or self._pending is not None)

    def flush(self) -> None:
        """Drain the in-flight lagged decode window (if any), emitting its
        tokens and settling metrics. Shutdown/idle hook — a no-op when the
        pipeline is already drained."""
        self._drain_pending()

    def discard_inflight(self) -> None:
        """Crash recovery only: drop the in-flight window WITHOUT fetching
        (the device may be poisoned — a drain would raise again and wedge
        ``has_work`` forever). Callers must have failed/aborted the owning
        requests first; the window's tokens are lost with it."""
        self._pending = None

    # ------------------------------------------------- page import / export

    def export_kv_pages(self, prompt_ids: list[int],
                        hashes: Optional[list[int]] = None,
                        hash_seed: int = 0, skip_blocks: int = 0,
                        max_pages: Optional[int] = None):
        """Stage this replica's resident pages for ``prompt_ids``'s prefix
        (cross-replica pull / prefill→decode handoff). MUST run under the
        AsyncEngine step lock — it reads the live pool arrays. Returns an
        :class:`~runbookai_tpu.engine.kv_cache.ExportedPages` or None
        (nothing to export — the planned pages were evicted/re-registered
        since the probe; the chain re-walk under the lock is the
        staleness guard)."""
        out = self.kv.export_pages(
            self._kv_k, self._kv_v, prompt_ids, hashes=hashes,
            hash_seed=hash_seed, skip_blocks=skip_blocks,
            max_pages=max_pages)
        if out is not None:
            out.src_replica = self.replica_idx
            self.metrics["kv_pages_exported"] += out.num_pages
        return out

    def import_kv_pages(self, exported) -> int:
        """Install exported pages into this replica's pool (digest-checked,
        retired→matchable). MUST run under the AsyncEngine step lock; the
        pool arrays are functionally updated so the next dispatch serves
        the imported bytes. Returns pages imported."""
        self._kv_k, self._kv_v, n = self.kv.import_pages(
            self._kv_k, self._kv_v, exported)
        if n:
            self.metrics["kv_pages_imported"] += n
        return n

    def _trash_pos(self) -> int:
        return self.kv.max_pages_per_seq * self.ecfg.page_size

    def _adapter_ids_for_slots(self) -> np.ndarray:
        """Per-slot LoRA adapter rows (0 = base) for a decode dispatch."""
        ids = np.zeros((self.ecfg.max_batch_slots,), dtype=np.int32)
        for req in self.decoding:
            ids[req.slot] = req.adapter_idx
        return ids

    def _tables_for(self, reqs: list[Optional[EngineRequest]]) -> np.ndarray:
        """[N, max_pages + 1] page tables with the trailing trash column."""
        n = len(reqs)
        out = np.zeros((n, self.kv.max_pages_per_seq + 1), dtype=np.int32)
        for i, r in enumerate(reqs):
            if r is not None and r.request_id in self.kv.seqs:
                out[i, : self.kv.max_pages_per_seq] = self.kv.page_table_row(r.request_id)
        return out

    # ------------------------------------------------- overlapped pipeline

    def _bump_epoch(self) -> None:
        """Invalidate the cached decode dispatch inputs. Called wherever
        the slot→request mapping changes: slot assignment, finish,
        preemption. Page-table growth invalidates separately through
        ``kv.version`` (part of the same cache key)."""
        self._sched_epoch += 1

    def _lead(self, req: EngineRequest) -> int:
        """Tokens scheduled for ``req`` in the in-flight window but not yet
        consumed on host — the host's view of the sequence lags the device
        by this much while the pipeline is primed."""
        p = self._pending
        if (p is not None and req.state == RequestState.DECODE
                and req.request_id in p.req_ids):
            return p.k
        return 0

    def _slot_inputs(self) -> _SlotInputs:
        """Device inputs for a decode dispatch, rebuilt only when the
        scheduler epoch or a page table moved (zero steady-state host
        prep)."""
        key = (self._sched_epoch, self.kv.version)
        si = self._slot_cache
        if si is not None and si.key == key:
            return si
        b = self.ecfg.max_batch_slots
        temps = np.zeros((b,), dtype=np.float32)
        top_ps = np.ones((b,), dtype=np.float32)
        top_ks = np.zeros((b,), dtype=np.int32)
        pres = np.zeros((b,), dtype=np.float32)
        freq = np.zeros((b,), dtype=np.float32)
        seeds = np.full((b,), -1, dtype=np.int32)
        use_pen = any(r.sampling.penalized for r in self.decoding)
        use_seed = any(r.sampling.seed is not None for r in self.decoding)
        use_bias = any(r.sampling.logit_bias for r in self.decoding)
        bias = (np.zeros((b, self.cfg.vocab_size), dtype=np.float32)
                if use_bias else None)
        for req in self.decoding:
            i = req.slot
            temps[i] = req.sampling.temperature
            top_ps[i] = req.sampling.top_p
            top_ks[i] = req.sampling.top_k
            pres[i] = req.sampling.presence_penalty
            freq[i] = req.sampling.frequency_penalty
            if req.sampling.seed is not None:
                seeds[i] = req.sampling.seed & 0x7FFFFFFF
            if bias is not None:
                for tok_id, b_val in req.sampling.logit_bias:
                    bias[i, tok_id] = b_val
        si = _SlotInputs(
            key=key,
            tables=jnp.asarray(self._tables_for(self._slots)),
            adapters=jnp.asarray(self._adapter_ids_for_slots()),
            temps=jnp.asarray(temps), top_ps=jnp.asarray(top_ps),
            top_ks=jnp.asarray(top_ks), pres=jnp.asarray(pres),
            freq=jnp.asarray(freq), seeds=jnp.asarray(seeds),
            bias=jnp.asarray(bias) if bias is not None else None,
            use_pen=use_pen, use_seed=use_seed, use_bias=use_bias,
        )
        self._slot_cache = si
        return si

    def _fetch_tokens(self, toks_dev: jax.Array) -> np.ndarray:
        """THE decode-loop token egress. Every decode path (lagged drain,
        forced-sync, guided k=1, speculative verify) consumes its sampled
        tokens through this single point; the host copy was started
        asynchronously at dispatch time, so in the lagged pipeline this
        wait is bounded by whatever device time the host failed to hide."""
        # runbook: noqa[RBK002] — sanctioned sync: the async-egress
        # consumption point — the one token fetch in the decode loop
        # (prefill TTFT and the logprob triple keep their own fetches).
        return np.asarray(jax.device_get(toks_dev))

    def _drain(self, pending: _PendingDecode, overlapped: bool) -> np.ndarray:
        """Consume one decode window: fetch its tokens and emit them.

        Stop conditions fire here — one window late in the lagged pipeline
        (emit-then-truncate: a request finishing mid-window discards the
        rest of its row, and a finished request's rows in any already-issued
        overshoot window are discarded at that window's drain; the overshoot
        KV writes land in pages reclaimed on finish and are never published).
        ``overlapped`` marks emission work running while the next dispatch
        executes on device — the time the pipeline hides."""
        t0 = time.perf_counter()
        toks_host = self._fetch_tokens(pending.toks_dev)
        t_fetch = time.perf_counter()
        emitted = 0
        for step_idx in range(pending.k):
            for req, slot in pending.reqs:
                if req.state == RequestState.DECODE:
                    self._emit_token(req, int(toks_host[slot, step_idx]))
                    emitted += 1
        t_emit = time.perf_counter()
        self.metrics["decode_tokens"] += emitted
        self.metrics["decode_steps"] += pending.k
        self.metrics["decode_dispatch_time_s"] += t_fetch - t0
        self.metrics["decode_host_time_s"] += t_emit - t_fetch
        if overlapped:
            self.metrics["decode_host_overlap_s"] += t_emit - t_fetch
        self.metrics["decode_time_s"] += t_emit - t0
        self._drain_time_acc += t_emit - t0
        return toks_host

    def _drain_pending(self) -> None:
        """Synchronously settle the in-flight window (reconciliation point:
        speculation drafting, guided masks, preemption folds, context-limit
        finishes and shutdown all need the host view current first)."""
        pending, self._pending = self._pending, None
        if pending is not None:
            self._drain(pending, overlapped=False)

    # ------------------------------------------------------------ scheduling

    def _admit(self) -> None:
        free_slots = sum(s is None for s in self._slots)
        in_flight = len(self.prefilling)
        # Admission order (FCFS within a class either way; ordering by
        # arrival_time keeps re-queued preempted requests ahead of
        # same-priority newcomers): the weighted-deficit scheduler
        # interleaves classes in weight proportion — a batch flood can no
        # longer starve interactive admits, and steady interactive load
        # can no longer starve batch (sched/wdrr.py) — while the classic
        # "priority" policy keeps the strict priority-then-FCFS sort.
        if len(self.waiting) > 1:
            if self._sched is not None:
                self.waiting = self._sched.order(self.waiting)
            else:
                self.waiting.sort(key=lambda r: (-r.priority,
                                                 r.arrival_time))
        while self.waiting and (free_slots - in_flight) > 0:
            req = self.waiting[0]
            # Headroom never exceeds what the request could actually generate;
            # an otherwise-idle engine admits with zero headroom so a request
            # that only fits exactly still makes progress (preemption has
            # nothing to evict in that case anyway).
            # Remaining budget, not the full one: a preempted request that
            # already generated most of its tokens must not head-of-line
            # block admission reserving headroom it can never use.
            headroom = min(self.ecfg.admit_headroom_tokens,
                           max(req.sampling.max_new_tokens - req.num_generated, 0))
            idle = not (self.prefilling or self.decoding)
            if idle:
                headroom = 0
            if req.block_hashes is None:
                # Seeded by the LoRA adapter row: adapter KV differs for
                # the same tokens, so each adapter gets its own prefix-
                # cache namespace (base = seed 0).
                req.block_hashes = hash_blocks(req.prompt_ids,
                                               self.ecfg.page_size,
                                               seed=req.adapter_idx)
            if self.kv.spill is not None:
                # Spill-tier readmit: blocks evicted from HBM but still in
                # host RAM come back as ordinary prefix pages, so the
                # probe below sees them as hits instead of re-prefilling.
                self._kv_k, self._kv_v, back = self.kv.readmit_spilled(
                    self._kv_k, self._kv_v, req.prompt_ids,
                    hashes=req.block_hashes, hash_seed=req.adapter_idx)
                if back:
                    self.metrics["kv_spill_readmits"] += back
                    self.metrics["kv_pages_imported"] += back
            ok, matched = self.kv.probe_admit(req.prompt_ids, headroom,
                                              hashes=req.block_hashes,
                                              hash_seed=req.adapter_idx)
            if not ok:
                if idle:
                    # Idle engine, zero headroom, retired prefix pages count
                    # as free — if it still doesn't fit, no future release
                    # can ever make it fit. Fail it rather than spinning
                    # has_work forever (liveness: surfaced by the priority
                    # preemption test, but reachable by any oversized
                    # prompt or a recompute cycle whose folded prompt
                    # outgrew the pool).
                    self.waiting.pop(0)
                    req.state = RequestState.FAILED
                    req.finish_reason = FinishReason.ABORTED
                    self._observe_finish(req)
                    self.finished.append(req)
                    if req.done_event is not None:
                        req.done_event.set()
                    continue
                break
            self.waiting.pop(0)
            if self._sched is not None:
                # Advance the class's stride pass for the ACTUAL admission
                # (ordering alone never charges a class).
                self._sched.commit(req.priority)
            # Reuse resident pages for the shared prompt prefix (same system
            # prompt across agent iterations): prefill resumes at the first
            # novel token.
            cached = self.kv.add_sequence(req.request_id, req.prompt_ids,
                                          hashes=req.block_hashes,
                                          matched=matched,
                                          hash_seed=req.adapter_idx)
            req.state = RequestState.PREFILL
            req.prefill_pos = cached
            cls = class_label(req.priority)
            if not req.folded_out_ids:
                # First admission only: a preempted request re-matching
                # its OWN published pages is recompute avoidance, not a
                # prompt-cache hit the client should be billed less for.
                req.cached_tokens = cached
                wait_s = time.perf_counter() - req.arrival_time
                self.hist_queue_wait.observe(wait_s)
                self.hist_class_queue_wait.labels(cls=cls).observe(wait_s)
            self._m_class_admits.labels(cls=cls).inc()
            self.metrics["cached_prefix_tokens"] += cached
            self.prefilling.append(req)
            in_flight += 1
            if self.tracer.enabled:
                meta = {"request": req.request_id, "cached_tokens": cached,
                        "cls": class_name(req.priority),
                        "queue_ms": round((time.perf_counter()
                                           - req.arrival_time) * 1e3, 3)}
                if self.replica_idx is not None:
                    meta["replica"] = self.replica_idx
                if req.trace_id is not None:
                    meta["trace_id"] = req.trace_id
                self.tracer.event("engine.admit", **meta)

    @staticmethod
    def _fold_into_prompt(req: EngineRequest, prefill_pos: int) -> None:
        """Fold generated tokens into the prompt. They move to
        folded_out_ids (not out_ids) so ctx_len never double-counts them
        and the output/budget accounting still sees every generated token.
        ``prefill_pos`` says how much of the new prompt already has K/V in
        the pool (0 for preemption-recompute; the written length for the
        grammar fast-forward, which keeps its pages)."""
        req.prompt_ids = req.prompt_ids + req.out_ids
        req.folded_out_ids = req.folded_out_ids + req.out_ids
        req.out_ids = []
        req.block_hashes = None
        req.prefill_pos = prefill_pos

    def _preempt_youngest(self) -> bool:
        """Evict the lowest-priority, most recently arrived decoding
        request (recompute on re-admission)."""
        if not self.decoding:
            return False
        # Folding generated tokens into the prompt needs the host view
        # complete: settle the in-flight lagged window before choosing a
        # victim (the drained tokens may even finish someone and free the
        # pages this preemption was about to chase).
        self._drain_pending()
        if not self.decoding:
            return False
        victim = max(self.decoding,
                     key=lambda r: (-r.priority, r.arrival_time))
        self.decoding.remove(victim)
        if victim.slot is not None:
            self._slots[victim.slot] = None
            victim.slot = None
        # Publish the victim's full pages before freeing: re-admission will
        # match its own prefix and recompute only the tail.
        self.kv.release(victim.request_id, token_ids=self._kv_valid_tokens(victim))
        if self.draft is not None:
            self.draft.release(victim.request_id)
        self._fold_into_prompt(victim, prefill_pos=0)
        victim.state = RequestState.WAITING
        self.waiting.insert(0, victim)
        self.metrics["preemptions"] += 1
        self._bump_epoch()
        return True

    def _kv_valid_tokens(self, req: EngineRequest) -> list[int]:
        """Tokens whose K/V has actually been written to the pool.

        Prefilled prompt tokens plus every generated token that was fed back
        (all but the last emitted one — its KV write happens on the *next*
        decode dispatch, which never runs for a finishing sequence).
        """
        valid = req.prompt_ids[: req.prefill_pos]
        if req.out_ids:
            valid = valid + req.out_ids[:-1]
        return valid

    def _observe_finish(self, req: EngineRequest) -> None:
        """Latency histograms + trace correlation for a finishing request.

        Idempotent via ``finish_time``: force_finish re-runs the cleanup of
        a partially-finished request after an abort crash, and one request
        must never observe twice."""
        if req.finish_time is not None:
            return
        now = time.perf_counter()
        req.finish_time = now
        self.hist_e2e.observe(now - req.arrival_time)
        if req.first_token_time is not None and req.num_generated > 1:
            self.hist_tpot.observe((now - req.first_token_time)
                                   / (req.num_generated - 1))
        # One JSONL line per request ties the engine's view back to the
        # server's x-request-id (req.trace_id) — the join key between a
        # trace record and the request's metrics. No-op when tracing is off.
        meta = {"request": req.request_id,
                "reason": req.finish_reason.value if req.finish_reason else None,
                "generated": req.num_generated}
        if self.replica_idx is not None:
            meta["replica"] = self.replica_idx
        if req.ttft_ms is not None:
            meta["ttft_ms"] = round(req.ttft_ms, 3)
        if req.trace_id is not None:
            meta["trace_id"] = req.trace_id
        self.tracer.event("engine.request", **meta)
        if self.workload_tap is not None:
            # Workload fingerprinting (obs/): sample the finished request.
            # Best-effort — observation must never fail a request.
            try:
                self.workload_tap(req)
            except Exception:  # noqa: BLE001 — observer errors stay silent
                pass

    def _finish(self, req: EngineRequest, reason: FinishReason) -> None:
        req.state = RequestState.FINISHED
        req.finish_reason = reason
        self._observe_finish(req)
        self._bump_epoch()
        if req.slot is not None:
            self._slots[req.slot] = None
            req.slot = None
        if req in self.decoding:
            self.decoding.remove(req)
        if req in self.prefilling:
            self.prefilling.remove(req)
        self.kv.release(req.request_id, token_ids=self._kv_valid_tokens(req))
        if self.draft is not None:
            self.draft.release(req.request_id)
        self._last_token.pop(req.request_id, None)
        self.finished.append(req)
        if req.done_event is not None:
            req.done_event.set()

    def force_finish(self, req: EngineRequest) -> None:
        """Best-effort finish for crash recovery: every cleanup step runs
        independently (pool removal, slot, KV pages, last-token map), so a
        corrupted core still ends with the request out of the live pools
        and its awaiter unblocked. Normal paths use :meth:`_finish`."""
        for pool in (self.waiting, self.prefilling, self.decoding):
            if req in pool:
                pool.remove(req)
        self._bump_epoch()
        if req.slot is not None and req.slot < len(self._slots):
            self._slots[req.slot] = None
            req.slot = None
        try:
            if req.request_id in self.kv.seqs:
                self.kv.release(req.request_id,
                                token_ids=self._kv_valid_tokens(req))
        except Exception:  # noqa: BLE001 — release itself may be poisoned
            pass
        self._last_token.pop(req.request_id, None)
        req.state = RequestState.FINISHED
        req.finish_reason = req.finish_reason or FinishReason.ABORTED
        try:
            self._observe_finish(req)
        except Exception:  # noqa: BLE001 — metrics must not block recovery
            pass
        if req not in self.finished:
            self.finished.append(req)
        if req.done_event is not None:
            req.done_event.set()

    def abort(self, request_id: str) -> bool:
        """Abort a live request (streaming consumer went away): frees its
        batch slot and KV pages immediately so concurrent requests are not
        starved by a generation nobody is draining. Returns False when the
        request is unknown or already finished."""
        for pool in (self.waiting, self.prefilling, self.decoding):
            for req in pool:
                if req.request_id == request_id:
                    if req in self.waiting:
                        self.waiting.remove(req)
                        req.state = RequestState.FINISHED
                        req.finish_reason = FinishReason.ABORTED
                        self._observe_finish(req)
                        self.finished.append(req)
                        if req.done_event is not None:
                            req.done_event.set()
                    else:
                        self._finish(req, FinishReason.ABORTED)
                    return True
        return False

    # --------------------------------------------------------------- prefill

    def _run_prefill(self) -> None:
        """One BATCHED prefill dispatch: chunks for up to ``prefill_batch``
        sequences in a single forward. Serializing prefill one sequence per
        step made TTFT degrade linearly under concurrent submissions
        (VERDICT r1 weak #5); batching restores near-constant TTFT while the
        per-row chunking still bounds dispatch latency for decode overlap.
        """
        t0 = time.perf_counter()
        rows: list[tuple[EngineRequest, int, int]] = []  # (req, chunk, new_ctx)
        for req in list(self.prefilling[: max(1, self.ecfg.prefill_batch)]):
            chunk_len = min(self.ecfg.prefill_chunk,
                            len(req.prompt_ids) - req.prefill_pos)
            new_ctx = req.prefill_pos + chunk_len
            if self.kv.spill is not None:
                # Capture the retired pages this extension would evict into
                # the host spill tier BEFORE they are recycled (the one
                # point evicted bytes are still addressable).
                alloc = self.kv.seqs.get(req.request_id)
                need = (alloc.pages_needed(new_ctx, self.ecfg.page_size)
                        if alloc is not None else 0)
                if need:
                    self.kv.spill_evictable(self._kv_k, self._kv_v, need)
            try:
                self.kv.extend(req.request_id, new_ctx)
            except MemoryError:
                if rows:
                    # Run what fits; this request retries next step. Keep
                    # scanning — a later request's (smaller) extension may
                    # still fit this dispatch (ADVICE r2: breaking here
                    # head-of-line blocked the rest of the batch). Liveness:
                    # the HEAD request always fails with rows empty (FIFO
                    # scan), taking the preempt/abort path below — and a
                    # skipped request reaches the head in bounded steps as
                    # earlier rows finish, so no request starves.
                    continue
                if self._preempt_youngest():
                    return  # retry next step
                self.prefilling.remove(req)
                self._finish(req, FinishReason.ABORTED)
                return
            rows.append((req, chunk_len, new_ctx))
        if not rows:
            return

        # Pad the row count to a power of two so the compile count stays
        # O(log prefill_batch); pad rows write to the null page and attend
        # over one masked key (ctx 1 avoids an all-masked softmax).
        b = 1
        while b < len(rows):
            b *= 2
        t = self.ecfg.prefill_chunk
        tokens = np.zeros((b, t), dtype=np.int32)
        positions = np.full((b, t), self._trash_pos(), dtype=np.int32)
        ctx_lens = np.ones((b,), dtype=np.int32)
        last_idx = np.zeros((b,), dtype=np.int32)
        adapter_ids = np.zeros((b,), dtype=np.int32)
        tables = self._tables_for([r for r, _, _ in rows] +
                                  [None] * (b - len(rows)))
        for i, (req, chunk_len, new_ctx) in enumerate(rows):
            tokens[i, :chunk_len] = req.prompt_ids[req.prefill_pos:new_ctx]
            positions[i, :chunk_len] = np.arange(req.prefill_pos, new_ctx)
            ctx_lens[i] = new_ctx
            last_idx[i] = chunk_len - 1
            adapter_ids[i] = req.adapter_idx

        pf_meta: dict[str, Any] = {"batch": len(rows),
                                   "tokens": int(sum(c for _, c, _ in rows))}
        if self.tracer.enabled:
            # Request attribution for `runbook timeline`: which sequences'
            # chunks rode this dispatch (built only when tracing is on).
            pf_meta["requests"] = [r.request_id for r, _, _ in rows]
        with self.tracer.span("engine.prefill", **pf_meta), \
                annotate("prefill"):
            last_logits, self._kv_k, self._kv_v = _prefill_step(
                self.params, self.cfg, jnp.asarray(tokens), self._kv_k, self._kv_v,
                jnp.asarray(positions), jnp.asarray(tables),
                jnp.asarray(ctx_lens), jnp.asarray(last_idx),
                jnp.asarray(adapter_ids),
                page_size=self.ecfg.page_size, block_pages=self.ecfg.block_pages,
                attn_impl=self.ecfg.attn_impl, mesh=self.mesh,
                qmm_impl=self.ecfg.qmm_impl,
            )

        done_rows: list[tuple[int, EngineRequest]] = []
        self.metrics["prefill_steps"] += 1
        for i, (req, chunk_len, new_ctx) in enumerate(rows):
            req.prefill_pos = new_ctx
            self.metrics["prefill_tokens"] += chunk_len
            if req.prefill_pos >= len(req.prompt_ids):
                done_rows.append((i, req))

        if done_rows:
            # Slot assignment FIRST: penalized rows need their count row
            # prepared before the first sampled token, and the gather
            # below maps prefill rows to slots. Counts track GENERATED
            # tokens only (OpenAI's c[j] counts previously *sampled*
            # tokens — prompt content is never penalized): fresh
            # assignments batch-zero their rows in one dispatch;
            # re-admissions after preemption restore the generated-so-far
            # histogram (rare path, per-request).
            fresh_pen_rows = np.zeros((self.ecfg.max_batch_slots,),
                                      dtype=bool)
            for i, req in done_rows:
                # Publish the prompt's full pages so concurrent/following
                # requests with the same prefix skip their prefill.
                self.kv.register_prefix(req.request_id, req.prompt_ids,
                                        hashes=req.block_hashes)
                self.prefilling.remove(req)
                slot = self._slots.index(None)
                self._slots[slot] = req
                req.slot = slot
                req.state = RequestState.DECODE
                self.decoding.append(req)
                if req.sampling.penalized:
                    if req.all_out_ids:
                        self._seed_counts_for(req)
                    else:
                        fresh_pen_rows[slot] = True
            self._bump_epoch()  # slot→request mapping changed
            if fresh_pen_rows.any():
                self._tok_counts = _reset_count_rows(
                    self._tok_counts, jnp.asarray(fresh_pen_rows))

            # Sample every completed row's first output token in ONE batched
            # dispatch + sync (per-row sampling would re-serialize the TTFT
            # win for short prompts finishing together).
            temps = np.zeros((b,), dtype=np.float32)
            top_ps = np.ones((b,), dtype=np.float32)
            top_ks = np.zeros((b,), dtype=np.int32)
            need_mask = False
            mask = np.ones((b, self.cfg.vocab_size), dtype=bool)
            use_pen = any(req.sampling.penalized for _, req in done_rows)
            use_seed = any(req.sampling.seed is not None
                           for _, req in done_rows)
            use_bias = any(req.sampling.logit_bias for _, req in done_rows)
            pres = np.zeros((b,), dtype=np.float32)
            freq = np.zeros((b,), dtype=np.float32)
            seeds = np.full((b,), -1, dtype=np.int32)
            bias = (np.zeros((b, self.cfg.vocab_size), dtype=np.float32)
                    if use_bias else None)
            slot_map = np.zeros((b,), dtype=np.int32)
            for i, req in done_rows:
                temps[i] = req.sampling.temperature
                top_ps[i] = req.sampling.top_p
                top_ks[i] = req.sampling.top_k
                pres[i] = req.sampling.presence_penalty
                freq[i] = req.sampling.frequency_penalty
                slot_map[i] = req.slot
                if req.sampling.seed is not None:
                    seeds[i] = req.sampling.seed & 0x7FFFFFFF
                if bias is not None:
                    for tok_id, b_val in req.sampling.logit_bias:
                        bias[i, tok_id] = b_val
                if self.mask_fn and req.sampling.guided:
                    m = self.mask_fn(req)
                    if m is not None:
                        mask[i] = m
                        need_mask = True
            counts_rows = (jnp.take(self._tok_counts,
                                    jnp.asarray(slot_map), axis=0)
                           if use_pen else None)
            self._key, sub = jax.random.split(self._key)
            toks = sample_tokens(
                last_logits, sub, jnp.asarray(temps), jnp.asarray(top_ps),
                jnp.asarray(mask) if need_mask else None,
                jnp.asarray(top_ks),
                counts=counts_rows,
                presence=jnp.asarray(pres) if use_pen else None,
                frequency=jnp.asarray(freq) if use_pen else None,
                seeds=jnp.asarray(seeds) if use_seed else None,
                positions=jnp.asarray(ctx_lens) if use_seed else None,
                bias=jnp.asarray(bias) if use_bias else None,
            )
            # Wire the first tokens into the device-resident decode feed
            # before fetching them: row i scatters to its slot, pad rows
            # scatter out of bounds and drop (fixed shape per prefill
            # width, so no extra compile per batch composition).
            feed_idx = np.full((b,), self.ecfg.max_batch_slots,
                               dtype=np.int32)
            for i, req in done_rows:
                feed_idx[i] = req.slot
            self._feed_toks = self._feed_toks.at[jnp.asarray(feed_idx)].set(
                toks, mode="drop")
            # runbook: noqa[RBK002] — sanctioned sync: the one batched
            # first-token fetch per prefill dispatch (TTFT emission point).
            toks_host = np.asarray(jax.device_get(toks))
            lp_pairs = [(i, req) for i, req in done_rows
                        if req.sampling.logprobs]
            if lp_pairs:
                self._append_logprob_entries(
                    lp_pairs, toks_host, _token_logprobs(last_logits, toks))
            if use_pen:
                # ONE batched scatter for every penalized first token —
                # per-request bumps would re-serialize the TTFT win the
                # batched sampling above exists for.
                live = np.zeros((b,), dtype=np.int32)
                for i, req in done_rows:
                    if req.sampling.penalized:
                        live[i] = 1
                self._tok_counts = _bump_counts_batch(
                    self._tok_counts, jnp.asarray(slot_map),
                    jnp.asarray(toks_host.astype(np.int32)),
                    jnp.asarray(live))
            for i, req in done_rows:
                if req.first_token_time is None:  # true TTFT across preemption
                    req.first_token_time = time.perf_counter()
                    self.hist_ttft.observe(req.first_token_time
                                           - req.arrival_time)
                self._emit_token(req, int(toks_host[i]))
        self.metrics["prefill_time_s"] += time.perf_counter() - t0

    def _seed_counts_for(self, req: EngineRequest,
                         slot: Optional[int] = None) -> None:
        """Restore the request's slot row to its GENERATED-token histogram
        (OpenAI penalties count sampled tokens, never the prompt); ids pad
        to powers of two so compile count stays O(log len). ``slot``
        overrides ``req.slot`` for the mixed dispatch, which prepares the
        row BEFORE the in-dispatch first-token sampling assigns it."""
        ids = req.all_out_ids
        n = max(1, len(ids))
        padded_len = 1
        while padded_len < n:
            padded_len *= 2
        padded = np.zeros((padded_len,), dtype=np.int32)
        padded[: len(ids)] = ids
        self._tok_counts = _seed_count_row(
            self._tok_counts,
            jnp.int32(req.slot if slot is None else slot),
            jnp.asarray(padded), jnp.int32(len(ids)))

    # ---------------------------------------------------------------- decode

    @staticmethod
    def _append_logprob_entries(pairs, toks_h, scored) -> None:
        """Attach one {token_id, logprob, top} record per (row, request)
        pair from a scored batch (single host fetch for the triple)."""
        # runbook: noqa[RBK002] — sanctioned sync: one [B, K+1] fetch per
        # dispatch for logprob requests (full-vocab rows would dwarf it).
        chosen, top_ids, top_lp = jax.device_get(scored)
        chosen, top_ids, top_lp = (np.asarray(chosen), np.asarray(top_ids),
                                   np.asarray(top_lp))
        for i, req in pairs:
            n = min(req.sampling.logprobs, _TOPK_LOGPROBS)
            req.out_logprobs.append({
                "token_id": int(toks_h[i]),
                "logprob": float(chosen[i]),
                "top": [(int(t), float(p))
                        for t, p in zip(top_ids[i, :n], top_lp[i, :n])],
            })

    def _score_logprobs(self, last_logits, toks, toks_h, reqs) -> None:
        """Top-K logprobs for requests that asked (k==1 dispatches only —
        _pick_k forces that). Raw model distribution, pre-mask. ``reqs``
        is the dispatch-time snapshot: a request finishing on this very
        token must still get the token's entry."""
        pairs = [(slot, r) for r, slot in reqs if r.sampling.logprobs]
        if not pairs:
            return
        self._append_logprob_entries(pairs, toks_h,
                                     _token_logprobs(last_logits, toks))

    def _emit_token(self, req: EngineRequest, token: int) -> None:
        """Record a sampled token and apply finish rules."""
        req.out_ids.append(token)
        if req.on_token is not None:
            req.on_token(token)
        self._last_token[req.request_id] = token
        grammar_done = False
        if self.advance_fn and req.sampling.guided:
            grammar_done = self.advance_fn(req, token)
        stop_ids = set(req.sampling.stop_token_ids) | {self.tokenizer.eos_id, self.tokenizer.eot_id}
        if token in stop_ids:
            self._finish(req, FinishReason.STOP_TOKEN)
        elif grammar_done:
            self._finish(req, FinishReason.GRAMMAR_END)
        elif req.num_generated >= req.sampling.max_new_tokens:
            self._finish(req, FinishReason.MAX_TOKENS)
        elif req.sampling.stop_strings:
            # Tail-only slices: all_out_ids would copy O(N) per emitted token.
            tail = self.tokenizer.decode(
                (req.folded_out_ids[-32:] + req.out_ids[-32:])[-32:])
            if any(s in tail for s in req.sampling.stop_strings):
                self._finish(req, FinishReason.STOP_STRING)

    def _pick_k(self) -> int:
        """Decode tokens per dispatch: 1 when any guided request needs
        per-token masks, else the largest power of two ≤ config that fits
        every sequence's remaining max_seq headroom."""
        if any(r.sampling.forced_sync for r in self.decoding):
            return 1
        k = max(1, self.ecfg.decode_steps_per_dispatch)
        # Scheduled (lead-adjusted) lengths: the in-flight window's tokens
        # occupy context the host hasn't consumed yet.
        remaining = min(self.ecfg.max_seq_len - (r.ctx_len + self._lead(r))
                        for r in self.decoding)
        while k > 1 and (k > remaining):
            k //= 2
        # power-of-two clamp bounds distinct compiled programs
        p = 1
        while p * 2 <= k:
            p *= 2
        return p

    def _draft_for(self, req: EngineRequest, max_draft: int) -> list[int]:
        """Prompt-lookup draft: tokens that followed the most recent earlier
        occurrence of the sequence's trailing n-gram (vectorized search)."""
        n = self.ecfg.spec_ngram
        hist = req.prompt_ids[: req.prefill_pos] + req.out_ids
        if max_draft < 1 or len(hist) <= n:
            return []
        # Cap the lookback so per-dispatch host cost stays bounded on long
        # agent contexts; recent repeats dominate acceptance anyway.
        arr = np.asarray(hist[-2048:], dtype=np.int64)
        tail = arr[-n:]
        windows = np.lib.stride_tricks.sliding_window_view(arr[:-1], n)
        hits = np.nonzero((windows == tail).all(axis=1))[0]
        if hits.size == 0:
            return []
        start = int(hits[-1]) + n
        return arr[start : start + max_draft].tolist()

    def _grow_pages_for_decode(self, k: int) -> None:
        """Ensure every decoding sequence has pages for its scheduled
        context (ctx + in-flight lead) + k tokens, preempting the youngest
        (or aborting) under pool pressure. Preemption drains the lagged
        window first (the fold needs the host view complete), so the
        lead — and each target — may legitimately shrink mid-loop."""
        for req in list(self.decoding):
            while (
                req.state == RequestState.DECODE
                and not self.kv.can_extend(
                    req.request_id, req.ctx_len + self._lead(req) + k)
            ):
                # _preempt_youngest may evict ``req`` itself — the state guard
                # above then exits the loop. Its internal drain may even
                # FINISH ``req`` (a stop was sitting in the lagged window),
                # so re-check before declaring the pool unfixable.
                if not self._preempt_youngest():
                    if req.state == RequestState.DECODE:
                        self._finish(req, FinishReason.ABORTED)
                    break
            if req.state == RequestState.DECODE and req.request_id in self.kv.seqs:
                # Growth invalidates the cached dispatch tables by itself:
                # kv.version is part of the _SlotInputs cache key.
                self.kv.extend(req.request_id,
                               req.ctx_len + self._lead(req) + k)

    def _run_decode_spec(self, drafts: dict[str, list[int]], k: int) -> None:
        """Speculative dispatch: feed [last, draft...] as one T=k chunk and
        accept the agreeing prefix."""
        t0 = time.perf_counter()
        self._grow_pages_for_decode(k)
        if not self.decoding:
            return

        b = self.ecfg.max_batch_slots
        tokens = np.zeros((b, k), dtype=np.int32)
        positions = np.zeros((b, k), dtype=np.int32)
        ctx_lens = np.zeros((b,), dtype=np.int32)
        feeds: dict[str, list[int]] = {}
        for req in self.decoding:
            i = req.slot
            draft = drafts.get(req.request_id, [])[: k - 1]
            feed = [self._last_token[req.request_id]] + draft
            feed = feed + [feed[-1]] * (k - len(feed))  # pad rows to T=k
            feeds[req.request_id] = feed
            tokens[i] = feed
            positions[i] = np.arange(req.ctx_len - 1, req.ctx_len - 1 + k)
            ctx_lens[i] = req.ctx_len + k - 1  # keys written for all fed tokens
            self.metrics["spec_drafted"] += len(draft)
        si = self._slot_inputs()

        spec_meta: dict[str, Any] = {"k": k, "batch": len(self.decoding)}
        if self.tracer.enabled:
            spec_meta["requests"] = [r.request_id for r in self.decoding]
        with self.tracer.span("engine.decode_spec", **spec_meta), \
                annotate("decode_spec"):
            t_issue = time.perf_counter()
            toks, self._kv_k, self._kv_v = _decode_spec(
                self.params, self.cfg, jnp.asarray(tokens), jnp.asarray(positions),
                self._kv_k, self._kv_v, si.tables, jnp.asarray(ctx_lens),
                si.adapters,
                page_size=self.ecfg.page_size, block_pages=self.ecfg.block_pages,
                attn_impl=self.ecfg.attn_impl, mesh=self.mesh,
                qmm_impl=self.ecfg.qmm_impl,
            )
            toks_host = self._fetch_tokens(toks)  # [B, k]
            t_fetch = time.perf_counter()

        emitted = 0
        for req in list(self.decoding):
            i = req.slot
            feed = feeds[req.request_id]
            draft = drafts.get(req.request_id, [])[: k - 1]
            self._emit_token(req, int(toks_host[i, 0]))
            emitted += 1
            j = 1
            while (req.state == RequestState.DECODE and j <= len(draft)
                   and feed[j] == int(toks_host[i, j - 1])):
                self._emit_token(req, int(toks_host[i, j]))
                emitted += 1
                self.metrics["spec_accepted"] += 1
                j += 1
        # Re-arm the device-resident feed with each survivor's last
        # accepted token (the verify argmax buffer's last column is not the
        # accepted tail); pad rows scatter out of bounds and drop.
        feed_idx = np.full((b,), b, dtype=np.int32)
        feed_val = np.zeros((b,), dtype=np.int32)
        for req in self.decoding:
            feed_idx[req.slot] = req.slot
            feed_val[req.slot] = self._last_token[req.request_id]
        self._feed_toks = self._feed_toks.at[jnp.asarray(feed_idx)].set(
            jnp.asarray(feed_val), mode="drop")
        t_end = time.perf_counter()
        self.metrics["decode_tokens"] += emitted
        self.metrics["decode_steps"] += 1
        self.metrics["decode_dispatches"] += 1
        self.metrics["decode_dispatch_time_s"] += t_fetch - t_issue
        self.metrics["decode_host_time_s"] += (
            (t_issue - t0) + (t_end - t_fetch))
        self.metrics["decode_time_s"] += t_end - t0

    def _grammar_fast_forward(self, req: EngineRequest) -> None:
        """Emit a grammar-FORCED token run without per-token model dispatches.

        Schema-guided documents are dominated by deterministic stretches
        (object keys, quotes, separators — with a byte tokenizer well over
        half the bytes): wherever the mask admits exactly ONE token there is
        nothing to sample, so decoding them one 70ms host round-trip at a
        time is pure overhead. Probe the grammar on a COPY, and when a run
        of ≥4 forced tokens exists, emit the whole run at once and fold it
        (with the pending last token) into the prompt — the prefill path
        then writes their K/V in chunked batches and samples the next free
        token with the post-run mask. The same fold preemption uses, minus
        the page release.
        """
        enabled = self.ecfg.grammar_fast_forward
        if enabled is None:
            enabled = jax.default_backend() in ("tpu", "axon")
        if not enabled:
            return
        if not (self.mask_fn and self.advance_fn and req.sampling.guided):
            return
        if req.sampling.logprobs:
            return  # forced runs surface no logits to score
        if req.sampling.stop_strings:
            # Forced runs would bypass the stop-string tail scan; rare for
            # guided requests, so just leave them on the per-token path.
            return
        budget = req.sampling.max_new_tokens - req.num_generated
        if budget <= 0:
            return
        orig = req.guided_state
        if orig is None:
            self.mask_fn(req)  # provider initializes the machine lazily
            orig = req.guided_state
            if orig is None:
                return
        probe = orig.copy()
        req.guided_state = probe
        forced: list[int] = []
        cap = min(budget, 4 * self.ecfg.prefill_chunk,
                  self.ecfg.max_seq_len - req.ctx_len - 1)
        stop_ids = set(req.sampling.stop_token_ids) | {
            self.tokenizer.eos_id, self.tokenizer.eot_id}
        try:
            while len(forced) < cap:
                m = self.mask_fn(req)
                if m is None:
                    break
                ids = np.nonzero(m)[0]
                if ids.size != 1 or int(ids[0]) in stop_ids:
                    break  # stop tokens take the normal emit/finish path
                tok = int(ids[0])
                forced.append(tok)
                if self.advance_fn(req, tok):
                    break  # grammar completed inside the run
        except Exception:
            req.guided_state = orig  # surface provider bugs, state restored
            raise
        if len(forced) < 4:
            req.guided_state = orig  # not worth a fold: restore
            return
        # Commit: the advanced probe IS the new grammar state. Forced tokens
        # are counted separately (not in decode_tokens: their K/V cost lands
        # in the prefill fold, so booking them as decode throughput would
        # inflate the BASELINE decode-tok/s metric).
        req.out_ids.extend(forced)
        if req.on_token is not None:
            for tok in forced:
                req.on_token(tok)
        self._last_token[req.request_id] = forced[-1]
        self.metrics["grammar_forced_tokens"] = (
            self.metrics.get("grammar_forced_tokens", 0) + len(forced))
        # Fold emitted-but-unprocessed tokens (the pending last token + the
        # forced run) into the prompt BEFORE any finish: _kv_valid_tokens /
        # prefix publication must only ever claim tokens whose K/V exists.
        written = req.ctx_len - len(forced) - 1  # tokens with K/V in the pool
        self._fold_into_prompt(req, prefill_pos=written)
        self.decoding.remove(req)
        if req.slot is not None:
            self._slots[req.slot] = None
            req.slot = None
        # Slot freed without a finish: invalidate the cached dispatch
        # inputs or the next decode would read a stale table whose freed
        # row still points at this request's live pages.
        self._bump_epoch()
        if req.num_generated >= req.sampling.max_new_tokens:
            self._finish(req, FinishReason.MAX_TOKENS)
            return
        req.state = RequestState.PREFILL
        self.prefilling.append(req)

    # ------------------------------------------------------- mixed dispatch

    def _can_mix(self) -> bool:
        """True when this step can run as ONE unified mixed dispatch.

        Forced-sync consumers (guided masks, logprob attachment) and
        sequences at the context limit keep the classic split path — their
        reconciliation rules (docs/decode_pipeline.md) are defined against
        it. The prefill HEAD is checked rather than skipped so FIFO
        fairness survives: a guided prompt at the head falls the whole
        step back to the classic path instead of starving behind mixers.
        """
        if not (self._mixed and self.prefilling and self.decoding):
            return False
        if any(r.sampling.forced_sync for r in self.decoding):
            return False
        if any(r.ctx_len + self._lead(r) + 1 > self.ecfg.max_seq_len
               for r in self.decoding):
            return False
        return not self.prefilling[0].sampling.forced_sync

    def _run_mixed(self) -> bool:
        """One ragged dispatch: every live decode slot (1 token each) plus
        the oldest prefill chunk(s), within the mixed token budget.

        Decode rows behave exactly like a k=1 :meth:`_run_decode` window
        (device-resident feed in, overlap pipeline out); prefill rows
        advance their chunk, and rows completing their prompt sample the
        FIRST output token inside the same dispatch (TTFT saves a whole
        dispatch). Returns False when reconciliation (drains, preemption,
        pool pressure) left nothing to mix — the caller then falls back to
        the classic split path for this step; the dispatch has not been
        issued and any prefill page extensions done here are idempotent
        under the classic chunk sizes.
        """
        t0 = time.perf_counter()
        acc0 = self._drain_time_acc
        # Same all-budget-covered tail rule as _run_decode: a dispatch
        # whose decode rows would all be overshoot is pure waste.
        if self._pending is not None and all(
                r.num_generated + self._lead(r) >= r.sampling.max_new_tokens
                for r in self.decoding):
            self._drain_pending()
        if not self._can_mix():
            return False
        rq = _RAGGED_BLOCK
        b = self.ecfg.max_batch_slots
        # Prefill row selection: FIFO, chunked, budget- and row-capped.
        # Stopping (not skipping) at the first ineligible/unfittable
        # request preserves admission order; the classic path serves it.
        pf_rows: list[tuple[EngineRequest, int, int]] = []
        used = 0
        for req in list(self.prefilling[: self._mix_pf_rows]):
            if req.sampling.forced_sync:
                break
            room = self._mix_pf_tokens - used
            if room < 1:
                break
            chunk = min(self.ecfg.prefill_chunk,
                        len(req.prompt_ids) - req.prefill_pos, room)
            new_ctx = req.prefill_pos + chunk
            try:
                self.kv.extend(req.request_id, new_ctx)
            except MemoryError:
                break  # run what fits; classic preempts when nothing does
            pf_rows.append((req, chunk, new_ctx))
            used += -(-chunk // rq) * rq
        if not pf_rows:
            return False
        # Decode page growth AFTER the prefill extends, mirroring the
        # classic step order (prefill dispatch precedes decode). The
        # internal preemption/drain may finish or evict decoders — or the
        # whole decode side — so re-check before committing to the mix.
        self._grow_pages_for_decode(1)
        if not self.decoding:
            return False

        t_build = time.perf_counter()
        n = b * rq + self._mix_pf_tokens
        n_pf = self._mix_pf_rows
        pad_row = self._mix_rows - 1
        trash = self._trash_pos()
        tokens = np.zeros((n,), dtype=np.int32)
        positions = np.full((n,), trash, dtype=np.int32)
        row_ids = np.full((n,), pad_row, dtype=np.int32)
        ctx_lens = np.zeros((self._mix_rows,), dtype=np.int32)
        adapters = np.zeros((self._mix_rows,), dtype=np.int32)
        dec_idx = np.arange(b, dtype=np.int32) * rq
        dec_live = np.zeros((b,), dtype=np.int32)
        for req in self.decoding:
            s = req.slot
            ec = req.ctx_len + self._lead(req)  # scheduled context
            positions[s * rq] = ec - 1
            row_ids[s * rq: (s + 1) * rq] = s
            ctx_lens[s] = ec
            adapters[s] = req.adapter_idx
            dec_live[s] = 1
        pf_last = np.zeros((n_pf,), dtype=np.int32)
        off = b * rq
        for j, (req, chunk, new_ctx) in enumerate(pf_rows):
            r = b + j
            tokens[off: off + chunk] = req.prompt_ids[req.prefill_pos:new_ctx]
            positions[off: off + chunk] = np.arange(req.prefill_pos, new_ctx)
            row_ids[off: off + (-(-chunk // rq) * rq)] = r
            ctx_lens[r] = new_ctx
            adapters[r] = req.adapter_idx
            pf_last[j] = off + chunk - 1
            off += -(-chunk // rq) * rq
        tables = self._tables_for(
            list(self._slots) + [r for r, _, _ in pf_rows]
            + [None] * (n_pf - len(pf_rows)) + [None])

        # Completions are host-known before the dispatch: precompute the
        # slot each will take (same lowest-free-slot order the classic
        # path uses) so penalty count rows can be prepared NOW — the
        # in-dispatch first-token sampling reads them.
        done: list[tuple[int, EngineRequest, int]] = []
        free = [i for i, s in enumerate(self._slots) if s is None]
        for j, (req, chunk, new_ctx) in enumerate(pf_rows):
            if new_ctx >= len(req.prompt_ids):
                done.append((j, req, free.pop(0)))
        fresh_pen = np.zeros((b,), dtype=bool)
        for j, req, slot in done:
            if req.sampling.penalized:
                if req.all_out_ids:
                    self._seed_counts_for(req, slot=slot)
                else:
                    fresh_pen[slot] = True
        if fresh_pen.any():
            self._tok_counts = _reset_count_rows(
                self._tok_counts, jnp.asarray(fresh_pen))

        si = self._slot_inputs()
        pf_temps = np.zeros((n_pf,), dtype=np.float32)
        pf_top_ps = np.ones((n_pf,), dtype=np.float32)
        pf_top_ks = np.zeros((n_pf,), dtype=np.int32)
        pf_pres = np.zeros((n_pf,), dtype=np.float32)
        pf_freq = np.zeros((n_pf,), dtype=np.float32)
        pf_seeds = np.full((n_pf,), -1, dtype=np.int32)
        pf_slot_map = np.full((n_pf,), b, dtype=np.int32)  # b → dropped
        pf_live = np.zeros((n_pf,), dtype=np.int32)
        pf_use_pen = any(req.sampling.penalized for _, req, _ in done)
        pf_use_seed = any(req.sampling.seed is not None
                          for _, req, _ in done)
        pf_use_bias = any(req.sampling.logit_bias for _, req, _ in done)
        pf_bias = (np.zeros((n_pf, self.cfg.vocab_size), dtype=np.float32)
                   if pf_use_bias else None)
        for j, req, slot in done:
            pf_temps[j] = req.sampling.temperature
            pf_top_ps[j] = req.sampling.top_p
            pf_top_ks[j] = req.sampling.top_k
            pf_pres[j] = req.sampling.presence_penalty
            pf_freq[j] = req.sampling.frequency_penalty
            pf_slot_map[j] = slot
            if req.sampling.penalized:
                pf_live[j] = 1
            if req.sampling.seed is not None:
                pf_seeds[j] = req.sampling.seed & 0x7FFFFFFF
            if pf_bias is not None:
                for tok_id, b_val in req.sampling.logit_bias:
                    pf_bias[j, tok_id] = b_val
        use_pen = si.use_pen or pf_use_pen

        real_tokens = len(self.decoding) + sum(c for _, c, _ in pf_rows)
        dec_snapshot = list(self.decoding)
        inflight = self._pending is not None
        self._key, sub = jax.random.split(self._key)
        mix_meta: dict[str, Any] = {"batch": len(dec_snapshot),
                                    "prefill_rows": len(pf_rows),
                                    "tokens": int(real_tokens)}
        if self.tracer.enabled:
            mix_meta["requests"] = (
                [r.request_id for r in dec_snapshot]
                + [r.request_id for r, _, _ in pf_rows])
        with self.tracer.span("engine.mixed", **mix_meta), annotate("mixed"):
            t_issue = time.perf_counter()
            (toks_win, pf_toks, feed_new, self._kv_k, self._kv_v,
             counts_out) = _mixed_step(
                self.params, self.cfg, jnp.asarray(tokens), self._feed_toks,
                jnp.asarray(dec_idx), jnp.asarray(positions),
                jnp.asarray(row_ids), self._kv_k, self._kv_v,
                jnp.asarray(tables), jnp.asarray(ctx_lens),
                jnp.asarray(adapters), jnp.asarray(pf_last),
                si.temps, si.top_ps, si.top_ks, sub,
                jnp.asarray(pf_temps), jnp.asarray(pf_top_ps),
                jnp.asarray(pf_top_ks), jnp.asarray(pf_slot_map),
                jnp.asarray(pf_live),
                dec_live=jnp.asarray(dec_live) if use_pen else None,
                counts=self._tok_counts if use_pen else None,
                pres=si.pres if si.use_pen else None,
                freq=si.freq if si.use_pen else None,
                seeds=si.seeds if si.use_seed else None,
                bias=si.bias if si.use_bias else None,
                pf_pres=jnp.asarray(pf_pres) if pf_use_pen else None,
                pf_freq=jnp.asarray(pf_freq) if pf_use_pen else None,
                pf_seeds=jnp.asarray(pf_seeds) if pf_use_seed else None,
                pf_bias=jnp.asarray(pf_bias) if pf_use_bias else None,
                page_size=self.ecfg.page_size,
                block_pages=self.ecfg.block_pages,
                attn_impl=self.ecfg.attn_impl, mesh=self.mesh,
                qmm_impl=self.ecfg.qmm_impl, ragged_block=rq,
            )
        if counts_out is not None:
            self._tok_counts = counts_out
        self._feed_toks = feed_new

        pending = _PendingDecode(
            toks_dev=toks_win,
            reqs=[(r, r.slot) for r in dec_snapshot],
            req_ids=frozenset(r.request_id for r in dec_snapshot),
            k=1,
        )
        if hasattr(toks_win, "copy_to_host_async"):
            toks_win.copy_to_host_async()

        # Prefill bookkeeping (chunk advance, completions join decode).
        self.metrics["prefill_tokens"] += sum(c for _, c, _ in pf_rows)
        for req, chunk, new_ctx in pf_rows:
            req.prefill_pos = new_ctx
        for j, req, slot in done:
            self.kv.register_prefix(req.request_id, req.prompt_ids,
                                    hashes=req.block_hashes)
            self.prefilling.remove(req)
            self._slots[slot] = req
            req.slot = slot
            req.state = RequestState.DECODE
            self.decoding.append(req)
        if done:
            self._bump_epoch()  # slot→request mapping changed
            # runbook: noqa[RBK002] — sanctioned sync: the one batched
            # mixed-step first-token fetch (TTFT emission; decode rows
            # stay device-resident in the overlap window).
            pf_host = np.asarray(jax.device_get(pf_toks))
            for j, req, slot in done:
                if req.first_token_time is None:
                    req.first_token_time = time.perf_counter()
                    self.hist_ttft.observe(req.first_token_time
                                           - req.arrival_time)
                self._emit_token(req, int(pf_host[j]))

        # Decode rows ride the overlap pipeline exactly like _run_decode.
        if self.ecfg.overlap_decode:
            prev, self._pending = self._pending, pending
            if prev is not None:
                self._drain(prev, overlapped=True)
        else:
            self._drain(pending, overlapped=False)

        self.metrics["mixed_steps"] += 1
        self.metrics["mixed_tokens"] += real_tokens
        self.hist_mixed_tokens.observe(real_tokens)
        # Host-prep attribution mirrors _run_decode: build work counts as
        # (overlappable) host decode time; the drained window's fetch/emit
        # was already booked as decode_* inside _drain. mixed_time_s books
        # only this step's own un-drained wall, so pure-step counters keep
        # their /healthz + PromQL semantics.
        self.metrics["decode_host_time_s"] += t_issue - t_build
        if inflight:
            self.metrics["decode_host_overlap_s"] += t_issue - t_build
        self.metrics["mixed_time_s"] += (
            (time.perf_counter() - t0) - (self._drain_time_acc - acc0))
        return True

    def _run_decode(self) -> None:
        if not self.decoding:
            # Tail flush: every row of the in-flight window finished or
            # aborted since its dispatch — consume (and discard) so device
            # state and metrics settle even with nothing left to schedule.
            self._drain_pending()
            return
        t0 = time.perf_counter()
        acc0 = self._drain_time_acc
        # The token budget is host-known: when the in-flight window already
        # covers every sequence's max_new_tokens, a new dispatch would be
        # all-overshoot (every row discarded at drain). Drain instead —
        # this is the common stream tail, e.g. a batch finishing together.
        if self._pending is not None and all(
                r.num_generated + self._lead(r) >= r.sampling.max_new_tokens
                for r in self.decoding):
            self._drain_pending()
            if not self.decoding:
                return
        overlap = self.ecfg.overlap_decode
        # Reconciliation: paths that must see the host view current before
        # the next dispatch can even be BUILT — per-token grammar masks and
        # logprob attachment (k=1 fetch), forced-sync mode, and sequences
        # whose scheduled context hits the limit (finish precedes growth).
        need_sync = (not overlap) or any(
            r.sampling.forced_sync for r in self.decoding)
        if not need_sync and any(
                r.ctx_len + self._lead(r) + 1 > self.ecfg.max_seq_len
                for r in self.decoding):
            need_sync = True
        if need_sync:
            self._drain_pending()
            # Sequences at the context limit finish before K is chosen.
            for req in list(self.decoding):
                if req.ctx_len + 1 > self.ecfg.max_seq_len:
                    self._finish(req, FinishReason.MAX_TOKENS)
            # Grammar fast-forward may move guided requests back to prefill
            # (their next tokens are forced — no sampling needed).
            for req in list(self.decoding):
                self._grammar_fast_forward(req)
            if not self.decoding:
                return
        k = self._pick_k()
        # Prompt-lookup speculation for all-greedy batches: one T=k verify
        # forward replaces k sequential decode steps when any draft exists.
        # Drafting needs the host-current history, so each probe drains the
        # lagged window; a draftless probe backs off re-probing so
        # non-repetitive traffic keeps the overlap instead of paying a
        # drain every step.
        if (k > 1 and self.ecfg.speculative
                and all(r.sampling.temperature == 0.0
                        and not r.sampling.guided
                        and not r.sampling.logprobs
                        # Penalized greedy shifts the argmax per position
                        # as counts evolve; the verify forward has no
                        # count plumbing — multi-step handles these.
                        # logit_bias likewise shifts the verify argmax.
                        and not r.sampling.penalized
                        and not r.sampling.logit_bias
                        for r in self.decoding)):
            if self._spec_backoff > 0:
                self._spec_backoff -= 1
            else:
                self._drain_pending()
                if not self.decoding:
                    return
                if self.draft is not None:
                    committed = [(r.request_id,
                                  r.prompt_ids[: r.prefill_pos] + r.out_ids)
                                 for r in self.decoding]
                    drafts = self.draft.draft(committed, k - 1)
                    for r in self.decoding:  # prompt-lookup fallback
                        if not drafts.get(r.request_id):
                            drafts[r.request_id] = self._draft_for(r, k - 1)
                    self.metrics.update(self.draft.metrics)
                else:
                    drafts = {r.request_id: self._draft_for(r, k - 1)
                              for r in self.decoding}
                # Worth it only when most of the batch drafts (nonempty
                # decoding list makes this imply at least one draft): an
                # undrafted request gets 1 token from a spec dispatch vs k
                # from multi-step.
                if 2 * sum(bool(d) for d in drafts.values()) >= len(self.decoding):
                    self._spec_miss_streak = 0
                    self._run_decode_spec(drafts, k)
                    return
                self._spec_miss_streak += 1
                self._spec_backoff = min(
                    max(0, self.ecfg.spec_backoff_rounds),
                    2 ** (self._spec_miss_streak - 1))
        # Grow pages to cover scheduled ctx + K for every sequence; preempt
        # on pressure (preemption drains the lagged window internally).
        self._grow_pages_for_decode(k)
        if not self.decoding:
            self.metrics["decode_time_s"] += (
                (time.perf_counter() - t0) - (self._drain_time_acc - acc0))
            return

        b = self.ecfg.max_batch_slots
        inflight = self._pending is not None
        t_build = time.perf_counter()
        si = self._slot_inputs()
        positions = np.zeros((b, 1), dtype=np.int32)
        ctx_lens = np.zeros((b,), dtype=np.int32)
        need_mask = False
        mask = None
        if self.mask_fn and any(r.sampling.guided for r in self.decoding):
            mask = np.ones((b, self.cfg.vocab_size), dtype=bool)
        for req in self.decoding:
            i = req.slot
            ec = req.ctx_len + self._lead(req)  # scheduled context
            positions[i, 0] = ec - 1  # position of the token being fed
            ctx_lens[i] = ec
            if mask is not None and req.sampling.guided:
                m = self.mask_fn(req)
                if m is not None:
                    mask[i] = m
                    need_mask = True
        self._key, sub = jax.random.split(self._key)
        pen_kw = dict(
            counts=self._tok_counts if si.use_pen else None,
            pres=si.pres if si.use_pen else None,
            freq=si.freq if si.use_pen else None,
            seeds=si.seeds if si.use_seed else None,
            bias=si.bias if si.use_bias else None,
        )
        # Device-resident token feedback: each slot's last sampled token
        # never visits the host on the input side.
        tokens_dev = self._feed_toks[:, None]

        dec_meta: dict[str, Any] = {"k": k, "batch": len(self.decoding)}
        if self.tracer.enabled:
            dec_meta["requests"] = [r.request_id for r in self.decoding]
        with self.tracer.span("engine.decode", **dec_meta), annotate("decode"):
            t_issue = time.perf_counter()
            last_logits = None
            if k == 1:
                (toks, last_logits, self._kv_k, self._kv_v,
                 counts_out) = _decode_step(
                    self.params, self.cfg, tokens_dev, jnp.asarray(positions),
                    self._kv_k, self._kv_v, si.tables, jnp.asarray(ctx_lens),
                    si.temps, si.top_ps, si.top_ks, sub,
                    jnp.asarray(mask) if need_mask else None,
                    si.adapters, **pen_kw,
                    page_size=self.ecfg.page_size, block_pages=self.ecfg.block_pages,
                    attn_impl=self.ecfg.attn_impl, mesh=self.mesh,
                    qmm_impl=self.ecfg.qmm_impl,
                )
                self._feed_toks = toks
                toks_win = toks[:, None]  # [B, 1]
            else:
                toks_win, self._kv_k, self._kv_v, counts_out = _decode_multi(
                    self.params, self.cfg, tokens_dev, jnp.asarray(positions),
                    self._kv_k, self._kv_v, si.tables, jnp.asarray(ctx_lens),
                    si.temps, si.top_ps, si.top_ks, sub,
                    si.adapters, **pen_kw,
                    page_size=self.ecfg.page_size, block_pages=self.ecfg.block_pages,
                    k_steps=k, attn_impl=self.ecfg.attn_impl, mesh=self.mesh,
                    qmm_impl=self.ecfg.qmm_impl,
                )
                self._feed_toks = toks_win[:, -1]
            if counts_out is not None:
                self._tok_counts = counts_out
            t_done = time.perf_counter()

        pending = _PendingDecode(
            toks_dev=toks_win,
            reqs=[(r, r.slot) for r in self.decoding],
            req_ids=frozenset(r.request_id for r in self.decoding),
            k=k,
        )
        # Start the token egress behind the (async) dispatch: by the time
        # the window is drained, the DMA has had a full device step to land.
        if hasattr(toks_win, "copy_to_host_async"):
            toks_win.copy_to_host_async()
        self.metrics["decode_host_time_s"] += t_issue - t_build
        if inflight:
            # Input prep ran while the previous window executed on device.
            self.metrics["decode_host_overlap_s"] += t_issue - t_build
        self.metrics["decode_dispatch_time_s"] += t_done - t_issue
        self.metrics["decode_dispatches"] += 1

        if need_sync:
            # Forced-sync: consume this window before returning (guided
            # masks / logprob attachment need the tokens before the next
            # dispatch can be built anyway). Logprob entries attach BEFORE
            # emission: _finish (inside the drain) wakes streaming
            # consumers, and their tail flush must never observe the final
            # token's entry still missing.
            if k == 1 and any(r.sampling.logprobs for r, _ in pending.reqs):
                toks_host = self._fetch_tokens(pending.toks_dev)
                self._score_logprobs(last_logits, toks_win[:, 0],
                                     toks_host[:, 0], pending.reqs)
            self._drain(pending, overlapped=False)
        else:
            # One-step lag: park this window and consume the PREVIOUS one —
            # its emission (detokenize, stop scans, stream callbacks) runs
            # while this window executes on device.
            prev, self._pending = self._pending, pending
            if prev is not None:
                self._drain(prev, overlapped=True)
        self.metrics["decode_time_s"] += (
            (time.perf_counter() - t0) - (self._drain_time_acc - acc0))

    # ------------------------------------------------------------------ step

    # ``finished`` high-water trim: a days-long server must not retain
    # every EngineRequest (prompt/output ids, logprobs) for process
    # lifetime — the 600s soak measured ~0.4 MB/s RSS growth from
    # exactly this. Recent entries stay addressable for callers that
    # inspect the tail.
    _FINISHED_HIGH_WATER = 4096
    _FINISHED_KEEP = 1024

    def step(self) -> list[EngineRequest]:
        """One scheduler iteration; returns requests finished during it.

        With prompts and decodes both live (and mixed dispatch enabled),
        the step runs as ONE unified ragged dispatch; otherwise — or when
        mixing bails during reconciliation — the classic split
        prefill-then-decode pair runs, at most one dispatch each."""
        if self.chaos_hook is not None:
            # Fault-injection seam (runbookai_tpu/chaos): runs before any
            # pool mutation so an injected crash leaves a consistent core
            # for the supervisor's failover sweep.
            self.chaos_hook(self)
        if len(self.finished) > self._FINISHED_HIGH_WATER:
            del self.finished[: -self._FINISHED_KEEP]
        before = len(self.finished)
        recording = self.flight.enabled
        if recording:
            m = self.metrics
            t0 = time.perf_counter()
            pre = (m["prefill_steps"], m["decode_dispatches"],
                   m["mixed_steps"], m["prefill_tokens"],
                   m["decode_tokens"], m["decode_dispatch_time_s"],
                   m["decode_host_time_s"], m["decode_host_overlap_s"],
                   m["preemptions"])
        self._admit()
        if not (self._can_mix() and self._run_mixed()):
            if self.prefilling:
                self._run_prefill()
            self._run_decode()
        if self.feedback is not None:
            # SLO feedback (sched/feedback.py): every interval window the
            # controller moves the mixed-dispatch prefill share one level
            # against the live TPOT burn. None (the default) = untouched.
            self.feedback.on_step(self)
        if recording:
            self._record_step(t0, pre)
        return self.finished[before:]

    def _record_step(self, t0: float, pre: tuple) -> None:
        """Append this step's flight record (O(1): one dict + ring slot).

        Dispatch kind derives from the PR 4 counters' deltas — ``mixed``
        for the unified ragged step, ``prefill+decode`` when the classic
        split path ran both dispatches, ``idle`` for a drain/admit-only
        step. Token counts follow the metrics dict's semantics: decode
        tokens book at window DRAIN, one window late under overlap."""
        m = self.metrics
        d_prefill = m["prefill_steps"] - pre[0]
        d_decode = m["decode_dispatches"] - pre[1]
        d_mixed = m["mixed_steps"] - pre[2]
        if d_mixed:
            kind = "mixed"
        elif d_prefill and d_decode:
            kind = "prefill+decode"
        elif d_prefill:
            kind = "prefill"
        elif d_decode:
            kind = "decode"
        else:
            kind = "idle"
        batch = len(self.decoding)
        # Per-class batch occupancy: who holds the decode slots this step
        # (the starvation picture /debug/steps is read for — a batch
        # flood squeezing interactive out shows up here first).
        classes: dict[str, int] = {}
        for r in self.decoding:
            label = class_name(r.priority)
            classes[label] = classes.get(label, 0) + 1
        rec = {
            "ts": round(time.time(), 6),
            "kind": kind,
            "classes": classes,
            "tokens": (m["prefill_tokens"] - pre[3]
                       + m["decode_tokens"] - pre[4]),
            "batch": batch,
            "occupancy": round(batch / self.ecfg.max_batch_slots, 4),
            "queue_depth": len(self.waiting) + len(self.prefilling),
            "kv_free_pages": self.kv.allocator.free_pages,
            "kv_utilization": round(self.kv.utilization(), 4),
            "dispatch_s": round(m["decode_dispatch_time_s"] - pre[5], 6),
            "host_s": round(m["decode_host_time_s"] - pre[6], 6),
            "overlap_s": round(m["decode_host_overlap_s"] - pre[7], 6),
            "wall_s": round(time.perf_counter() - t0, 6),
            "preemptions": m["preemptions"] - pre[8],
        }
        # Page transfers land BETWEEN steps (cross-replica pulls, disagg
        # handoffs, spill readmits run under the engine lock outside
        # step()), so these deltas are measured against the LAST RECORDED
        # step, not this step's start — otherwise every pull would be
        # invisible in /debug/steps.
        imported, exported = (m["kv_pages_imported"],
                              m["kv_pages_exported"])
        rec["kv_imported"] = imported - self._flight_kv_mark[0]
        rec["kv_exported"] = exported - self._flight_kv_mark[1]
        self._flight_kv_mark = (imported, exported)
        if self.replica_idx is not None:
            rec["replica"] = self.replica_idx
        self.flight.append(rec)

    def run_until_idle(self, max_steps: int = 100_000) -> list[EngineRequest]:
        done: list[EngineRequest] = []
        for _ in range(max_steps):
            if not self.has_work:
                break
            done.extend(self.step())
        return done

    def output_for(self, req: EngineRequest) -> EngineOutput:
        # Strip the stop token from the visible text.
        ids = req.all_out_ids  # includes tokens folded by preemption
        stop_ids = set(req.sampling.stop_token_ids) | {self.tokenizer.eos_id, self.tokenizer.eot_id}
        text_ids = ids[:-1] if ids and ids[-1] in stop_ids else ids
        text = self.tokenizer.decode(text_ids)
        if req.finish_reason == FinishReason.STOP_STRING:
            # OpenAI semantics: the matched stop sequence is NOT part of
            # the returned content (clients split on it).
            cut = min((i for i in (text.find(s)
                                   for s in req.sampling.stop_strings)
                       if i >= 0), default=-1)
            if cut >= 0:
                text = text[:cut]
        logprobs = None
        if req.sampling.logprobs:
            # OpenAI invariant: logprobs.content aligns 1:1 with the
            # tokens of message.content — entries for the stripped stop
            # token / cut stop-string tail must not leak through.
            logprobs = list(req.out_logprobs[: len(text_ids)])
            if req.finish_reason == FinishReason.STOP_STRING:
                # Byte-accurate trim via id_to_bytes: per-token decode()
                # would yield U+FFFD for multi-byte characters split
                # across tokens and miscount against the joint text.
                budget = len(text.encode("utf-8"))
                kept = acc = 0
                for e in logprobs:
                    acc += len(self.tokenizer.id_to_bytes(e["token_id"]))
                    if acc > budget:
                        break
                    kept += 1
                logprobs = logprobs[:kept]
        return EngineOutput(
            request_id=req.request_id,
            token_ids=list(ids),
            text=text,
            finish_reason=req.finish_reason or FinishReason.ABORTED,
            ttft_ms=req.ttft_ms,
            decode_tokens=req.num_generated,
            elapsed_s=time.perf_counter() - req.arrival_time,
            logprobs=logprobs,
            cached_tokens=req.cached_tokens,
        )
