"""Draft-model speculative decoding: a small in-family model drafts, the
target verifies (r3 VERDICT next #6).

Prompt-lookup speculation (``EngineCore._draft_for``) only accelerates
repetitive stretches; a real draft model (llama-3.2-1B drafting for 8B)
speculates on NOVEL text too. The engine's verify machinery is unchanged —
``_run_decode_spec`` accepts the agreeing prefix of ANY draft — this module
only produces better drafts:

- The worker keeps its own paged KV pool (own page size/pool — the draft's
  dims differ from the target's) and a per-request count of COMMITTED
  tokens whose K/V it has written.
- Each round, per request: (1) sync — feed committed tokens the draft has
  not seen (everything but the last) through the chunked prefill step;
  (2) draft — run ``k`` greedy decode steps in ONE ``_decode_multi``
  dispatch (on-device sampling loop, single host sync), starting from the
  last committed token.
- Speculative K/V written during drafting is position-addressed, so the
  next round's sync simply overwrites the slots of rejected tokens — the
  same recovery trick the target engine uses for its own rejected drafts.

TPU shape discipline: sync chunks pad to a fixed length and drafting is a
fixed-K scan, so the worker adds exactly two compiled programs per pool
geometry regardless of traffic.

No reference counterpart: RunbookAI calls hosted LLM APIs (SURVEY §2.2).
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from runbookai_tpu.engine.kv_cache import KVCacheManager


class DraftWorker:
    """Owns the draft model's params + KV pool; produces per-request drafts."""

    def __init__(
        self,
        cfg,
        params,
        max_batch_slots: int,
        max_seq_len: int,
        page_size: int = 16,
        num_pages: int = 1024,
        prefill_chunk: int = 256,
        block_pages: int = 16,
        attn_impl: str = "xla",
    ):
        self.cfg = cfg
        self.params = params
        self.ecfg_page_size = page_size
        self.prefill_chunk = prefill_chunk
        self.block_pages = block_pages
        self.attn_impl = attn_impl
        self.max_batch_slots = max_batch_slots
        dtype = params["embed"].dtype
        self.kv = KVCacheManager(
            n_layers=cfg.n_layers, num_pages=num_pages, page_size=page_size,
            n_kv_heads=cfg.n_kv_heads, head_dim=cfg.head_dim,
            max_seq_len=max_seq_len, dtype=dtype)
        self._kv_k = self.kv.pool.kv_k
        self._kv_v = self.kv.pool.kv_v
        # Committed tokens whose K/V the draft pool holds, per request.
        self.ctx: dict[str, int] = {}
        # Per-request padded table rows, keyed on the pool's table version:
        # rebuilding the O(max_pages) row every sync wave and draft round
        # is redundant host work unless pages actually moved.
        self._row_cache: dict[str, tuple[int, np.ndarray]] = {}
        # Requests the draft can no longer cover (pool pressure/length):
        # they fall back to prompt-lookup upstream.
        self.dead: set[str] = set()
        self.metrics = {"draft_time_s": 0.0, "draft_tokens": 0,
                        "draft_sync_tokens": 0}

    # ------------------------------------------------------------ lifecycle

    def release(self, request_id: str) -> None:
        self.ctx.pop(request_id, None)
        self.dead.discard(request_id)
        self._row_cache.pop(request_id, None)
        if request_id in self.kv.seqs:
            self.kv.release(request_id)

    def _ensure_pages(self, rid: str, need_tokens: int) -> bool:
        if need_tokens > self.kv.max_pages_per_seq * self.kv.page_size:
            return False
        if rid not in self.kv.seqs:
            self.kv.add_sequence(rid)
            self.ctx[rid] = 0
        if not self.kv.can_extend(rid, need_tokens):
            return False
        self.kv.extend(rid, need_tokens)
        return True

    # ----------------------------------------------------------------- sync

    def _trash_pos(self) -> int:
        return self.kv.max_pages_per_seq * self.kv.page_size

    def _table_row(self, rid: str) -> np.ndarray:
        hit = self._row_cache.get(rid)
        if hit is not None and hit[0] == self.kv.version:
            return hit[1]
        out = np.zeros((self.kv.max_pages_per_seq + 1,), dtype=np.int32)
        out[: self.kv.max_pages_per_seq] = self.kv.page_table_row(rid)
        self._row_cache[rid] = (self.kv.version, out)
        return out

    def _kill(self, rid: str) -> None:
        """Stop covering a request (pool/length pressure): free its pages
        so they serve other drafts; upstream falls back to prompt-lookup."""
        self.dead.add(rid)
        self._row_cache.pop(rid, None)
        if rid in self.kv.seqs:
            self.kv.release(rid)
        self.ctx.pop(rid, None)

    def _sync_batch(self, live: list[tuple[str, list[int]]]) -> None:
        """Write K/V for committed tokens the pool is missing (all but each
        request's last — the decode feed writes that one), in BATCHED
        chunk waves: one [B, chunk] dispatch serves every pending request
        rather than a padded dispatch per request per round."""
        from runbookai_tpu.engine.engine import _prefill_step

        t = self.prefill_chunk
        pending = [(rid, hist) for rid, hist in live
                   if self.ctx.get(rid, 0) < len(hist) - 1]
        while pending:
            rows = pending[: self.max_batch_slots]
            b = self.max_batch_slots  # fixed rows -> one compiled program
            tokens = np.zeros((b, t), dtype=np.int32)
            positions = np.full((b, t), self._trash_pos(), dtype=np.int32)
            tables = np.zeros((b, self.kv.max_pages_per_seq + 1),
                              dtype=np.int32)
            ctx_lens = np.ones((b,), dtype=np.int32)
            for i, (rid, hist) in enumerate(rows):
                start = self.ctx.get(rid, 0)
                chunk = hist[start : min(start + t, len(hist) - 1)]
                tokens[i, : len(chunk)] = chunk
                positions[i, : len(chunk)] = np.arange(start,
                                                       start + len(chunk))
                tables[i] = self._table_row(rid)
                ctx_lens[i] = start + len(chunk)
                self.metrics["draft_sync_tokens"] += len(chunk)
            _, self._kv_k, self._kv_v = _prefill_step(
                self.params, self.cfg, jnp.asarray(tokens), self._kv_k,
                self._kv_v, jnp.asarray(positions), jnp.asarray(tables),
                jnp.asarray(ctx_lens),
                np.zeros((b,), np.int32), jnp.zeros((b,), jnp.int32),
                page_size=self.kv.page_size, block_pages=self.block_pages,
                attn_impl=self.attn_impl,
            )
            for i, (rid, hist) in enumerate(rows):
                self.ctx[rid] = int(ctx_lens[i])
            pending = [(rid, hist) for rid, hist in pending
                       if self.ctx.get(rid, 0) < len(hist) - 1]

    # ---------------------------------------------------------------- draft

    def draft(self, reqs: list[tuple[str, list[int]]], k: int
              ) -> dict[str, list[int]]:
        """Draft up to ``k`` tokens per request with one batched dispatch.

        ``reqs`` pairs request ids with their COMMITTED token history
        (prompt + accepted output). Requests the pool cannot cover return
        no draft (upstream falls back to prompt-lookup).
        """
        from runbookai_tpu.engine.engine import _decode_multi

        t0 = time.perf_counter()
        live: list[tuple[int, str, list[int]]] = []
        for i, (rid, hist) in enumerate(reqs[: self.max_batch_slots]):
            if len(hist) < 1 or rid in self.dead:
                continue
            # Pages for the full committed history + k speculative slots,
            # BEFORE paying any sync dispatch: a request that cannot draft
            # must not sync forever under pool pressure.
            if not self._ensure_pages(rid, len(hist) + k):
                self._kill(rid)
                continue
            live.append((i, rid, hist))
        if not live:
            return {}
        self._sync_batch([(rid, hist) for _, rid, hist in live])

        b = self.max_batch_slots
        tokens = np.zeros((b, 1), dtype=np.int32)
        positions = np.zeros((b, 1), dtype=np.int32)
        ctx_lens = np.zeros((b,), dtype=np.int32)
        tables = np.zeros((b, self.kv.max_pages_per_seq + 1), dtype=np.int32)
        for i, rid, hist in live:
            tokens[i, 0] = hist[-1]
            positions[i, 0] = len(hist) - 1
            ctx_lens[i] = len(hist)
            tables[i] = self._table_row(rid)
        greedy = np.zeros((b,), dtype=np.float32)
        toks, self._kv_k, self._kv_v, _ = _decode_multi(
            self.params, self.cfg, jnp.asarray(tokens), jnp.asarray(positions),
            self._kv_k, self._kv_v, jnp.asarray(tables),
            jnp.asarray(ctx_lens), jnp.asarray(greedy),
            jnp.ones((b,), jnp.float32), jnp.zeros((b,), jnp.int32),
            jax.random.PRNGKey(0), jnp.zeros((b,), jnp.int32),
            page_size=self.kv.page_size, block_pages=self.block_pages,
            k_steps=k, attn_impl=self.attn_impl,
        )
        # runbook: noqa[RBK002] — sanctioned sync: one fetch per draft
        # round; the k drafted tokens ride back in a single transfer.
        toks_host = np.asarray(jax.device_get(toks))  # [B, k]
        out: dict[str, list[int]] = {}
        for i, rid, hist in live:
            out[rid] = [int(x) for x in toks_host[i]]
            self.metrics["draft_tokens"] += k
        self.metrics["draft_time_s"] += time.perf_counter() - t0
        return out
