"""Data-parallel engine fleet: N ``EngineCore`` replicas behind a
prefix-affinity router.

The serving comparison literature is unambiguous that above the engine, the
two highest-leverage pod-scale moves are (1) data-parallel replica scaling —
most of Gemma-on-TPU's pod throughput comes from replicas, not deeper model
sharding — and (2) prefix-cache-aware request routing across those replicas
(AIBrix, arXiv:2504.03648). This module is both:

- :func:`build_engine_fleet` constructs ``EngineConfig.dp_replicas``
  independent :class:`~runbookai_tpu.engine.engine.EngineCore` replicas,
  each pinned to a disjoint device slice of the dp axis
  (``parallel/mesh.replica_device_slices``). Replicas never communicate
  inside compiled programs — weights are replicated, KV pools are private —
  so the fleet scales the *data* axis of ``parallel/mesh.py`` without
  touching the TP/seq story within a replica. On CPU tier-1 the replicas
  land on the virtual mesh's devices (or share the default device when the
  platform exposes only one).

- :class:`AsyncFleet` fronts the replicas with the exact
  ``generate``/``generate_stream``/``start``/``stop``/``refresh_lora``
  surface of :class:`~runbookai_tpu.engine.async_engine.AsyncEngine`, so
  ``server/openai_api.py``, ``server/mcp.py``, the agent runtime and the
  eval suite all switch to a fleet behind the one-line config change
  ``EngineConfig.dp_replicas`` (``llm.dp_replicas`` in config files).

Routing policy (:meth:`AsyncFleet._route`): hash the prompt's full pages
once (``kv_cache.hash_blocks``) and probe every replica's
``KVCacheManager.match_prefix`` — requests sharing a system prompt land on
the replica already holding those pages, so agent iterations ride the
prefix cache instead of re-prefilling on a cold replica. Affinity is
load-guarded: a matching replica wins only while its live load stays
within ``affinity_load_slack`` of the least-loaded replica (a hot prefix
must not pile the whole pod onto one engine). With no usable match,
placement is least-loaded with a round-robin tiebreak. Overflow sheds
(``shed_queue_depth``) and a replica that aborts on pool pressure gets the
request retried on its siblings (``max_retries``).

Fleet-wide KV page sharing (``FleetConfig.kv_share``): KV pages are
location-addressable, not replica-private — when the placed replica holds
fewer of the prompt's prefix pages than a sibling, the router pulls the
missing pages from that sibling (host-staged copy on CPU; the same
export/import seam carries device-to-device transfers on TPU) instead of
re-prefilling them. Every pull is staleness-guarded per chain (the
export re-walks the planned chain with per-page token verification under
the source's engine lock) and digest-checked at import, so a pulled page
is byte-identical to recompute or it is not installed at all.

Prefill/decode disaggregation (``FleetConfig.disagg_prefill_replicas``):
the first N replicas form a prefill tier — prompts with enough full pages
prefill there via a 1-token warm request, the pages hand off to a
decode-tier replica through the same pull seam, and the request streams
entirely from the decode tier, so prompt bursts never sit in front of
decode dispatches (AIBrix, arXiv:2504.03648).

Per-request streams are byte-identical to the single-engine path: the
router only *chooses* a replica (and optionally pre-stages byte-identical
KV pages); the chosen ``AsyncEngine`` serves the request exactly as a
standalone engine would.
"""

from __future__ import annotations

import asyncio
import random
import threading
import time as _time
from contextvars import ContextVar
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

from runbookai_tpu.engine.async_engine import AsyncEngine
from runbookai_tpu.engine.engine import (
    LEGACY_COUNTER_EXPORTS,
    EngineConfig,
    EngineCore,
)
from runbookai_tpu.engine.kv_cache import hash_blocks
from runbookai_tpu.engine.request import (
    EngineOutput,
    FinishReason,
    FleetSaturated,
    SamplingParams,
)
from runbookai_tpu.sched import class_label
from runbookai_tpu.utils import metrics as metrics_mod
from runbookai_tpu.utils.trace import get_tracer

# Per-asyncio-task eval-case attribution: the eval runner sets this around
# each case (AsyncFleet.begin_case/end_case) and contextvars flow through
# awaits, so every engine call a case makes — however deep in the agent
# stack — is attributed to it without plumbing ids through the orchestrator.
CURRENT_CASE: ContextVar[Optional[str]] = ContextVar(
    "runbook_fleet_case", default=None)

# Bound on the routed-case attribution map: entries are popped by
# case_routes(); a caller that never collects them must not leak memory.
_CASE_ROUTES_MAX = 4096


@dataclass
class FleetConfig:
    """Router policy knobs (docs/SERVING.md)."""

    # Prefix-affinity placement on/off (off = pure least-loaded).
    affinity: bool = True
    # A prefix-matching replica may exceed the least-loaded replica's live
    # load by at most this many requests and still win placement. None =
    # one batch's worth (the replica's max_batch_slots): affinity is worth
    # at most one slot-generation of queueing, never a pile-up.
    affinity_load_slack: Optional[int] = None
    # Shed (synthetic abort / FleetSaturated, no submission) when EVERY
    # replica's waiting queue is at least this deep. None = never shed.
    shed_queue_depth: Optional[int] = None
    # Cross-replica retries when a replica aborts a request on pool
    # pressure. None = up to every other replica once.
    max_retries: Optional[int] = None
    # Fleet-wide KV page sharing: when the placed replica holds fewer of
    # the prompt's prefix pages than a sibling, pull the missing pages
    # from that sibling (digest-checked, chain-reverified host-staged copy)
    # before submitting, instead of re-prefilling them. Implied on by
    # disaggregation (the prefill→decode handoff IS a pull).
    kv_share: bool = False
    # Minimum full-page deficit (sibling's match minus the placed
    # replica's) worth a pull — below it, recompute is cheaper than the
    # two lock acquisitions + copy.
    kv_share_min_pages: int = 1
    # Prefill/decode disaggregation: dedicate the FIRST this-many replicas
    # to a prefill tier. Prompts with at least ``disagg_min_prompt_pages``
    # full pages prefill there (a 1-token warm request), their pages hand
    # off to a decode-tier replica, and the request streams entirely from
    # the decode tier — prompt bursts never sit in front of decode
    # dispatches. 0 = symmetric fleet (the classic router).
    disagg_prefill_replicas: int = 0
    # Prompts below this many full pages skip the prefill tier (the warm
    # round-trip would cost more than the tail prefill it saves).
    disagg_min_prompt_pages: int = 1
    # Cross-replica retry backoff: attempt k (1-based) waits
    # min(max, base * 2**(k-1)) scaled by seeded jitter in [0.5, 1.0)
    # before re-placing — an aborting replica's siblings see a spread-out
    # retry wave, not a synchronized stampede. 0 disables (the historical
    # immediate re-place). The jitter stream is seeded per fleet so a
    # soak's retry schedule is reproducible run over run.
    retry_backoff_base: float = 0.05
    retry_backoff_max: float = 2.0
    retry_jitter_seed: int = 0


@dataclass
class _Placement:
    """One routing decision: the chosen replica plus an optional page-pull
    plan (source replica and how many blocks the destination already
    holds). The plan's staleness is handled by the export itself: it
    re-walks the chain with per-page token verification under the
    source's engine lock, so planned pages that vanished since the probe
    simply export nothing."""

    idx: Optional[int]
    hashes: Optional[list[int]] = None
    pull_src: Optional[int] = None
    pull_dst_blocks: int = 0
    # Full pages the probe planned to pull (source match minus the
    # destination's). An export that lands SHORT of this count was
    # truncated between probe and copy — the mid-pull-preemption signal
    # the stale reason label attributes.
    pull_pages: int = 0


def split_engine_budget(engine_cfg: EngineConfig, dp: int) -> EngineConfig:
    """Per-replica EngineConfig from a fleet-TOTAL slot/page budget.

    The split is exact, never rounded UP past the total (a floor that
    rounded the per-replica pool up would hand a dp arm more aggregate
    pages than dp=1 and fake a win via fewer preemptions — the bench
    --dp arm's fixed-total-budget contract, and this helper's ONLY
    caller). Plan artifacts and the autotuner's measured arms carry
    PER-REPLICA slot/page budgets already (the llm.*/EngineConfig
    contract) and must never pass through this split.
    Allocator minimums: 1 slot, 2 pages per replica.
    """
    import dataclasses

    dp = max(1, dp)
    slots_per = max(1, engine_cfg.max_batch_slots // dp)
    return dataclasses.replace(
        engine_cfg, dp_replicas=dp, max_batch_slots=slots_per,
        num_pages=max(2, engine_cfg.num_pages // dp),
        # The host spill tier is per replica too: an unsplit value would
        # hand the dp arm dp× the aggregate host bytes (and spill
        # readmits) of the dp=1 arm — the exact fake-win this split
        # exists to prevent. 0 stays 0 (tier disabled).
        kv_spill_pages=engine_cfg.kv_spill_pages // dp,
        prefill_batch=max(1, min(engine_cfg.prefill_batch, slots_per)))


def _agg_utilization(cores: Sequence[EngineCore]) -> float:
    usable = sum(c.kv.allocator.num_pages - 1 for c in cores)
    used = sum(c.kv.pages_in_use for c in cores)
    return used / usable if usable > 0 else 0.0


def _agg_prefix_hit_ratio(cores: Sequence[EngineCore]) -> float:
    cached = sum(c.metrics.get("cached_prefix_tokens", 0) for c in cores)
    total = cached + sum(c.metrics.get("prefill_tokens", 0) for c in cores)
    return cached / total if total else 0.0


def _agg_overlap_ratio(cores: Sequence[EngineCore]) -> float:
    host = sum(c.metrics.get("decode_host_time_s", 0.0) for c in cores)
    overlap = sum(c.metrics.get("decode_host_overlap_s", 0.0)
                  for c in cores)
    return overlap / host if host > 0 else 0.0


def install_fleet_aggregates(cores: Sequence[EngineCore]) -> None:
    """Re-bind the unlabeled engine metric names to aggregates over
    ``cores`` — the fleet-wide truth an existing single-engine dashboard
    keeps reading. Last bind wins: a single fleet binds its own replicas
    here; a multi-model fleet calls this once more with the union of
    every group's cores so the process-wide names cover all groups."""
    cores = list(cores)
    reg = metrics_mod.get_registry()
    reg.gauge("runbook_running_requests",
              "Requests holding a decode slot").set_function(
        lambda: sum(len(c.decoding) for c in cores))
    reg.gauge("runbook_waiting_requests",
              "Requests queued or prefilling").set_function(
        lambda: sum(len(c.waiting) + len(c.prefilling) for c in cores))
    g_cls_wait = reg.gauge(
        "runbook_sched_waiting_requests",
        "Requests queued or prefilling, per priority class",
        labels=("cls",))
    g_cls_wait.clear_functions()
    for label in ("interactive", "batch", "other"):
        g_cls_wait.labels(cls=label).set_function(
            lambda lb=label: float(sum(
                1 for c in cores
                for r in list(c.waiting) + list(c.prefilling)
                if class_label(r.priority) == lb)))
    reg.gauge("runbook_kv_pages_total", "KV pool size in pages"
              ).set_function(
        lambda: sum(c.kv.allocator.num_pages for c in cores))
    reg.gauge("runbook_kv_pages_in_use",
              "KV pages referenced by live sequences").set_function(
        lambda: sum(c.kv.pages_in_use for c in cores))
    reg.gauge("runbook_kv_pages_cached",
              "Retired-but-resident prefix-cache pages").set_function(
        lambda: sum(c.kv.allocator.cached_pages for c in cores))
    reg.counter("runbook_kv_spill_pages_total",
                "KV pages captured into the host spill tier at "
                "eviction time").set_function(
        lambda: float(sum(c.kv.spill.pages_spilled for c in cores
                          if c.kv.spill)))
    reg.counter("runbook_kv_spill_evictions_total",
                "Spill-tier pages dropped by its LRU bound"
                ).set_function(
        lambda: float(sum(c.kv.spill.evictions for c in cores
                          if c.kv.spill)))
    reg.gauge("runbook_kv_pool_utilization",
              "Fraction of allocatable KV pages held by live sequences"
              ).set_function(lambda: _agg_utilization(cores))
    reg.gauge("runbook_prefix_cache_hit_ratio",
              "Cached prompt tokens / (cached + prefilled) since start"
              ).set_function(lambda: _agg_prefix_hit_ratio(cores))
    reg.gauge("runbook_decode_overlap_ratio",
              "Fraction of host decode work hidden behind device "
              "execution by the lagged pipeline (0 in forced-sync mode)"
              ).set_function(lambda: _agg_overlap_ratio(cores))
    for key, name, help_text in LEGACY_COUNTER_EXPORTS:
        reg.counter(name, help_text).set_function(
            lambda k=key: float(sum(c.metrics.get(k, 0) for c in cores)))


def build_engine_fleet(
    model_cfg,
    params,
    tokenizer,
    engine_cfg: Optional[EngineConfig] = None,
    *,
    mask_fn=None,
    advance_fn=None,
    seed: int = 0,
    tracer=None,
    lora_registry=None,
    draft_worker_factory: Optional[Callable[[int], Any]] = None,
    devices: Optional[Sequence[Any]] = None,
    replica_indices: Optional[Sequence[int]] = None,
    pin_devices: bool = False,
) -> list[EngineCore]:
    """Construct the fleet's ``EngineCore`` replicas.

    Each replica ``i`` gets ``replica_idx=i`` (request-id namespace
    ``r{i}-``) and — when the host exposes enough devices — its own
    single-slice mesh with the params replicated onto it, so its compiled
    steps and KV pool live entirely on its slice of the dp axis. With too
    few devices (single-device CPU), replicas share the default device:
    N independent engines whose dispatch loops interleave on it.

    ``replica_indices`` restricts construction to a subset of the global
    fleet — each pod host passes ``multihost.local_replica_range(dp)`` with
    ``devices=jax.local_devices()`` so replicas never span hosts.
    ``draft_worker_factory(i)`` builds a per-replica draft worker (one
    worker cannot serve two cores — its slot state is per-engine).
    ``pin_devices`` pins params/mesh to the computed slice even for a
    single-replica build — a multi-model fleet's dp=1 groups must each
    own THEIR device, not all share the default one.
    """
    import jax

    from runbookai_tpu.parallel.mesh import (
        build_mesh,
        replica_device_slices,
        replicated,
    )

    ecfg = engine_cfg or EngineConfig()
    dp = max(1, ecfg.dp_replicas)
    indices = list(replica_indices if replica_indices is not None
                   else range(dp))
    # Slices are computed over the replicas built HERE (this host's
    # share), positioned within the caller's device list — a pod host
    # building replicas [4, 8) of a dp=8 fleet owns slices 0..3 of its
    # jax.local_devices(), not (nonexistent) global offsets 4..7.
    slices = replica_device_slices(len(indices), devices=devices)
    if (len(indices) > 1 and slices[0] is None
            and jax.default_backend() in ("tpu", "axon")):
        # Single-device timesharing is the legitimate CPU tier-1 fleet;
        # on an accelerator it means dp was oversized for the slice —
        # "dp=8" results measured on one chip with 7 idle. Loud, not
        # fatal: a deliberately oversubscribed smoke run stays possible.
        import logging

        logging.getLogger(__name__).warning(
            "engine fleet: %d replicas but only %d local device(s) — "
            "all replicas will timeshare the default device",
            len(indices),
            len(devices) if devices is not None else len(jax.devices()))
    cores: list[EngineCore] = []
    for pos, i in enumerate(indices):
        mesh_i = None
        params_i = params
        if (dp > 1 or pin_devices) and slices[pos] is not None:
            mesh_i = build_mesh(devices=slices[pos])
            # DP means replicated weights: each replica's slice holds its
            # own copy, placed once here so per-dispatch transfers never
            # pay for it.
            params_i = jax.device_put(params, replicated(mesh_i))
        cores.append(EngineCore(
            model_cfg, params_i, tokenizer, ecfg,
            mask_fn=mask_fn, advance_fn=advance_fn, seed=seed,
            tracer=tracer, mesh=mesh_i, lora_registry=lora_registry,
            draft_worker=(draft_worker_factory(i)
                          if draft_worker_factory else None),
            replica_idx=i,
        ))
    return cores


class AsyncFleet:
    """AsyncEngine-compatible facade over N replicas + the router.

    ``model_label`` names the served model this fleet's metric series
    carry (``runbook_router_*{model=...}`` / ``runbook_replica_*``) —
    a multi-model fleet (``runbookai_tpu/fleet``) builds one AsyncFleet
    per model group, so the label is what separates the groups on a
    dashboard. Default: the model config's own name. ``clear_labeled``
    controls whether construction drops every existing labelset callback
    first (the single-fleet rebuild behavior); a multi-model builder
    clears once for its first group so sibling groups' bindings survive.
    """

    def __init__(self, cores: Sequence[EngineCore],
                 fleet_cfg: Optional[FleetConfig] = None,
                 model_label: Optional[str] = None,
                 clear_labeled: bool = True,
                 replica_factory: Optional[Callable[[int], EngineCore]]
                 = None):
        if not cores:
            raise ValueError("a fleet needs at least one EngineCore")
        self.cores = list(cores)
        self.model = (model_label
                      or getattr(cores[0].cfg, "name", None) or "default")
        self.replicas = [AsyncEngine(core) for core in self.cores]
        self.dp = len(self.cores)
        # GLOBAL replica ids for everything operator-facing (metric
        # labels, health rows, eval attribution): on a pod host building
        # replicas [4, 8) these must match the r{idx}- request prefixes
        # and trace records, not local list positions 0..3.
        self.replica_ids = [c.replica_idx if c.replica_idx is not None
                            else i for i, c in enumerate(self.cores)]
        self.cfg = fleet_cfg or FleetConfig()
        self._page_size = self.cores[0].ecfg.page_size
        slack = self.cfg.affinity_load_slack
        self._slack = (slack if slack is not None
                       else self.cores[0].ecfg.max_batch_slots)
        # Disaggregated tiers: GLOBAL replica ids [0, n) form the prefill
        # tier, the rest decode (global, not local list positions — a pod
        # host building replicas [2, 4) of a dp=4 fleet with one prefill
        # replica must see zero local prefill replicas, not dedicate its
        # own replica 2). Every request STREAMS from a decode-tier
        # replica; the prefill tier only runs warm prefills whose pages
        # hand off. A split that leaves this fleet no decode tier is
        # refused — it would place every request nowhere.
        n_pf = max(0, self.cfg.disagg_prefill_replicas)
        self._prefill_tier = [i for i, g in enumerate(self.replica_ids)
                              if g < n_pf]
        self._decode_tier = [i for i, g in enumerate(self.replica_ids)
                             if g >= n_pf]
        if n_pf and not self._decode_tier:
            raise ValueError(
                f"disagg_prefill_replicas={n_pf} leaves no decode tier "
                f"in this fleet (replicas {self.replica_ids})")
        # The handoff IS a pull, so disaggregation forces page sharing on.
        self._kv_share = bool(self.cfg.kv_share or n_pf)
        # Router state below is mutated ONLY under this lock (routing runs
        # on event-loop threads and, for bench/eval drivers, possibly
        # several of them).
        self._lock = threading.Lock()
        self._routed = [0] * self.dp
        self._rr = 0
        self._affinity_hits = 0
        self._case_routes: dict[str, dict[int, int]] = {}
        # Supervision (runbookai_tpu/chaos): quarantined LOCAL replica
        # positions are excluded from routing (placement AND pull
        # sources) until the supervisor rejoins them. Replaced as a
        # whole frozenset under self._lock; racy reads see either the
        # old or new set — the same one-step-stale contract as the load
        # reads.
        self._quarantined: frozenset[int] = frozenset()
        # Online rebuild: a caller-supplied factory (global replica id ->
        # fresh EngineCore on that replica's device slice); None falls
        # back to cloning the dead core's construction inputs.
        self.replica_factory = replica_factory
        # Hook re-running any wrapper's metric bindings after a rebuild
        # swaps a core (fleet/multimodel re-unions its rollups here).
        self._rebuild_listener: Optional[Callable[[], None]] = None
        # Attach points read by /healthz and `runbook chaos status`:
        # the fleet supervisor (chaos/supervisor.py) and the fault
        # injector (chaos/inject.py) publish their snapshots through
        # health_snapshot when present.
        self.supervisor = None
        self.chaos = None
        # Fault-injection seam on the page-pull path: applied to the
        # ExportedPages payload INSIDE the export worker thread (a delay
        # or corruption must never block the event loop).
        self.chaos_pull_hook = None
        # Seeded jitter stream for retry backoff (drawn under _lock).
        self._retry_rng = random.Random(self.cfg.retry_jitter_seed)
        self._install_metrics(clear=clear_labeled)

    # ------------------------------------------------------------- routing

    def _live_load(self, core: EngineCore) -> int:
        """Live slots + queue depth (racy read of the engine's pools —
        at worst one step stale, same contract as the scrape gauges)."""
        return (len(core.waiting) + len(core.prefilling)
                + len(core.decoding))

    def _hash_seed(self, adapter: Optional[str]) -> int:
        """Prefix-cache namespace of the request (LoRA adapter row)."""
        if adapter is None:
            return 0
        lora = self.cores[0].lora
        if lora is None:
            return 0
        try:
            return lora.index_of(adapter)
        except Exception:  # noqa: BLE001 — unknown adapter errors at submit
            return 0

    def _route(self, prompt_ids: list[int], hash_seed: int = 0,
               exclude: frozenset[int] = frozenset(),
               trace_id: Optional[str] = None) -> _Placement:
        """Pick a replica: prefix affinity under a load guard, else
        least-loaded with round-robin tiebreak. ``idx=None`` = shed.

        Placement is restricted to the decode tier under disaggregation;
        with kv_share on, every replica (both tiers) is additionally
        probed as a page-pull SOURCE, and a sibling holding at least
        ``kv_share_min_pages`` more of the prompt's prefix than the
        placed replica yields a pull plan the caller executes before
        submit. ``trace_id`` (the caller's x-request-id) rides into the
        ``router.place`` trace event so a request timeline can show
        WHERE the router put it and WHY (affinity vs least-loaded) —
        routing runs on the event-loop thread, where the server
        handler's per-thread tracer context is not visible."""
        probe = (self.cfg.affinity or self._kv_share) \
            and len(prompt_ids) >= self._page_size
        hashes = None
        if probe:
            hashes = hash_blocks(
                prompt_ids, self._page_size,
                max_blocks=(len(prompt_ids) - 1) // self._page_size,
                seed=hash_seed)
        # (idx, matched, load, queue_depth): load is the full live count
        # (waiting + prefilling + decoding); queue_depth is the not-yet-
        # decoding backlog — the tiebreak between equally-loaded replicas
        # (two replicas both at load 8 are NOT equal when one has 8
        # decoding and the other 8 queued behind a long prefill).
        candidates: list[tuple[int, int, int, int]] = []
        sources: list[tuple[int, int]] = []  # (idx, matched)
        quarantined = self._quarantined  # one racy read per decision
        for i, core in enumerate(self.cores):
            if i in exclude or i in quarantined:
                # Quarantined replicas (supervisor failover) serve
                # nothing: not placement, not pull sources — a dead
                # core's pages cannot be trusted mid-rebuild.
                continue
            matched = (core.kv.match_prefix(prompt_ids, hashes=hashes,
                                            hash_seed=hash_seed)
                       if hashes else 0)
            if i in self._decode_tier:
                depth = len(core.waiting) + len(core.prefilling)
                candidates.append((i, matched, self._live_load(core),
                                   depth))
                # The depth the router actually saw for this decision —
                # a stored gauge, so a dashboard can join placement
                # choices against the backlog they were made under.
                # runbook: noqa[RBK010] — model/replica labels: configured
                # group name + pinned replica ids, fixed at fleet build.
                self._m_depth.labels(
                    model=self.model,
                    replica=str(self.replica_ids[i])).set(depth)
            if self._kv_share and matched:
                sources.append((i, matched))
        if not candidates:
            return _Placement(idx=None)
        min_load = min(load for _, _, load, _ in candidates)
        if (self.cfg.shed_queue_depth is not None
                and all(len(self.cores[i].waiting) >= self.cfg.shed_queue_depth
                        for i, _, _, _ in candidates)):
            self._m_shed.inc()
            shed_meta = {"dp": self.dp}
            if trace_id is not None:
                shed_meta["trace_id"] = trace_id
            get_tracer().event("router.shed", **shed_meta)
            return _Placement(idx=None)
        # kv_share probes matches even with affinity routing off — the
        # matches then only plan pulls, never placement.
        affine = ([c for c in candidates
                   if c[1] >= self._page_size
                   and c[2] <= min_load + self._slack]
                  if self.cfg.affinity else [])
        with self._lock:
            if affine:
                pick, _matched, _load, _depth = max(
                    affine, key=lambda c: (c[1], -c[2]))
                self._affinity_hits += 1
                self._m_affinity.inc()
            else:
                # Queue-depth-aware least-loaded: load ties break on the
                # waiting+prefilling backlog first (the replica whose
                # live count is decode-heavy starts this request sooner
                # than one with the same count queued), then round-robin
                # so a cold fleet spreads a burst instead of flooding
                # replica 0.
                tied = [c for c in candidates if c[2] == min_load]
                min_depth = min(c[3] for c in tied)
                tied_ids = [c[0] for c in tied if c[3] == min_depth]
                pick = min(tied_ids, key=lambda i: (i - self._rr) % self.dp)
                self._rr = (pick + 1) % self.dp
            self._routed[pick] += 1
            case = CURRENT_CASE.get()
            if case is not None and (case in self._case_routes
                                     or len(self._case_routes)
                                     < _CASE_ROUTES_MAX):
                # The cap bounds NEW entries only: a case already being
                # tracked keeps counting, or its attribution would silently
                # undercount mid-flight.
                per = self._case_routes.setdefault(case, {})
                gid = self.replica_ids[pick]
                per[gid] = per.get(gid, 0) + 1
        # runbook: noqa[RBK010] — model/replica labels: configured
        # group name + pinned replica ids, fixed at fleet build.
        self._m_requests.labels(
            model=self.model, replica=str(self.replica_ids[pick])).inc()
        tracer = get_tracer()
        if tracer.enabled:
            meta = {"replica": self.replica_ids[pick],
                    "affinity": bool(affine)}
            if trace_id is not None:
                meta["trace_id"] = trace_id
            tracer.event("router.place", **meta)
        placement = _Placement(idx=pick, hashes=hashes)
        if sources:
            # Page-pull plan: the richest sibling beats the placed
            # replica's own match by at least kv_share_min_pages full
            # pages → pull the deficit before submit. The export
            # re-validates the chain under the source's engine lock, so
            # a plan outdated by eviction degrades to recompute there.
            dst_matched = next((m for i, m, _, _ in candidates
                                if i == pick), 0)
            src, src_matched = max(
                ((i, m) for i, m in sources if i != pick),
                key=lambda s: s[1], default=(None, 0))
            deficit = (src_matched - dst_matched) // self._page_size
            if src is not None and deficit >= max(
                    1, self.cfg.kv_share_min_pages):
                placement.pull_src = src
                placement.pull_dst_blocks = dst_matched // self._page_size
                placement.pull_pages = deficit
        return placement

    # -------------------------------------------------- page pull / disagg

    async def _execute_pull(self, placement: _Placement,
                            prompt_ids: list[int], hash_seed: int,
                            trace_id: Optional[str] = None) -> int:
        """Run a planned page pull: export from the source replica (under
        its engine lock, chain-reverified) and import into the placed
        replica (under its lock, digest-checked). Both halves run in
        worker threads — the event loop (and every live stream) stays
        free. A stale plan (pages evicted since the probe) or full
        destination pool degrades to recompute; the request is submitted
        either way. Returns pages pulled.

        Staleness is attributed per failure mode
        (``runbook_router_xreplica_stale_total{reason=}``):
        ``epoch_moved`` — the under-lock chain re-walk found NOTHING (the
        planned pages were evicted/re-registered since the probe);
        ``mid_pull_preempt`` — the export landed SHORT of the planned
        deficit (the chain truncated while the pull was in flight; the
        partial prefix still installs); ``digest_mismatch`` — the import
        rejected a corrupted payload block."""
        dst, src = placement.idx, placement.pull_src
        t0 = _time.perf_counter()
        exported = await self.replicas[src].run_locked(
            lambda: self.cores[src].export_kv_pages(
                prompt_ids, hashes=placement.hashes, hash_seed=hash_seed,
                skip_blocks=placement.pull_dst_blocks))
        if exported is None:
            self._m_stale["epoch_moved"].inc()
            return 0
        hook = self.chaos_pull_hook
        if hook is not None:
            # Fault injection on the in-transit payload (chaos/inject.py:
            # d2d delay / corruption). Runs in a worker thread with NO
            # engine lock held — a delayed pull stalls only this
            # request, never a step loop or the event loop.
            exported = await asyncio.to_thread(hook, exported)

        def _import() -> tuple[int, bool]:
            core = self.cores[dst]
            n = core.import_kv_pages(exported)
            # Both reads under the destination's engine lock: the flag
            # belongs to exactly this import call.
            return n, core.kv.last_import_digest_mismatch

        pulled, digest_bad = await self.replicas[dst].run_locked(_import)
        # ONE reason per pull (stale_rejections() sums the labels, so a
        # pull that both truncated AND hit a bad digest must not count
        # twice): corruption outranks truncation as the thing to page on.
        if digest_bad:
            self._m_stale["digest_mismatch"].inc()
        elif placement.pull_pages \
                and exported.num_pages < placement.pull_pages:
            self._m_stale["mid_pull_preempt"].inc()
        elapsed = _time.perf_counter() - t0
        if pulled:
            self._m_xreplica_hits.inc()
            self._m_xreplica_pages.inc(pulled)
            self._m_xreplica_seconds.inc(elapsed)
        tracer = get_tracer()
        if tracer.enabled:
            # The timeline's pull span: destination + SOURCE replica,
            # pages moved, the wall it cost, and the OWNING CHAIN id —
            # the tail block hash of the pulled prefix chain (chained
            # hashing makes it identify the whole prefix), so repeated
            # pulls of one hot conversation join up across timelines.
            chain = (exported.hashes[-1] if exported.hashes
                     else (placement.hashes[-1] if placement.hashes
                           else 0))
            meta = {"replica": self.replica_ids[dst],
                    "src": self.replica_ids[src], "pages": pulled,
                    "chain": f"{chain & 0xFFFFFFFFFFFFFFFF:016x}",
                    "pull_ms": round(elapsed * 1e3, 3)}
            if trace_id is not None:
                meta["trace_id"] = trace_id
            tracer.event("router.page_pull", **meta)
        return pulled

    def shed_total(self) -> int:
        """Requests this fleet shed (every replica over
        ``shed_queue_depth``) — the public accessor the incident
        detector's ``router_shed`` delta signal reads
        (obs/incident.py), so detection never touches the private
        metric child."""
        return int(self._m_shed.value)

    def stale_rejections(self) -> int:
        """Total stale-pull count across reasons for THIS fleet's model
        label (the /healthz ``kv_share.stale_rejections`` figure): pulls
        whose PLAN was not fully honored — at most one count per pull. A
        ``mid_pull_preempt`` entry still installed its partial prefix;
        the per-reason breakdown separates those from true no-page
        rejections."""
        return int(sum(child.value for child in self._m_stale.values()))

    def _full_pages(self, prompt_ids: list[int]) -> int:
        """Full prefix pages a prompt can publish ((len-1)//page_size —
        the engine always prefills at least the last token itself)."""
        return max(0, (len(prompt_ids) - 1) // self._page_size)

    async def _disagg_warm(self, prompt_ids: list[int], hash_seed: int,
                           adapter: Optional[str],
                           trace_id: Optional[str]) -> Optional[int]:
        """Prefill ``prompt_ids`` on the prefill tier: a greedy 1-token
        warm request on the least-loaded prefill replica computes and
        publishes the prompt's full pages, which then hand off to the
        decode replica at first-token time (the pull in generate /
        generate_stream). Returns the warm replica, or None when the
        prompt is too short to be worth the round-trip."""
        if not self._prefill_tier \
                or self._full_pages(prompt_ids) \
                < max(1, self.cfg.disagg_min_prompt_pages):
            return None
        pick = min(self._prefill_tier,
                   key=lambda i: self._live_load(self.cores[i]))
        warm = SamplingParams(temperature=0.0, max_new_tokens=1,
                              stop_token_ids=())
        try:
            out = await self.replicas[pick].generate(
                prompt_ids, warm, adapter=adapter,
                request_id=(f"{trace_id}-warm" if trace_id else None))
        except Exception:  # noqa: BLE001 — a sick prefill tier must not
            return None    # fail the request; decode tier recomputes
        if out.finish_reason is FinishReason.ABORTED:
            return None  # prefill pool pressure — recompute on decode tier
        # runbook: noqa[RBK010] — model/replica labels: configured
        # group name + pinned replica ids, fixed at fleet build.
        self._m_warm.labels(model=self.model,
                            replica=str(self.replica_ids[pick])).inc()
        return pick

    # ----------------------------------------------------- AsyncEngine API

    async def start(self) -> None:
        for replica in self.replicas:
            await replica.start()

    async def stop(self) -> None:
        await asyncio.gather(*(r.stop() for r in self.replicas))

    async def refresh_lora(self) -> None:
        await asyncio.gather(*(r.refresh_lora() for r in self.replicas))

    def _shed_output(self, request_id: Optional[str]) -> EngineOutput:
        return EngineOutput(
            request_id=request_id or "shed", token_ids=[], text="",
            finish_reason=FinishReason.ABORTED, ttft_ms=None,
            decode_tokens=0, elapsed_s=0.0)

    async def generate(
        self,
        prompt_ids: list[int],
        sampling: Optional[SamplingParams] = None,
        timeout_s: Optional[float] = None,
        priority: int = 0,
        adapter: Optional[str] = None,
        request_id: Optional[str] = None,
    ) -> EngineOutput:
        """Route, then delegate to the chosen replica's ``generate``.

        A replica aborting the request (admission fail-fast / pool
        pressure) triggers a retry on its siblings — one replica's full
        pool must not 503 a pod with idle capacity elsewhere. Timeouts
        propagate without retry: the caller's budget is already spent.
        """
        retries = (self.cfg.max_retries if self.cfg.max_retries is not None
                   else self.dp - 1)
        # The TTFT clock starts HERE: warm prefills and page pulls below
        # are part of the first token's latency, so they ride inside the
        # arrival time the replica's EngineRequest is backdated to.
        t_arrival = _time.perf_counter()
        hash_seed = self._hash_seed(adapter)
        if self._prefill_tier and not self.is_saturated():
            # Disaggregation: the heavy prefill runs on the prefill tier
            # first; its pages hand off through the pull below, so the
            # decode replica prefills only the sub-page tail. A saturated
            # fleet skips the warm — the most expensive work in the
            # system must not run for a request about to be shed.
            await self._disagg_warm(prompt_ids, hash_seed, adapter,
                                    request_id)
        tried: set[int] = set()  # decode-tier picks that aborted
        out: Optional[EngineOutput] = None
        for attempt in range(retries + 1):
            if attempt:
                # Bounded exponential backoff with seeded jitter BEFORE
                # re-placing: the sibling that absorbs a failed-over
                # request gets a beat to drain, and concurrent retries
                # de-synchronize instead of stampeding one replica.
                # Sleeping cannot change tokens — retry byte-identity is
                # regression-pinned in tests/test_fleet.py.
                await self._retry_backoff(attempt)
            placement = self._route(prompt_ids, hash_seed,
                                    exclude=frozenset(tried),
                                    trace_id=request_id)
            idx = placement.idx
            if idx is None:
                break
            if attempt:
                self._m_retries.inc()
            if placement.pull_src is not None:
                await self._execute_pull(placement, prompt_ids, hash_seed,
                                         trace_id=request_id)
            out = await self.replicas[idx].generate(
                prompt_ids, sampling, timeout_s=timeout_s,
                priority=priority, adapter=adapter, request_id=request_id,
                arrival_time=t_arrival)
            if out.finish_reason is not FinishReason.ABORTED:
                return out
            tried.add(idx)
        return out if out is not None else self._shed_output(request_id)

    async def generate_stream(
        self,
        prompt_ids: list[int],
        sampling: Optional[SamplingParams] = None,
        priority: int = 0,
        adapter: Optional[str] = None,
        request_sink: Optional[list] = None,
        request_id: Optional[str] = None,
    ):
        """Route, then yield the chosen replica's token stream.

        Failover happens only BEFORE the first token: a replica that
        aborts the request without yielding anything (pool pressure, a
        crash's failover sweep) is retried on its siblings with the same
        backoff as :meth:`generate` — the caller's stream just starts a
        beat later, byte-identical. Once a token has been yielded it
        cannot be unsaid, so a mid-stream abort ends the stream with the
        request's ABORTED state (the HTTP layer turns that into a clean
        SSE error event) instead of hanging or silently truncating.
        Shedding raises :class:`FleetSaturated`."""
        t_arrival = _time.perf_counter()  # TTFT includes warm + pull
        hash_seed = self._hash_seed(adapter)
        if self._prefill_tier and not self.is_saturated():
            await self._disagg_warm(prompt_ids, hash_seed, adapter,
                                    request_id)
        retries = (self.cfg.max_retries if self.cfg.max_retries is not None
                   else self.dp - 1)
        tried: set[int] = set()
        for attempt in range(retries + 1):
            if attempt:
                await self._retry_backoff(attempt)
            placement = self._route(prompt_ids, hash_seed,
                                    exclude=frozenset(tried),
                                    trace_id=request_id)
            idx = placement.idx
            if idx is None:
                raise FleetSaturated(
                    f"all {self.dp} replicas over shed_queue_depth="
                    f"{self.cfg.shed_queue_depth} or quarantined")
            if attempt:
                self._m_retries.inc()
            if placement.pull_src is not None:
                await self._execute_pull(placement, prompt_ids, hash_seed,
                                         trace_id=request_id)
            # The replica appends its EngineRequest to the sink when the
            # stream starts; a private sink keeps failed-over attempts'
            # entries out of the caller's view until they actually serve.
            sink: list = []

            def mirror() -> None:
                if request_sink is not None and sink \
                        and (not request_sink
                             or request_sink[-1] is not sink[0]):
                    request_sink.append(sink[0])

            agen = self.replicas[idx].generate_stream(
                prompt_ids, sampling, priority=priority, adapter=adapter,
                request_sink=sink, request_id=request_id,
                arrival_time=t_arrival)
            yielded = False
            try:
                async for tok in agen:
                    mirror()
                    yielded = True
                    yield tok
            finally:
                # `async for` abandons (never closes) its iterator on
                # early exit; close explicitly so the replica's
                # early-exit abort (slot + KV pages freed) runs NOW,
                # not at GC time.
                await agen.aclose()
            mirror()
            req = sink[0] if sink else None
            if (not yielded and req is not None
                    and req.finish_reason is FinishReason.ABORTED
                    and attempt < retries):
                # Nothing reached the caller: fail over to a sibling —
                # the stream the caller finally sees is byte-identical
                # to an untroubled placement. The serving attempt's
                # request (not this aborted one) is what lands in the
                # caller's request_sink.
                if request_sink is not None and request_sink \
                        and request_sink[-1] is req:
                    request_sink.pop()
                tried.add(idx)
                continue
            return

    # ------------------------------------------------- retry backoff

    async def _retry_backoff(self, attempt: int) -> None:
        """Sleep the bounded-exponential, seeded-jitter backoff for retry
        ``attempt`` (1-based) and observe it into
        ``runbook_router_retry_backoff_seconds``. 0-base disables."""
        base = self.cfg.retry_backoff_base
        if base <= 0:
            return
        raw = min(self.cfg.retry_backoff_max,
                  base * (2 ** (attempt - 1)))
        with self._lock:
            jitter = self._retry_rng.random()
        delay = raw * (0.5 + 0.5 * jitter)
        self._m_backoff.observe(delay)
        await asyncio.sleep(delay)

    # ------------------------------------------- supervision / rebuild

    def quarantine(self, idx: int) -> None:
        """Remove LOCAL replica position ``idx`` from routing (placement
        and pull sources). Idempotent; the supervisor calls this the
        moment a replica is declared failed."""
        with self._lock:
            self._quarantined = self._quarantined | {idx}

    def unquarantine(self, idx: int) -> None:
        with self._lock:
            self._quarantined = self._quarantined - {idx}

    def quarantined_replicas(self) -> list[int]:
        """GLOBAL replica ids currently out of routing."""
        return sorted(self.replica_ids[i] for i in self._quarantined)

    def available_replicas(self) -> int:
        """Decode-tier replicas currently accepting placements."""
        quarantined = self._quarantined
        return sum(1 for i in self._decode_tier if i not in quarantined)

    def failing_over(self) -> bool:
        """True while NO decode-tier replica accepts placements (every
        one quarantined mid-failover): the HTTP layer answers 503 with
        Retry-After instead of burning a shed on a request that cannot
        be placed."""
        return self.available_replicas() == 0

    def _default_replica_factory(self, old: EngineCore) -> EngineCore:
        """Rebuild an EngineCore from the dead core's own construction
        inputs: same model/engine config, the SAME param tree (already
        resident on the replica's device slice — nothing re-uploads),
        same mesh, guided hooks, LoRA registry, tracer, seed and replica
        index. The draft worker is NOT rebuilt (its slot state died with
        the core; speculation resumes only through an explicit
        ``replica_factory``)."""
        params = old.params
        if old.lora is not None:
            # EngineCore re-stacks the registry's adapters itself; the
            # dead core's params carry its stale stacked copy.
            params = {k: v for k, v in params.items() if k != "lora"}
        return EngineCore(
            old.cfg, params, old.tokenizer, old.ecfg,
            mask_fn=old.mask_fn, advance_fn=old.advance_fn,
            seed=old.seed, tracer=old.tracer, mesh=old.mesh,
            lora_registry=old.lora, replica_idx=old.replica_idx)

    def rebuild_replica(self, idx: int) -> EngineCore:
        """Online replica rebuild: tear down LOCAL position ``idx``'s
        engine and construct a fresh one on the same device slice, as a
        first-class runtime operation. The caller (the supervisor) has
        already quarantined the replica and failed over its in-flight
        requests; this swaps the core + AsyncEngine pair under the
        router lock, re-binds the per-replica metric callbacks to the
        new core, and notifies any wrapping fleet (multi-model rollups)
        so no scrape keeps reading the dead engine. The replica remains
        quarantined — rejoining is the supervisor's hysteresis call."""
        old_replica = self.replicas[idx]
        old_core = self.cores[idx]
        # The old loop must exit when (if) it ever wakes: a wedged step
        # thread finishing hours later must find a stopped engine, not
        # re-enter scheduling on an abandoned core.
        old_replica._stopped = True
        factory = self.replica_factory or (
            lambda _gid: self._default_replica_factory(old_core))
        new_core = factory(self.replica_ids[idx])
        with self._lock:
            self.cores[idx] = new_core
            self.replicas[idx] = AsyncEngine(new_core)
        # Re-point every per-replica labeled callback and the unlabeled
        # aggregates at the live core list (the previous bindings hold
        # the dead core). clear=False: sibling labelsets stay bound.
        self._install_metrics(clear=False)
        if self._rebuild_listener is not None:
            self._rebuild_listener()
        return new_core

    # -------------------------------------------------- eval attribution

    def begin_case(self, case_id: str):
        """Attribute subsequent routing in this asyncio task (and its
        awaited children) to ``case_id``; returns the reset token."""
        return CURRENT_CASE.set(case_id)

    def end_case(self, token) -> None:
        CURRENT_CASE.reset(token)

    def case_routes(self, case_id: str) -> dict[int, int]:
        """Pop {replica: request_count} attributed to a finished case."""
        with self._lock:
            return self._case_routes.pop(case_id, {})

    # --------------------------------------------------------- observability

    def routed_counts(self) -> list[int]:
        with self._lock:
            return list(self._routed)

    def _imbalance(self) -> float:
        with self._lock:
            routed = list(self._routed)
        total = sum(routed)
        if total == 0:
            return 0.0
        return max(routed) / (total / len(routed))

    def affinity_hit_ratio(self) -> float:
        with self._lock:
            hits, total = self._affinity_hits, sum(self._routed)
        return hits / total if total else 0.0

    def _install_metrics(self, clear: bool = True) -> None:
        """Router metrics + per-replica labeled gauges — every series
        carries the fleet's ``model`` label so a multi-model deployment
        separates its groups with plain PromQL — and the unlabeled
        engine names re-bound to cross-replica aggregates so an existing
        dashboard keeps reading fleet-wide truth. With ``clear``, labeled
        callbacks are dropped first: a larger previous fleet's stale
        replica labelsets must not keep scraping dead engines."""
        reg = metrics_mod.get_registry()
        model = self.model
        self._m_requests = reg.counter(
            "runbook_router_requests_total",
            "Requests placed by the fleet router",
            labels=("model", "replica"))
        # runbook: noqa[RBK010] — model label: configured group
        # name, fixed at fleet build.
        self._m_affinity = reg.counter(
            "runbook_router_affinity_hits_total",
            "Placements onto a replica already holding the request's "
            "prefix pages (>= one full page matched)",
            labels=("model",)).labels(model=model)
        # runbook: noqa[RBK010] — model label: configured group
        # name, fixed at fleet build.
        self._m_retries = reg.counter(
            "runbook_router_retries_total",
            "Cross-replica retries after a replica aborted on pool "
            "pressure", labels=("model",)).labels(model=model)
        # runbook: noqa[RBK010] — model label: configured group
        # name, fixed at fleet build.
        self._m_backoff = reg.histogram(
            "runbook_router_retry_backoff_seconds",
            "Seeded-jitter exponential backoff slept before each "
            "cross-replica retry re-place",
            buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                     2.0, 5.0),
            labels=("model",)).labels(model=model)
        # runbook: noqa[RBK010] — model label: configured group
        # name, fixed at fleet build.
        self._m_shed = reg.counter(
            "runbook_router_shed_total",
            "Requests shed with every replica over shed_queue_depth",
            labels=("model",)).labels(model=model)
        # Fleet-wide KV page sharing (docs/observability.md): pulls that
        # landed pages, pages moved, wall spent moving them, and pulls
        # whose planned pages were gone by export time.
        # runbook: noqa[RBK010] — model label: configured group
        # name, fixed at fleet build.
        self._m_xreplica_hits = reg.counter(
            "runbook_router_xreplica_hits_total",
            "Placements whose prefix pages were pulled from a sibling "
            "replica instead of re-prefilled",
            labels=("model",)).labels(model=model)
        # runbook: noqa[RBK010] — model label: configured group
        # name, fixed at fleet build.
        self._m_xreplica_pages = reg.counter(
            "runbook_router_xreplica_pages_pulled_total",
            "KV pages pulled across replicas (cross-replica prefix hits "
            "+ prefill-tier handoffs)",
            labels=("model",)).labels(model=model)
        # runbook: noqa[RBK010] — model label: configured group
        # name, fixed at fleet build.
        self._m_xreplica_seconds = reg.counter(
            "runbook_router_xreplica_pull_seconds_total",
            "Wall seconds spent exporting+importing pulled KV pages",
            labels=("model",)).labels(model=model)
        # Stale pulls with a BOUNDED failure-mode label: epoch_moved
        # (chain gone at export), mid_pull_preempt (chain truncated
        # mid-pull — partial prefix still lands), digest_mismatch
        # (corrupted payload rejected at import).
        m_stale = reg.counter(
            "runbook_router_xreplica_stale_total",
            "Planned pulls that fell short of their plan, by reason: the "
            "under-lock export re-walk found nothing (epoch_moved), the "
            "chain truncated mid-pull (mid_pull_preempt), or the import "
            "rejected a corrupted block (digest_mismatch)",
            labels=("model", "reason"))
        self._m_stale = {
            # runbook: noqa[RBK010] — model label: configured group
            # name, fixed at fleet build (reason is the literal tuple).
            reason: m_stale.labels(model=model, reason=reason)
            for reason in ("epoch_moved", "mid_pull_preempt",
                           "digest_mismatch")}
        self._m_warm = reg.counter(
            "runbook_router_prefill_tier_warms_total",
            "Disaggregated prefill-tier warm prefills",
            labels=("model", "replica"))
        # Stored-value gauge (not a callback): the waiting+prefilling
        # depth each candidate replica showed at the LAST routing
        # decision — joins placements against the backlog they saw.
        self._m_depth = reg.gauge(
            "runbook_router_observed_queue_depth",
            "Waiting+prefilling depth per replica as observed by the "
            "router at its most recent placement",
            labels=("model", "replica"))
        g_imbalance = reg.gauge(
            "runbook_router_imbalance_ratio",
            "Max over mean of per-replica routed request counts "
            "(1.0 = perfectly balanced, dp = everything on one replica)",
            labels=("model",))
        per_replica = (
            (reg.gauge("runbook_replica_running_requests",
                       "Requests holding a decode slot, per fleet replica",
                       labels=("model", "replica")),
             lambda c: float(len(c.decoding))),
            (reg.gauge("runbook_replica_waiting_requests",
                       "Requests queued or prefilling, per fleet replica",
                       labels=("model", "replica")),
             lambda c: float(len(c.waiting) + len(c.prefilling))),
            (reg.gauge("runbook_replica_kv_pool_utilization",
                       "Fraction of allocatable KV pages held by live "
                       "sequences, per fleet replica",
                       labels=("model", "replica")),
             lambda c: c.kv.utilization()),
            (reg.counter("runbook_replica_decode_tokens_total",
                         "Tokens sampled by decode dispatches, per fleet "
                         "replica", labels=("model", "replica")),
             lambda c: float(c.metrics.get("decode_tokens", 0))),
        )
        if clear:
            g_imbalance.clear_functions()
            for metric, _fn in per_replica:
                metric.clear_functions()
            # A previous MULTI-MODEL fleet's per-group rollups must not
            # keep scraping (and pinning) its dead cores either — a
            # multi-model build re-binds them right after its groups'
            # fleets construct (fleet/multimodel._install_metrics).
            for name in ("runbook_model_running_requests",
                         "runbook_model_waiting_requests",
                         "runbook_model_kv_pool_utilization",
                         "runbook_model_decode_tokens_total"):
                stale = reg.get(name)
                if stale is not None:
                    stale.clear_functions()
        # runbook: noqa[RBK010] — model label: configured group
        # name, fixed at fleet build.
        g_imbalance.labels(model=model).set_function(self._imbalance)
        for metric, fn in per_replica:
            for gid, core in zip(self.replica_ids, self.cores):
                # runbook: noqa[RBK010] — model/replica labels: configured
                # group name + pinned replica ids, fixed at fleet build.
                metric.labels(model=model, replica=str(gid)).set_function(
                    lambda c=core, f=fn: f(c))
        # Unlabeled engine names → fleet aggregates (each core's
        # _install_metrics bound them to itself during construction; the
        # last rebind wins, and the fleet is constructed last — a
        # multi-model fleet rebinds them once more over ALL groups).
        install_fleet_aggregates(self.cores)

    def _agg_utilization(self) -> float:
        return _agg_utilization(self.cores)

    def _agg_prefix_hit_ratio(self) -> float:
        return _agg_prefix_hit_ratio(self.cores)

    def _agg_overlap_ratio(self) -> float:
        return _agg_overlap_ratio(self.cores)

    def is_saturated(self) -> bool:
        """True when a placement would shed right now (every replica's
        waiting queue at/over ``shed_queue_depth``). The HTTP layer
        pre-checks this before committing SSE headers so a saturated
        stream gets a real 503; the inevitable check-then-route race
        falls back to the in-stream error event."""
        depth = self.cfg.shed_queue_depth
        if depth is None:
            return False
        quarantined = self._quarantined
        live = [i for i in self._decode_tier if i not in quarantined]
        # No live replica at all is failover, not saturation — the HTTP
        # layer checks failing_over() first and answers a distinct 503.
        return bool(live) and all(
            len(self.cores[i].waiting) >= depth for i in live)

    def debug_steps(self, last_n: Optional[int] = None,
                    lock_timeout: float = 0.5) -> dict:
        """Fleet-wide ``GET /debug/steps``: each replica's flight records
        (already stamped with their ``replica`` index by the recorder)
        merged into one timeline ordered by wall-clock ``ts``. ONE shared
        lock budget across the loop, like :meth:`health_snapshot` — a
        debug probe over a dp=8 fleet must stay as bounded as the single
        engine's."""
        import time

        merged: list[dict] = []
        capacity = 0
        steps_total = 0
        deadline = time.monotonic() + lock_timeout
        for engine in self.replicas:
            budget = max(0.0, deadline - time.monotonic())
            snap = engine.debug_steps(last_n, lock_timeout=budget)
            capacity += snap["capacity"]
            steps_total += snap["steps_total"]
            merged.extend(snap["steps"])
        merged.sort(key=lambda r: r.get("ts", 0.0))
        if last_n is not None:
            n = max(0, int(last_n))
            merged = merged[-n:] if n else []
        return {"capacity": capacity, "steps_total": steps_total,
                "dp_replicas": self.dp, "steps": merged}

    def health_snapshot(self, lock_timeout: float = 0.5) -> dict:
        """Aggregated ``/healthz`` body: summed legacy metrics dict (the
        contract keys keep their meaning — fleet-wide totals), pooled KV
        stats, per-replica breakdown, and router state. Each replica's
        metrics snapshot under its own step lock, with ``lock_timeout``
        as ONE shared budget across the whole loop — a probe over a dp=8
        fleet must stay as bounded as the single engine's (a liveness
        probe that blocks seconds gets the pod killed mid-compile); a
        torn-but-live snapshot beats a dead prober."""
        import time

        agg: dict = {}
        replicas = []
        unresponsive: list[int] = []
        quarantined = self._quarantined
        kv_total = kv_used = kv_cached = 0
        deadline = time.monotonic() + lock_timeout
        for i, (engine, core) in enumerate(zip(self.replicas, self.cores)):
            budget = max(0.0, deadline - time.monotonic())
            # Floor of 20 ms even after the shared budget is spent: one
            # genuinely wedged replica must not make every LATER replica
            # (probed with what would be a blocking=False attempt that
            # any normal in-flight dispatch fails) read as a phantom
            # fleet-wide outage. Worst case stays bounded:
            # lock_timeout + dp × 20 ms.
            locked = engine._lock.acquire(timeout=max(budget, 0.02))
            try:
                m = dict(core.metrics)
            finally:
                if locked:
                    engine._lock.release()
            # A replica that exhausts its lock budget is NOT silently
            # reported thin: its step thread is holding the lock past a
            # liveness probe's patience — the cheapest wedge signal the
            # supervisor has. (Its metrics row is the torn lock-free
            # read, explicitly labeled.)
            status = "ok"
            if not locked:
                status = "unresponsive"
                unresponsive.append(self.replica_ids[i])
            elif i in quarantined:
                status = "quarantined"
            for k, v in m.items():
                agg[k] = agg.get(k, 0) + v
            kv = core.kv
            kv_total += kv.allocator.num_pages
            kv_used += kv.pages_in_use
            kv_cached += kv.allocator.cached_pages
            replicas.append({
                "replica": self.replica_ids[i],
                "tier": ("prefill" if i in self._prefill_tier
                         else "decode" if self._prefill_tier else "mixed"),
                "status": status,
                "running": len(core.decoding),
                "waiting": len(core.waiting) + len(core.prefilling),
                "kv": {"pages_total": kv.allocator.num_pages,
                       "pages_in_use": kv.pages_in_use,
                       "pages_cached": kv.allocator.cached_pages,
                       "utilization": round(kv.utilization(), 4)},
                "decode_tokens": m.get("decode_tokens", 0),
                "kv_pages_imported": m.get("kv_pages_imported", 0),
                "kv_pages_exported": m.get("kv_pages_exported", 0),
            })
        usable = sum(c.kv.allocator.num_pages - 1 for c in self.cores)
        body = {
            "dp_replicas": self.dp,
            "kv": {"pages_total": kv_total, "pages_in_use": kv_used,
                   "pages_cached": kv_cached,
                   "utilization": round(kv_used / usable, 4)
                   if usable else 0.0},
            "metrics": agg,
            "replicas": replicas,
            "router": {
                "routed": self.routed_counts(),
                "affinity_hit_ratio": round(self.affinity_hit_ratio(), 4),
                "imbalance_ratio": round(self._imbalance(), 4),
            },
        }
        if unresponsive:
            body["unresponsive_replicas"] = unresponsive
        if quarantined:
            body["router"]["quarantined"] = self.quarantined_replicas()
        if self.supervisor is not None:
            # Replica supervision (chaos/supervisor.py): per-replica
            # state machine, rebuild/failover counters, recent
            # transitions — the `runbook chaos status` body.
            body["supervisor"] = self.supervisor.snapshot()
        if self.chaos is not None:
            # Live fault injection (chaos/inject.py): the seeded
            # schedule and every applied fault window with provenance.
            body["chaos"] = self.chaos.snapshot()
        if self._kv_share:
            body["router"]["kv_share"] = {
                "xreplica_hits": int(self._m_xreplica_hits.value),
                "pages_pulled": int(self._m_xreplica_pages.value),
                "pull_seconds": round(self._m_xreplica_seconds.value, 4),
                "stale_rejections": self.stale_rejections(),
                "stale_by_reason": {
                    reason: int(child.value)
                    for reason, child in self._m_stale.items()},
            }
        if self._prefill_tier:
            # The /healthz tier breakdown: which GLOBAL replica ids serve
            # each tier (matches the replicas[].tier rows above).
            body["router"]["disagg"] = {
                "prefill_replicas": [self.replica_ids[i]
                                     for i in self._prefill_tier],
                "decode_replicas": [self.replica_ids[i]
                                    for i in self._decode_tier],
                "warm_prefills": int(self._m_warm.total()),
            }
        return body
