"""Paged KV cache: device page pool + host-side page allocator.

The pool is a pair of arrays ``[n_layers, num_pages * page_size, n_kv_heads,
head_dim]`` — fully static shapes so every engine step hits the same compiled
program. Logical→physical mapping lives in per-slot page tables (int32), and
the free list is host-side (a C++ allocator can swap in behind the same
interface; the Python one is O(1) per op and not a bottleneck at v1 scale).

No reference counterpart (SURVEY.md §2.9 item 2 — green-field requirement).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class PagePool:
    """Device arrays for the paged KV cache."""

    kv_k: jax.Array
    kv_v: jax.Array
    page_size: int
    num_pages: int

    @staticmethod
    def create(
        n_layers: int,
        num_pages: int,
        page_size: int,
        n_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
    ) -> "PagePool":
        shape = (n_layers, num_pages * page_size, n_kv_heads, head_dim)
        return PagePool(
            kv_k=jnp.zeros(shape, dtype=dtype),
            kv_v=jnp.zeros(shape, dtype=dtype),
            page_size=page_size,
            num_pages=num_pages,
        )


class PageAllocator:
    """Host-side free-list allocator over physical page ids.

    Page 0 is reserved as the "null" page that padding/unused page-table slots
    point at, so garbage gathers stay in-bounds and get masked downstream.
    """

    NULL_PAGE = 0

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (one reserved null page)")
        self.num_pages = num_pages
        self._free: list[int] = list(range(num_pages - 1, 0, -1))  # stack; 0 reserved

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> list[int]:
        if n > len(self._free):
            raise MemoryError(f"KV page pool exhausted: want {n}, have {len(self._free)}")
        return [self._free.pop() for _ in range(n)]

    def free(self, pages: list[int]) -> None:
        for p in pages:
            if p != self.NULL_PAGE:
                self._free.append(p)


@dataclass
class SequenceAllocation:
    """Pages owned by one live sequence."""

    pages: list[int] = field(default_factory=list)
    ctx_len: int = 0  # tokens currently cached

    def pages_needed(self, new_len: int, page_size: int) -> int:
        have = len(self.pages)
        need = (new_len + page_size - 1) // page_size
        return max(0, need - have)


class KVCacheManager:
    """Pairs the device pool with the allocator and builds page tables."""

    def __init__(
        self,
        n_layers: int,
        num_pages: int,
        page_size: int,
        n_kv_heads: int,
        head_dim: int,
        max_seq_len: int,
        dtype=jnp.bfloat16,
    ):
        self.pool = PagePool.create(n_layers, num_pages, page_size, n_kv_heads, head_dim, dtype)
        self.allocator = PageAllocator(num_pages)
        self.page_size = page_size
        self.max_pages_per_seq = (max_seq_len + page_size - 1) // page_size
        self.seqs: dict[str, SequenceAllocation] = {}

    def add_sequence(self, seq_id: str) -> None:
        self.seqs[seq_id] = SequenceAllocation()

    def extend(self, seq_id: str, new_ctx_len: int) -> None:
        """Ensure pages exist to hold ``new_ctx_len`` tokens."""
        alloc = self.seqs[seq_id]
        if new_ctx_len > self.max_pages_per_seq * self.page_size:
            raise MemoryError(f"sequence {seq_id} exceeds max_seq_len")
        need = alloc.pages_needed(new_ctx_len, self.page_size)
        if need:
            alloc.pages.extend(self.allocator.alloc(need))
        alloc.ctx_len = new_ctx_len

    def can_extend(self, seq_id: str, new_ctx_len: int) -> bool:
        alloc = self.seqs.get(seq_id)
        if alloc is None:
            return False
        return alloc.pages_needed(new_ctx_len, self.page_size) <= self.allocator.free_pages

    def can_admit(self, prompt_len: int, headroom_tokens: int = 0) -> bool:
        need = (prompt_len + headroom_tokens + self.page_size - 1) // self.page_size
        return need <= self.allocator.free_pages

    def release(self, seq_id: str) -> None:
        alloc = self.seqs.pop(seq_id, None)
        if alloc:
            self.allocator.free(alloc.pages)

    def page_table_row(self, seq_id: str) -> np.ndarray:
        """Padded int32 row of physical page ids for one sequence."""
        row = np.full(self.max_pages_per_seq, PageAllocator.NULL_PAGE, dtype=np.int32)
        pages = self.seqs[seq_id].pages
        row[: len(pages)] = pages
        return row

    def page_tables(self, seq_ids: list[str]) -> np.ndarray:
        """[len(seq_ids), max_pages_per_seq] int32; unknown ids -> null rows."""
        rows = []
        for sid in seq_ids:
            if sid in self.seqs:
                rows.append(self.page_table_row(sid))
            else:
                rows.append(np.zeros(self.max_pages_per_seq, dtype=np.int32))
        return np.stack(rows) if rows else np.zeros((0, self.max_pages_per_seq), np.int32)
