"""Paged KV cache: device page pool + host-side allocator with prefix reuse.

The pool is a pair of arrays ``[n_layers, num_pages * page_size, n_kv_heads,
head_dim]`` — fully static shapes so every engine step hits the same compiled
program. Logical→physical mapping lives in per-slot page tables (int32), and
the free list is host-side.

Prefix caching (automatic, vLLM-style): full pages are content-addressed by a
hash chain over their token ids. When a new request's prompt shares a
page-aligned prefix with pages still resident in HBM — the same system prompt
re-sent by every agent iteration — those pages are reused (refcounted,
copy-on-write-free: shared pages are never written, because decode only ever
writes the *last, unshared* page of a sequence) and prefill skips straight to
the first novel token. Pages whose last reference drops move to an LRU of
retired-but-resident pages and are only truly recycled under pool pressure.

Two interchangeable backends implement the allocator+index: pure Python here,
and the C++ one in :mod:`runbookai_tpu.native` (selected automatically when
the compiled library is available; ``RUNBOOKAI_NATIVE=0`` disables).

No reference counterpart (SURVEY.md §2.9 item 2 — green-field requirement).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class PagePool:
    """Device arrays for the paged KV cache."""

    kv_k: jax.Array
    kv_v: jax.Array
    page_size: int
    num_pages: int

    @staticmethod
    def create(
        n_layers: int,
        num_pages: int,
        page_size: int,
        n_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
        sharding=None,
    ) -> "PagePool":
        shape = (n_layers, num_pages * page_size, n_kv_heads, head_dim)
        # int8 pools carry one f32 absmax scale per (token, kv head) —
        # tuple leaves thread through jit/scan/donation as a pytree, so
        # no engine signature changes (ops/attention.py quantize_kv).
        quantized = jnp.dtype(dtype) == jnp.int8
        scale_sharding = None
        if sharding is not None and quantized:
            from jax.sharding import NamedSharding, PartitionSpec

            scale_sharding = NamedSharding(
                sharding.mesh, PartitionSpec(*sharding.spec[:3]))

        if sharding is not None:
            # Create directly sharded (kv-heads over the model axis): a
            # host-side zeros + device_put would materialize the full
            # pool on one device first — an OOM at exactly the scale TP
            # exists for. One jitted closure per shape, reused for K and
            # V, so each zeros program compiles once.
            zeros = jax.jit(lambda: jnp.zeros(shape, dtype=dtype),
                            out_shardings=sharding)
            zeros_s = (jax.jit(lambda: jnp.zeros(shape[:3], jnp.float32),
                               out_shardings=scale_sharding)
                       if quantized else None)

            def alloc():
                return (zeros(), zeros_s()) if quantized else zeros()
        else:
            def alloc():
                vals = jnp.zeros(shape, dtype=dtype)
                if quantized:
                    return vals, jnp.zeros(shape[:3], jnp.float32)
                return vals

        kv_k, kv_v = alloc(), alloc()
        return PagePool(
            kv_k=kv_k,
            kv_v=kv_v,
            page_size=page_size,
            num_pages=num_pages,
        )


def hash_blocks(token_ids: Sequence[int], page_size: int,
                max_blocks: Optional[int] = None, seed: int = 0) -> list[int]:
    """FNV-1a hash chain over full pages of ``token_ids``.

    Block i's hash folds in block i-1's, so equal hashes imply equal full
    prefixes (up to hash collisions), never equal pages at different depths.
    Dispatches to the C++ implementation when the native library is built.

    ``seed`` partitions the cache namespace: KV pages computed under a LoRA
    adapter hold DIFFERENT values for the same tokens (adapters on wk/wv),
    so each adapter_idx seeds its own chain and can never match another
    adapter's (or the base model's) pages.
    """
    from runbookai_tpu import native

    if seed == 0 and native.available():
        out = native.hash_blocks_native(token_ids, page_size, max_blocks)
        if out is not None:
            return out
    n_full = len(token_ids) // page_size
    if max_blocks is not None:
        n_full = min(n_full, max_blocks)
    out: list[int] = []
    h = 0xCBF29CE484222325 ^ ((seed * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF)
    for b in range(n_full):
        for t in token_ids[b * page_size : (b + 1) * page_size]:
            h ^= (t + 1) & 0xFFFFFFFFFFFFFFFF
            h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
        out.append(h)
    return out


def _kv_leaves(tree: Any) -> list:
    """Flat leaves of one side of a pool (a bare array, or (values,
    scales) for quantized pools) — every leaf's axis 1 is the token/row
    axis, so page-row slicing is uniform across pool dtypes."""
    return jax.tree_util.tree_leaves(tree)


def _page_rows(pages: Sequence[int], page_size: int) -> np.ndarray:
    return np.concatenate(
        [np.arange(p * page_size, (p + 1) * page_size) for p in pages])


def _fetch_rows(tree: Any, rows: np.ndarray) -> list[np.ndarray]:
    """Fetch ``rows`` of every pool leaf to the host in one gather each.

    THE page-transfer sync point (docs/lint.md "page transfer" entry):
    every path that moves KV bytes off a device pool — cross-replica
    pull export, prefill→decode handoff, spill-tier capture — funnels
    its device→host copy through here, one batched fetch per transfer,
    never inside the decode loop (callers hold the engine lock between
    steps). tests/test_lint.py pins this as the only sanctioned sync in
    this module.
    """
    # runbook: noqa[RBK002] — sanctioned sync: the page-transfer fetch —
    # one batched device→host copy per pull/handoff/spill, on the
    # admission/routing path under the engine lock, never the decode loop.
    return [np.asarray(jax.device_get(leaf[:, rows]))
            for leaf in _kv_leaves(tree)]


def _block_digest(leaves_k: Sequence[np.ndarray],
                  leaves_v: Sequence[np.ndarray],
                  block: int, page_size: int) -> str:
    """Content digest of one page's K+V bytes in an exported batch.

    Checked again at import time: a pulled page is installed only if it is
    byte-identical to what the exporter read — a corrupted or re-ordered
    transfer must downgrade to recompute, never serve wrong KV."""
    h = hashlib.blake2b(digest_size=16)
    lo, hi = block * page_size, (block + 1) * page_size
    for leaf in (*leaves_k, *leaves_v):
        h.update(np.ascontiguousarray(leaf[:, lo:hi]).tobytes())
    return h.hexdigest()


@dataclass
class ExportedPages:
    """Host-staged KV pages in transit between pools (cross-replica pull,
    prefill→decode handoff, spill readmit). ``leaves_k``/``leaves_v`` hold
    ALL exported pages concatenated on the row axis (block ``i`` owns rows
    ``[i*page_size, (i+1)*page_size)``), fetched in ONE device→host copy.
    """

    page_size: int
    hash_seed: int
    skip_blocks: int  # chain depth of the first exported block
    hashes: list[int]  # chain hash per exported block
    blocks: list[tuple[int, ...]]  # token ids each page actually holds
    leaves_k: list[np.ndarray]
    leaves_v: list[np.ndarray]
    digests: list[str]
    src_version: int
    src_replica: Optional[int] = None

    @property
    def num_pages(self) -> int:
        return len(self.hashes)


@dataclass
class _SpillEntry:
    blocks: tuple[int, ...]
    leaves_k: list[np.ndarray]
    leaves_v: list[np.ndarray]
    digest: str


class HostSpillTier:
    """Bounded host-RAM store of evicted prefix-cache pages.

    HBM pressure evicts retired pages oldest-first; with a spill tier the
    evicted bytes drain here instead of vanishing, so the next request
    with the same prefix re-admits them (one host→device upload) instead
    of recomputing the prefill. LRU-bounded by ``max_pages``; keyed by the
    chain hash (already namespaced by the LoRA ``hash_seed``), with the
    token block stored alongside so a readmit is verified exactly like a
    resident prefix match."""

    def __init__(self, max_pages: int):
        self.max_pages = max(0, int(max_pages))
        self._store: OrderedDict[int, _SpillEntry] = OrderedDict()
        self.pages_spilled = 0  # total puts (runbook_kv_spill_pages_total)
        self.evictions = 0  # LRU drops (runbook_kv_spill_evictions_total)
        self.readmitted = 0  # pages re-admitted into a device pool

    def __len__(self) -> int:
        return len(self._store)

    def put(self, block_hash: int, blocks: tuple[int, ...],
            leaves_k: list[np.ndarray], leaves_v: list[np.ndarray],
            digest: str) -> None:
        if not self.max_pages:
            return
        if block_hash in self._store:
            self._store.move_to_end(block_hash)
            return
        while len(self._store) >= self.max_pages:
            self._store.popitem(last=False)
            self.evictions += 1
        self._store[block_hash] = _SpillEntry(blocks, leaves_k, leaves_v,
                                              digest)
        self.pages_spilled += 1

    def get(self, block_hash: int) -> Optional[_SpillEntry]:
        entry = self._store.get(block_hash)
        if entry is not None:
            self._store.move_to_end(block_hash)
        return entry

    def evict_all(self) -> int:
        """Drop every resident entry (counted as evictions) — the
        spill-pressure fault in chaos/inject.py simulates the host-RAM
        envelope collapsing under an external consumer. Returns the
        number of pages dropped. Call under the owning engine's lock
        (the tier is otherwise only touched from the step thread)."""
        dropped = len(self._store)
        self._store.clear()
        self.evictions += dropped
        return dropped


class PageAllocator:
    """Host-side allocator over physical page ids with a prefix-cache index.

    Page 0 is reserved as the "null" page that padding/unused page-table slots
    point at, so garbage gathers stay in-bounds and get masked downstream.

    Page lifecycle::

        free ──alloc──▶ referenced (ref ≥ 1, owned by live sequences)
          ▲                │ decref→0, has content hash
          │                ▼
          └──evict──── retired LRU (resident, matchable, recyclable)

    ``alloc`` prefers the free list and falls back to evicting the
    least-recently-retired cached page (its hash entry is invalidated).
    """

    NULL_PAGE = 0

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (one reserved null page)")
        self.num_pages = num_pages
        self._free: list[int] = list(range(num_pages - 1, 0, -1))  # stack; 0 reserved
        self._ref: dict[int, int] = {}
        self._retired: OrderedDict[int, None] = OrderedDict()  # LRU, ref == 0
        self._hash_to_page: dict[int, int] = {}
        self._page_to_hash: dict[int, int] = {}

    @property
    def free_pages(self) -> int:
        """Pages allocatable right now (free + evictable retired)."""
        return len(self._free) + len(self._retired)

    @property
    def cached_pages(self) -> int:
        return len(self._retired)

    def alloc(self, n: int) -> list[int]:
        if n > self.free_pages:
            raise MemoryError(
                f"KV page pool exhausted: want {n}, have {self.free_pages}")
        out: list[int] = []
        for _ in range(n):
            if self._free:
                p = self._free.pop()
            else:
                p, _ = self._retired.popitem(last=False)  # oldest retired
                self._invalidate(p)
            self._ref[p] = 1
            out.append(p)
        return out

    def free(self, pages: Sequence[int]) -> None:
        """Decref each page; unreferenced pages retire (if hashed) or free."""
        for p in pages:
            if p == self.NULL_PAGE:
                continue
            r = self._ref.get(p, 0) - 1
            if r > 0:
                self._ref[p] = r
                continue
            self._ref.pop(p, None)
            if p in self._page_to_hash:
                self._retired[p] = None
                self._retired.move_to_end(p)
            else:
                self._free.append(p)

    # ------------------------------------------------------------ prefix cache

    def register(self, page: int, block_hash: int) -> None:
        """Publish a full page's content hash so future prompts can match it."""
        if page == self.NULL_PAGE or block_hash in self._hash_to_page:
            return  # first writer wins; duplicates keep their private copy
        old = self._page_to_hash.get(page)
        if old is not None:
            self._hash_to_page.pop(old, None)
        self._page_to_hash[page] = block_hash
        self._hash_to_page[block_hash] = page

    def lookup(self, block_hash: int) -> Optional[int]:
        return self._hash_to_page.get(block_hash)

    def acquire(self, page: int) -> None:
        """Take a reference on a matched page (reviving it if retired)."""
        if page in self._retired:
            del self._retired[page]
            self._ref[page] = 1
        else:
            self._ref[page] = self._ref.get(page, 0) + 1

    def is_retired(self, page: int) -> bool:
        """True when the page is resident but unreferenced (counts toward
        ``free_pages``; acquiring it consumes allocatable capacity)."""
        return page in self._retired

    def _invalidate(self, page: int) -> None:
        h = self._page_to_hash.pop(page, None)
        if h is not None and self._hash_to_page.get(h) == page:
            del self._hash_to_page[h]


@dataclass
class SequenceAllocation:
    """Pages owned by one live sequence."""

    pages: list[int] = field(default_factory=list)
    ctx_len: int = 0  # tokens currently cached
    registered_blocks: int = 0  # full pages whose hashes are published
    hash_seed: int = 0  # prefix-cache namespace (LoRA adapter_idx)

    def pages_needed(self, new_len: int, page_size: int) -> int:
        have = len(self.pages)
        need = (new_len + page_size - 1) // page_size
        return max(0, need - have)


class KVCacheManager:
    """Pairs the device pool with the allocator and builds page tables."""

    def __init__(
        self,
        n_layers: int,
        num_pages: int,
        page_size: int,
        n_kv_heads: int,
        head_dim: int,
        max_seq_len: int,
        dtype=jnp.bfloat16,
        allocator: Optional[PageAllocator] = None,
        sharding=None,
        spill_pages: int = 0,
    ):
        self.pool = PagePool.create(n_layers, num_pages, page_size, n_kv_heads,
                                    head_dim, dtype, sharding=sharding)
        if allocator is None:
            from runbookai_tpu.native import make_page_allocator

            allocator = make_page_allocator(num_pages)
        self.allocator = allocator
        self.page_size = page_size
        self.max_pages_per_seq = (max_seq_len + page_size - 1) // page_size
        self.seqs: dict[str, SequenceAllocation] = {}
        # Monotonic page-table version: bumped whenever any sequence's page
        # list changes (add/extend/release). Consumers that upload page
        # tables to the device (engine decode dispatch, draft worker) key
        # their caches on it, so a steady-state decode step rebuilds
        # nothing and a stale table can never survive an allocation.
        self.version = 0
        # Whether the LAST import_pages call hit a content-digest
        # mismatch (payload corrupted in transit). Set under the engine
        # lock alongside the import itself; the fleet router reads it to
        # attribute the stale-pull reason label
        # (runbook_router_xreplica_stale_total{reason="digest_mismatch"}).
        self.last_import_digest_mismatch = False
        # Token ids actually stored in each published page — matches are
        # verified against these so a 64-bit hash collision can never serve
        # another request's KV (cross-request leakage). Bounded by num_pages.
        self._page_tokens: dict[int, tuple[int, ...]] = {}
        # Host-RAM spill tier (0 = disabled): evicted prefix-cache pages
        # drain here instead of vanishing; readmit_spilled pulls them back
        # under a fresh prefix match. Spill capture needs the allocator's
        # retired-LRU internals, so it is a pure-Python-allocator feature
        # (the native allocator reports no evictable inventory and the
        # tier stays empty — correct, just cold).
        self.spill: Optional[HostSpillTier] = (
            HostSpillTier(spill_pages) if spill_pages > 0 else None)

    # ----------------------------------------------------------- prefix reuse

    def _prompt_hashes(self, prompt_ids: Sequence[int],
                       hashes: Optional[list[int]],
                       hash_seed: int = 0) -> list[int]:
        """Hash chain for matching: capped below ``len(prompt_ids)`` so at
        least one prompt token is always prefilled (the engine needs its
        logits to sample from). ``hashes`` may be a memoized full chain."""
        max_blocks = (len(prompt_ids) - 1) // self.page_size
        if hashes is not None:
            return hashes[:max_blocks]
        return hash_blocks(prompt_ids, self.page_size, max_blocks,
                           seed=hash_seed)

    def _match_pages(self, prompt_ids: Sequence[int],
                     hashes: Optional[list[int]],
                     hash_seed: int = 0) -> list[int]:
        """Resident pages holding the prompt's leading full blocks, verified
        token-by-token (a bare hash hit is never trusted)."""
        matched: list[int] = []
        for b, h in enumerate(self._prompt_hashes(prompt_ids, hashes,
                                                  hash_seed)):
            page = self.allocator.lookup(h)
            if page is None:
                break
            blk = tuple(prompt_ids[b * self.page_size : (b + 1) * self.page_size])
            if self._page_tokens.get(page) != blk:
                break  # hash collision or stale publish — treat as a miss
            matched.append(page)
        return matched

    def match_prefix(self, prompt_ids: Sequence[int],
                     hashes: Optional[list[int]] = None,
                     hash_seed: int = 0) -> int:
        """Longest reusable page-aligned prefix length (read-only probe)."""
        return len(self._match_pages(prompt_ids, hashes,
                                     hash_seed)) * self.page_size

    def probe_admit(self, prompt_ids: Sequence[int], headroom_tokens: int = 0,
                    hashes: Optional[list[int]] = None,
                    hash_seed: int = 0,
                    ) -> tuple[bool, list[int]]:
        """Admission check honoring prefix reuse: ``(fits, matched_pages)``.

        Matched *retired* pages are about to be revived by ``add_sequence`` —
        they both reduce the pages to allocate and consume allocatable
        capacity, so they must be subtracted from ``free_pages`` too (a plain
        ``can_admit(cached_len=...)`` would double-count them). The matched
        pages are returned so ``add_sequence(matched=...)`` needn't re-walk
        the chain (valid only until the next alloc/release).
        """
        matched = self._match_pages(prompt_ids, hashes, hash_seed)
        cached = len(matched) * self.page_size
        reserved = sum(1 for p in matched if self.allocator.is_retired(p))
        need = self.add_pages_needed(len(prompt_ids), cached, headroom_tokens)
        return need <= self.allocator.free_pages - reserved, matched

    def add_sequence(self, seq_id: str, prompt_ids: Optional[Sequence[int]] = None,
                     hashes: Optional[list[int]] = None,
                     matched: Optional[list[int]] = None,
                     hash_seed: int = 0) -> int:
        """Register a sequence, reusing cached prefix pages. Returns the
        number of prompt tokens whose KV is already resident. ``matched``
        short-circuits the chain walk with pages a just-run ``probe_admit``
        already verified. ``hash_seed`` (the LoRA adapter row) is REMEMBERED
        on the allocation, so later publishes release into the same cache
        namespace the pages were matched from."""
        alloc = SequenceAllocation(hash_seed=hash_seed)
        cached = 0
        if prompt_ids:
            pages = (matched if matched is not None
                     else self._match_pages(prompt_ids, hashes, hash_seed))
            for page in pages:
                self.allocator.acquire(page)
                alloc.pages.append(page)
                cached += self.page_size
            alloc.ctx_len = cached
            alloc.registered_blocks = len(alloc.pages)
        self.seqs[seq_id] = alloc
        self.version += 1
        return cached

    def register_prefix(self, seq_id: str, token_ids: Sequence[int],
                        hashes: Optional[list[int]] = None) -> None:
        """Publish hashes for this sequence's newly completed full pages.

        ``token_ids`` must be the tokens whose KV the pages actually hold
        (prompt plus any generated tokens already fed back).
        """
        alloc = self.seqs.get(seq_id)
        if alloc is None:
            return
        max_blocks = min(len(token_ids) // self.page_size, len(alloc.pages))
        if hashes is None or len(hashes) < max_blocks:
            hashes = hash_blocks(token_ids, self.page_size, max_blocks,
                                 seed=alloc.hash_seed)
        for b in range(alloc.registered_blocks, max_blocks):
            page = alloc.pages[b]
            self.allocator.register(page, hashes[b])
            if self.allocator.lookup(hashes[b]) == page:  # publish took effect
                self._page_tokens[page] = tuple(
                    token_ids[b * self.page_size : (b + 1) * self.page_size])
        alloc.registered_blocks = max(alloc.registered_blocks, max_blocks)

    # ------------------------------------------------- page transfer / spill

    def _matched_chain(self, prompt_ids: Sequence[int],
                       hashes: Optional[list[int]], hash_seed: int,
                       ) -> tuple[list[int], list[int], list[tuple[int, ...]]]:
        """Verified resident prefix: ``(pages, chain hashes, token blocks)``
        — the same walk as :meth:`_match_pages`, keeping the hash/token
        metadata a transfer payload needs."""
        pages: list[int] = []
        keep_hashes: list[int] = []
        blocks: list[tuple[int, ...]] = []
        for b, h in enumerate(self._prompt_hashes(prompt_ids, hashes,
                                                  hash_seed)):
            page = self.allocator.lookup(h)
            if page is None:
                break
            blk = tuple(prompt_ids[b * self.page_size:(b + 1) * self.page_size])
            if self._page_tokens.get(page) != blk:
                break
            pages.append(page)
            keep_hashes.append(h)
            blocks.append(blk)
        return pages, keep_hashes, blocks

    def export_pages(self, kv_k, kv_v, prompt_ids: Sequence[int],
                     hashes: Optional[list[int]] = None, hash_seed: int = 0,
                     skip_blocks: int = 0, max_pages: Optional[int] = None,
                     ) -> Optional[ExportedPages]:
        """Stage this pool's resident prefix pages for another pool.

        ``kv_k``/``kv_v`` are the CALLER's live pool arrays (the engine's,
        not ``self.pool`` — the engine's dispatch donation leaves the pool
        handle stale after the first step). Staleness is guarded
        PER-CHAIN: a router probe reads the prefix index lock-free, and
        the plan it made is re-validated here under the engine lock by
        re-walking the chain with per-page token verification — pages
        evicted or re-registered since the probe simply fall out of the
        walk, and a plan whose pages are gone exports nothing (the
        requester recomputes). The global ``version`` epoch is NOT
        compared: it moves on every admission/extension/release anywhere
        in the pool, so on a busy source it would reject pulls whose
        pages are still verifiably resident. Matched pages are pinned
        (acquire/free) across the device→host copy so pool pressure
        cannot recycle them mid-export.
        """
        pages, keep_hashes, blocks = self._matched_chain(prompt_ids, hashes,
                                                         hash_seed)
        if max_pages is not None:
            end = skip_blocks + max(0, max_pages)
            pages, keep_hashes, blocks = (pages[:end], keep_hashes[:end],
                                          blocks[:end])
        pages = pages[skip_blocks:]
        keep_hashes = keep_hashes[skip_blocks:]
        blocks = blocks[skip_blocks:]
        if not pages:
            return None
        for p in pages:
            self.allocator.acquire(p)
        try:
            rows = _page_rows(pages, self.page_size)
            leaves_k = _fetch_rows(kv_k, rows)
            leaves_v = _fetch_rows(kv_v, rows)
        finally:
            self.allocator.free(pages)
        digests = [_block_digest(leaves_k, leaves_v, j, self.page_size)
                   for j in range(len(pages))]
        return ExportedPages(
            page_size=self.page_size, hash_seed=hash_seed,
            skip_blocks=skip_blocks, hashes=keep_hashes, blocks=blocks,
            leaves_k=leaves_k, leaves_v=leaves_v, digests=digests,
            src_version=self.version)

    def _leaves_compatible(self, kv_k,
                           leaves_k: Sequence[np.ndarray]) -> bool:
        mine = _kv_leaves(kv_k)
        if len(mine) != len(leaves_k):
            return False
        for leaf, data in zip(mine, leaves_k):
            if (leaf.shape[0] != data.shape[0]
                    or leaf.shape[2:] != data.shape[2:]
                    or jnp.dtype(leaf.dtype) != np.dtype(data.dtype)):
                return False
        return True

    @staticmethod
    def _set_rows(tree, rows: np.ndarray, data_leaves: Sequence[np.ndarray],
                  lo: int, hi: int):
        """Write host rows ``[lo:hi)`` of each data leaf into the pool
        tree at ``rows`` (functional update — returns the new tree)."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        new = [leaf.at[:, rows].set(jnp.asarray(d[:, lo:hi], dtype=leaf.dtype))
               for leaf, d in zip(leaves, data_leaves)]
        return jax.tree_util.tree_unflatten(treedef, new)

    def _install_blocks(self, kv_k, kv_v,
                        items: Sequence[tuple]) -> tuple[Any, Any, int]:
        """Install verified blocks: one STRICTLY-FREE page per item, all
        pages written in ONE functional pool update per tree.

        ``items`` = ``(block_hash, tokens, leaves_k, leaves_v, lo, hi)``
        per block, in chain order. A per-page ``.at[].set`` would
        materialize a full pool copy per installed page — under the
        destination engine's step lock that stalls every in-flight
        decode, so the writes batch exactly like the export's single
        ``_fetch_rows`` copy. Only strictly-free pages host installs —
        never the retired prefix cache: evicting resident cache for a
        speculative install would trade a known-hot page for a maybe-hot
        one, and (since installed pages retire immediately) the alloc
        would recycle the blocks installed moments earlier in this very
        call, leaving a broken non-prefix residue. A full pool stops the
        walk, keeping the installed prefix contiguous."""
        ps = self.page_size
        staged: list[tuple[int, tuple]] = []
        for block_hash, blk, leaves_k, leaves_v, lo, hi in items:
            if self.allocator.free_pages - self.allocator.cached_pages < 1:
                break
            try:
                [page] = self.allocator.alloc(1)
            except MemoryError:
                break
            self.allocator.register(page, block_hash)
            if self.allocator.lookup(block_hash) == page:
                self._page_tokens[page] = blk
            staged.append((page, (leaves_k, leaves_v, lo, hi)))
        if not staged:
            return kv_k, kv_v, 0
        dst_rows = _page_rows([p for p, _ in staged], ps)
        n_leaves = len(_kv_leaves(kv_k))
        data_k = [np.concatenate(
            [np.ascontiguousarray(src[0][i][:, src[2]:src[3]])
             for _, src in staged], axis=1) for i in range(n_leaves)]
        data_v = [np.concatenate(
            [np.ascontiguousarray(src[1][i][:, src[2]:src[3]])
             for _, src in staged], axis=1) for i in range(n_leaves)]
        kv_k = self._set_rows(kv_k, dst_rows, data_k, 0, len(staged) * ps)
        kv_v = self._set_rows(kv_v, dst_rows, data_v, 0, len(staged) * ps)
        # Retire immediately: installed pages are matchable exactly like
        # released prefix pages, and stay evictable under pool pressure
        # so installs can never starve live sequences.
        self.allocator.free([p for p, _ in staged])
        return kv_k, kv_v, len(staged)

    def import_pages(self, kv_k, kv_v, exported: ExportedPages,
                     ) -> tuple[Any, Any, int]:
        """Install exported pages into THIS pool (returns updated arrays +
        pages imported). Each block re-verifies its content digest before
        installation; blocks whose hash already resolves to a verified
        local page are skipped (the exporter raced a local prefill — fine,
        first writer wins). Installation semantics (strictly-free pages
        only, one batched pool write, contiguous-prefix stop on a full
        pool) live in :meth:`_install_blocks` — partial prefixes are
        still byte-exact wins."""
        self.last_import_digest_mismatch = False
        if exported.page_size != self.page_size \
                or not self._leaves_compatible(kv_k, exported.leaves_k):
            return kv_k, kv_v, 0
        ps = self.page_size
        items = []
        for j in range(exported.num_pages):
            h = exported.hashes[j]
            blk = exported.blocks[j]
            existing = self.allocator.lookup(h)
            if existing is not None and self._page_tokens.get(existing) == blk:
                continue
            if _block_digest(exported.leaves_k, exported.leaves_v, j,
                             ps) != exported.digests[j]:
                # Payload corrupted in transit — recompute instead. The
                # flag lets the puller label WHY its plan fell short.
                self.last_import_digest_mismatch = True
                break
            items.append((h, blk, exported.leaves_k, exported.leaves_v,
                          j * ps, (j + 1) * ps))
        kv_k, kv_v, imported = self._install_blocks(kv_k, kv_v, items)
        if imported:
            self.version += 1
        return kv_k, kv_v, imported

    def spill_evictable(self, kv_k, kv_v, want_pages: int) -> int:
        """Drain the retired pages an upcoming ``alloc(want_pages)`` would
        evict into the host spill tier (one batched device→host copy).

        Called by the engine right before prefill page allocation when the
        free list alone cannot satisfy the request — the only point pages
        leave HBM with their bytes still addressable. Decode-growth
        evictions skip this (no sync in the decode loop); those pages are
        simply lost to the tier, which is a cold-cache miss, not an error.
        """
        if self.spill is None:
            return 0
        free = getattr(self.allocator, "_free", None)
        retired = getattr(self.allocator, "_retired", None)
        to_hash = getattr(self.allocator, "_page_to_hash", None)
        if free is None or retired is None or to_hash is None:
            return 0  # native allocator: no evictable inventory exposed
        n_evict = min(max(0, want_pages - len(free)), len(retired))
        if n_evict <= 0:
            return 0
        victims = [p for p, _ in zip(retired.keys(), range(n_evict))
                   if to_hash.get(p) is not None
                   and self._page_tokens.get(p) is not None]
        if not victims:
            return 0
        rows = _page_rows(victims, self.page_size)
        leaves_k = _fetch_rows(kv_k, rows)
        leaves_v = _fetch_rows(kv_v, rows)
        spilled = 0
        for j, page in enumerate(victims):
            # Copies, not views: a view would keep the WHOLE batched
            # fetch alive per entry, so the tier's max_pages bound would
            # stop bounding host bytes and LRU eviction would free
            # nothing.
            lk = [np.ascontiguousarray(
                      leaf[:, j * self.page_size:(j + 1) * self.page_size])
                  for leaf in leaves_k]
            lv = [np.ascontiguousarray(
                      leaf[:, j * self.page_size:(j + 1) * self.page_size])
                  for leaf in leaves_v]
            self.spill.put(to_hash[page], self._page_tokens[page], lk, lv,
                           _block_digest(lk, lv, 0, self.page_size))
            spilled += 1
        return spilled

    def readmit_spilled(self, kv_k, kv_v, prompt_ids: Sequence[int],
                        hashes: Optional[list[int]] = None,
                        hash_seed: int = 0) -> tuple[Any, Any, int]:
        """Extend this prompt's resident prefix from the spill tier:
        blocks past the resident match whose hash+tokens verify in the
        tier are uploaded back into fresh pages (retired → matchable), so
        the admission that follows sees them as ordinary prefix hits."""
        if self.spill is None or not len(self.spill):
            return kv_k, kv_v, 0
        chain = self._prompt_hashes(prompt_ids, hashes, hash_seed)
        start = len(self._match_pages(prompt_ids, hashes, hash_seed))
        items = []
        for b in range(start, len(chain)):
            entry = self.spill.get(chain[b])
            blk = tuple(prompt_ids[b * self.page_size:(b + 1) * self.page_size])
            if entry is None or entry.blocks != blk:
                break
            if _block_digest(entry.leaves_k, entry.leaves_v, 0,
                             self.page_size) != entry.digest:
                break  # host copy corrupted — recompute
            if not self._leaves_compatible(kv_k, entry.leaves_k):
                break
            items.append((chain[b], blk, entry.leaves_k, entry.leaves_v,
                          0, self.page_size))
        kv_k, kv_v, readmitted = self._install_blocks(kv_k, kv_v, items)
        if readmitted:
            self.version += 1
            self.spill.readmitted += readmitted
        return kv_k, kv_v, readmitted

    # -------------------------------------------------------------- pressure

    @property
    def pages_in_use(self) -> int:
        """Pages referenced by live sequences (excludes the reserved null
        page, free pages, and retired-but-resident cache pages)."""
        a = self.allocator
        return a.num_pages - 1 - a.free_pages

    def utilization(self) -> float:
        """Live-reference pressure on the allocatable pool, 0..1 — the
        KV-pressure gauge serving dashboards alert on (retired cache pages
        still count as allocatable, exactly like admission does)."""
        usable = self.allocator.num_pages - 1
        return self.pages_in_use / usable if usable > 0 else 0.0

    # -------------------------------------------------------------- lifecycle

    def add_pages_needed(self, prompt_len: int, cached_len: int = 0,
                         headroom_tokens: int = 0) -> int:
        total = (prompt_len + headroom_tokens + self.page_size - 1) // self.page_size
        return max(0, total - cached_len // self.page_size)

    def extend(self, seq_id: str, new_ctx_len: int) -> None:
        """Ensure pages exist to hold ``new_ctx_len`` tokens."""
        alloc = self.seqs[seq_id]
        if new_ctx_len > self.max_pages_per_seq * self.page_size:
            raise MemoryError(f"sequence {seq_id} exceeds max_seq_len")
        need = alloc.pages_needed(new_ctx_len, self.page_size)
        if need:
            alloc.pages.extend(self.allocator.alloc(need))
            self.version += 1
        alloc.ctx_len = new_ctx_len

    def can_extend(self, seq_id: str, new_ctx_len: int) -> bool:
        alloc = self.seqs.get(seq_id)
        if alloc is None:
            return False
        return alloc.pages_needed(new_ctx_len, self.page_size) <= self.allocator.free_pages

    def can_admit(self, prompt_len: int, headroom_tokens: int = 0,
                  cached_len: int = 0) -> bool:
        need = self.add_pages_needed(prompt_len, cached_len, headroom_tokens)
        return need <= self.allocator.free_pages

    def release(self, seq_id: str, token_ids: Optional[Sequence[int]] = None) -> None:
        """Drop a sequence's references. When ``token_ids`` is given, full
        pages are published to the prefix cache first so the next request
        with the same prefix rides them."""
        alloc = self.seqs.get(seq_id)
        if alloc is None:
            return
        if token_ids is not None:
            self.register_prefix(seq_id, token_ids)
        del self.seqs[seq_id]
        self.allocator.free(alloc.pages)
        self.version += 1

    # ------------------------------------------------------------ page tables

    def page_table_row(self, seq_id: str) -> np.ndarray:
        """Padded int32 row of physical page ids for one sequence."""
        row = np.full(self.max_pages_per_seq, PageAllocator.NULL_PAGE, dtype=np.int32)
        pages = self.seqs[seq_id].pages
        row[: len(pages)] = pages
        return row

    def page_tables(self, seq_ids: list[str]) -> np.ndarray:
        """[len(seq_ids), max_pages_per_seq] int32; unknown ids -> null rows."""
        rows = []
        for sid in seq_ids:
            if sid in self.seqs:
                rows.append(self.page_table_row(sid))
            else:
                rows.append(np.zeros(self.max_pages_per_seq, dtype=np.int32))
        return np.stack(rows) if rows else np.zeros((0, self.max_pages_per_seq), np.int32)
