"""Engine request/response types and sampling parameters."""

from __future__ import annotations

import asyncio
import time
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Optional


class FleetSaturated(RuntimeError):
    """Every fleet replica is over the shed queue depth — the request was
    shed without being submitted (engine/fleet.py raises it on streaming
    placements; the HTTP layer maps it to 503 before headers, or to an
    SSE error event once they are out). Lives here, not in fleet.py, so
    the server can catch it without importing the jax-heavy fleet module."""


class RequestState(str, Enum):
    WAITING = "waiting"  # queued, no pages yet
    PREFILL = "prefill"  # prompt being processed in chunks
    DECODE = "decode"  # generating, owns a batch slot
    FINISHED = "finished"
    FAILED = "failed"


class FinishReason(str, Enum):
    STOP_TOKEN = "stop_token"
    MAX_TOKENS = "max_tokens"
    STOP_STRING = "stop_string"
    GRAMMAR_END = "grammar_end"
    ABORTED = "aborted"


@dataclass
class SamplingParams:
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled; composes with top_p
    max_new_tokens: int = 512
    stop_token_ids: tuple[int, ...] = ()
    stop_strings: tuple[str, ...] = ()
    # When set, token-level grammar masking constrains output: "json" is the
    # generic well-formed-JSON automaton (runbookai_tpu.model.guided); any
    # name registered with the mask provider selects a compiled schema
    # grammar ("triage", "evaluation", ... — model.schema_guided).
    guided: Optional[str] = None
    # Top-N token logprobs per sampled token (0 = off). Forces single-step
    # decode dispatches (the multi-step scan never surfaces logits) and
    # disables speculation/grammar fast-forward for the request; values
    # come from the RAW model distribution (pre-grammar-mask).
    logprobs: int = 0
    # OpenAI-style repetition penalties over the request's GENERATED
    # tokens (OpenAI's c[j] counts previously sampled tokens — prompt
    # content is never penalized): logits - presence*(count>0) -
    # frequency*count, applied before masking and greedy selection.
    # Token counts live in a device-resident [slots, vocab] array seeded
    # at slot assignment and updated in-dispatch — no per-step host
    # traffic. Penalized requests are excluded from speculation (the
    # verify argmax would need evolving counts per position).
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # Per-request sampling seed (OpenAI `seed`): each sampled position
    # draws from fold_in(PRNGKey(seed), position) — reproducible for a
    # given (seed, position) regardless of batch composition or engine
    # history. None keeps the engine's dispatch key — reproducible only
    # per run shape, since the overlapped decode pipeline's overshoot
    # windows consume extra key splits at stream tails
    # (docs/decode_pipeline.md). Seeded requests are pipeline-independent.
    seed: Optional[int] = None
    # OpenAI logit_bias: ((token_id, bias), ...) added to the logits
    # before penalties/masking/greedy. Densified host-side per dispatch
    # (same shipping pattern as grammar masks); -100/+100 effectively
    # ban/force tokens.
    logit_bias: tuple[tuple[int, float], ...] = ()

    @property
    def penalized(self) -> bool:
        return bool(self.presence_penalty or self.frequency_penalty)

    @property
    def forced_sync(self) -> bool:
        """True when the request pins the engine to synchronous k=1 decode
        dispatches: per-token grammar masks and logprob attachment both
        need the previous token on host before the next dispatch can be
        built. Such requests also keep the classic split prefill/decode
        dispatches — the unified mixed dispatch excludes them so its
        single-forward fast path never has to reconcile mid-step."""
        return bool(self.guided or self.logprobs)


@dataclass
class EngineRequest:
    prompt_ids: list[int]
    sampling: SamplingParams = field(default_factory=SamplingParams)
    request_id: str = field(default_factory=lambda: f"req-{uuid.uuid4().hex[:10]}")
    # Scheduling class: higher admits first and is preempted last (FCFS
    # within a class). Interactive agent turns outrank background eval
    # batches this way without separate engines.
    priority: int = 0
    # LoRA adapter name (None = base model). Resolved to a stacked-adapter
    # row index at submit; requests with different adapters batch together.
    adapter: Optional[str] = None
    adapter_idx: int = 0  # engine-resolved; 0 is the reserved zero adapter
    # Monotonic clock — compared against perf_counter() timestamps in the engine.
    arrival_time: float = field(default_factory=time.perf_counter)
    # Caller-supplied correlation id (the server's x-request-id): carried
    # into the engine's tracer records so a JSONL trace line joins back to
    # the HTTP request that produced it. None for internal callers.
    trace_id: Optional[str] = None

    # Mutable engine-owned state:
    state: RequestState = RequestState.WAITING
    prefill_pos: int = 0  # tokens of the prompt already processed
    out_ids: list[int] = field(default_factory=list)
    # Generated tokens folded into prompt_ids by preemption-by-recompute.
    # Logical output = folded_out_ids + out_ids; ctx_len must not double-count.
    folded_out_ids: list[int] = field(default_factory=list)
    # Memoized full-page hash chain over prompt_ids (admission hot path).
    block_hashes: Optional[list[int]] = None
    slot: Optional[int] = None  # decode batch slot index
    first_token_time: Optional[float] = None  # TTFT measurement
    finish_time: Optional[float] = None  # set by _finish; e2e/TPOT source
    finish_reason: Optional[FinishReason] = None
    guided_state: Any = None  # grammar automaton state
    # Completion signal for the async API (set by AsyncEngine).
    done_event: Optional[asyncio.Event] = None
    # Streaming hook: called with each sampled token id from the engine's
    # worker thread (bridge to an event loop with call_soon_threadsafe).
    # Preemption-by-recompute does NOT re-call this for folded tokens, so
    # a stream sees every token exactly once.
    on_token: Optional[Any] = None
    # Per emitted token, when sampling.logprobs > 0: dicts of
    # {"token_id", "logprob", "top": [(token_id, logprob), ...]}.
    out_logprobs: list = field(default_factory=list)
    # Prompt tokens served from the prefix cache at admission.
    cached_tokens: int = 0

    @property
    def ctx_len(self) -> int:
        return self.prefill_pos + len(self.out_ids)

    @property
    def all_out_ids(self) -> list[int]:
        """Every generated token, including ones folded by preemption."""
        return self.folded_out_ids + self.out_ids

    @property
    def num_generated(self) -> int:
        return len(self.folded_out_ids) + len(self.out_ids)

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_time is None:
            return None
        return (self.first_token_time - self.arrival_time) * 1000.0


@dataclass
class EngineOutput:
    request_id: str
    token_ids: list[int]
    text: str
    finish_reason: FinishReason
    ttft_ms: Optional[float]
    decode_tokens: int
    elapsed_s: float
    # Present when sampling.logprobs > 0 (same entries as out_logprobs).
    logprobs: Optional[list] = None
    # Prompt tokens served from the prefix cache (usage detail).
    cached_tokens: int = 0
