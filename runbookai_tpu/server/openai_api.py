"""OpenAI-compatible HTTP serving surface over the continuous-batching engine.

``runbook serve`` exposes the in-tree TPU serving engine the way the
ecosystem expects a model server to look (vLLM/TGI-style), so existing
OpenAI-client tooling can point at a TPU slice with no code changes:

- ``POST /v1/chat/completions`` — non-streaming and ``stream: true`` (SSE
  ``data:`` chunks, ``[DONE]`` terminator).
- ``GET /v1/models`` — the served catalog: the single model (plus its
  LoRA adapters), or under ``llm.models`` every model group with its
  replica count and group-local adapters.
- ``GET /healthz`` — liveness + engine metrics snapshot (taken under the
  engine's step lock) + uptime + KV-pool pressure.
- ``GET /metrics`` — Prometheus text exposition of the process registry
  (``runbookai_tpu.utils.metrics``): request/latency per route, engine
  TTFT/TPOT histograms, KV gauges, agent tool counters, and (when
  ``llm.slo`` objectives are configured) the ``runbook_slo_*`` series.
- ``GET /debug/steps?n=N`` — the engine flight recorder's last N per-step
  records (``engine/flight_recorder.py``): dispatch kind, tokens,
  occupancy (total + per priority class), queue depth, KV pressure, wall
  split; fleet deployments merge every replica's ring into one
  ts-ordered timeline.
- ``GET /debug/workload`` — live workload fingerprints + plan-drift
  (``runbookai_tpu/obs``): per served model group, the live traffic
  folded into the autotuner's ``Workload`` schema with its drift score
  against the serving plan's provenance workload, plus a merged
  fleet-wide view.
- ``GET /tenants`` — live tenant-accounting state (``sched/tenants.py``):
  per-tenant policy, bucket levels, admit/throttle counters.

Multi-model routing (``llm.models`` → ``runbookai_tpu/fleet``): the
request's ``model`` field resolves to a served model group (adapter
names resolve within their owning group; unknown names are 404s, never
silent base-model serving), and EVERYTHING downstream — prompt
encoding, sampling limits, admission page estimates, the stream itself
— uses the resolved group's tokenizer/chat-format/engine. A tenant may
be pinned to one group (``llm.tenants.keys.<name>.model``): requests
without a model field route there, explicit different groups are 403s.

Multi-tenant admission (``llm.tenants`` → ``runbookai_tpu/sched``): every
chat/completions request resolves its tenant from ``Authorization:
Bearer`` / ``x-api-key`` and must pass the tenant's rate, token-budget,
and in-flight KV-page buckets BEFORE enqueue — a throttled request is
answered ``429`` naming the failing bucket, with ``Retry-After``, and
never consumes an engine slot. Requests carry a
priority class (the tenant's configured class, or an explicit
``x-priority: interactive|batch`` header) into the engine's
weighted-deficit scheduler; fleet sheds and engine pool-pressure aborts
answer ``503`` with ``Retry-After``.

Every response carries an ``x-request-id`` header (client-supplied value
echoed, else generated); the id is attached to the handler thread's tracer
context and carried through the async engine into its span records, so a
trace JSONL line joins back to the request that produced it.

Architecture: a ``ThreadingHTTPServer`` (stdlib; no web framework in the
image) with a dedicated asyncio loop thread that owns the
:class:`~runbookai_tpu.engine.async_engine.AsyncEngine` — request handlers
bridge with ``run_coroutine_threadsafe``, so concurrent HTTP requests batch
together inside the engine exactly like concurrent agent investigations do.
No reference counterpart (RunbookAI calls hosted APIs; SURVEY.md §2.2).
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
import uuid
from concurrent.futures import TimeoutError as _FutTimeout  # builtin alias 3.11+, distinct on 3.10
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from runbookai_tpu.engine.request import FinishReason, FleetSaturated
from runbookai_tpu.sched import (
    CLASS_NAMES,
    PRIORITY_INTERACTIVE,
    class_priority,
)
from runbookai_tpu.utils.metrics import REQUEST_LATENCY_BUCKETS, get_registry
from runbookai_tpu.utils.trace import get_tracer

# Bounded route-label cardinality: anything else is scraped as "other".
_KNOWN_ROUTES = frozenset((
    "/v1/chat/completions", "/v1/completions", "/v1/embeddings",
    "/v1/adapters", "/v1/models", "/healthz", "/metrics", "/debug/steps",
    "/debug/workload", "/debug/incidents", "/debug/query", "/tenants",
))

# Every status this server emits; anything novel scrapes as "other" so the
# status label stays a statically bounded set (RBK010 contract).
_KNOWN_STATUSES = frozenset((
    "200", "400", "403", "404", "429", "500", "503", "504",
))

# Retry-After for fleet sheds / engine pool-pressure 503s: the backlog
# drains in engine-step time, so "about a second" is the honest hint (a
# tenant throttle's Retry-After is computed from its bucket instead).
_SHED_RETRY_AFTER_S = 1


def messages_to_prompt_parts(messages: list[dict[str, Any]]):
    """OpenAI messages -> (system, history, user) for build_chat_prompt."""
    system = ""
    turns: list[tuple[str, str]] = []
    for m in messages:
        role = m.get("role", "user")
        content = m.get("content") or ""
        if isinstance(content, list):  # content-part arrays
            content = "".join(p.get("text", "") for p in content
                              if isinstance(p, dict))
        if role in ("system", "developer"):
            # 'developer' is OpenAI's successor to 'system' — same slot.
            system = content if not system else f"{system}\n{content}"
        elif role in ("user", "assistant"):
            turns.append((role, content))
        elif role == "tool":
            # Tool-result round-trips: fold the result into the transcript
            # as a user-visible observation (our chat template has no
            # separate tool role) instead of silently dropping it.
            tool_id = m.get("tool_call_id") or m.get("name") or "tool"
            turns.append(("user", f"[tool result {tool_id}]\n{content}"))
        else:
            raise ValueError(f"unsupported message role {role!r}")
    if turns and turns[-1][0] == "assistant":
        # Assistant-prefill (trailing assistant message) is not supported
        # by the chat template; rendering an empty user turn would degrade
        # the prompt silently. Refuse loudly (maps to HTTP 400). A
        # system-only request stays valid (empty user turn, as before).
        raise ValueError(
            "the last non-system message must be a user or tool message; "
            "assistant prefill is not supported")
    user = turns.pop()[1] if turns else ""
    return system, turns, user


class _EngineBridge:
    """Owns the asyncio loop thread the AsyncEngine lives on."""

    def __init__(self, client):
        self.client = client  # JaxTpuClient
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self.loop.run_forever, name="serve-loop", daemon=True)
        self._thread.start()

    def run(self, coro, timeout: Optional[float] = None):
        return asyncio.run_coroutine_threadsafe(
            coro, self.loop).result(timeout)

    def stream(self, agen, timeout: Optional[float] = None):
        """Drain an async generator from a plain thread, yielding items.

        On a per-item timeout the pending ``__anext__`` task is CANCELLED
        on the loop first — that unwinds the generator's suspended await so
        its ``finally`` (the engine-abort path) actually runs — and only
        then is ``aclose`` awaited; closing a still-running generator would
        raise RuntimeError and leak the engine request."""
        sentinel = object()

        async def _next():
            try:
                return await agen.__anext__()
            except StopAsyncIteration:
                return sentinel

        while True:
            fut = asyncio.run_coroutine_threadsafe(_next(), self.loop)
            try:
                item = fut.result(timeout)
            except _FutTimeout:
                fut.cancel()

                async def _close():
                    try:
                        await agen.aclose()
                    except RuntimeError:
                        pass  # cancellation still unwinding

                try:
                    asyncio.run_coroutine_threadsafe(
                        _close(), self.loop).result(10)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
                raise TimeoutError("stream item timed out")
            if item is sentinel:
                return
            yield item

    def shutdown(self) -> None:
        try:
            self.run(self.client.shutdown(), timeout=10)
        except Exception:  # noqa: BLE001 — best-effort teardown
            pass
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=5)


def _logprob_entry(tokenizer, e: dict, top_n: int) -> dict:
    """Engine logprob record → OpenAI chat-completions schema entry."""

    def token_fields(tid: int) -> dict:
        # id_to_bytes round-trips tokens that are PARTIAL UTF-8 sequences
        # (byte-level BPE splits characters across tokens); decode([tid])
        # would corrupt them to U+FFFD and the bytes field exists so
        # clients can reassemble exactly these splits.
        raw = tokenizer.id_to_bytes(tid)
        return {"token": raw.decode("utf-8", errors="replace"),
                "bytes": list(raw)}

    out = token_fields(e["token_id"]) | {"logprob": e["logprob"]}
    if top_n:
        out["top_logprobs"] = [
            token_fields(t) | {"logprob": lp}
            for t, lp in e["top"][:top_n]]
    else:
        out["top_logprobs"] = []
    return out


def parse_openai_sampling(body: dict, client, tokenizer=None,
                          defaults=None) -> tuple[Any, int, int]:
    """Shared OpenAI sampling-field parsing for the chat and legacy
    completions endpoints: stop, n, logprobs, penalties, seed,
    logit_bias, max_tokens (and its max_completion_tokens alias).
    Returns (sampling, n, top_logprobs); raises ValueError on invalid
    input (the handlers map that to HTTP 400). ``tokenizer`` and
    ``defaults`` are the RESOLVED model group's pieces under multi-model
    serving — stop ids and the logit_bias vocab check are per group, and
    a group's derived config (``llm.models[].overrides``) supplies the
    temperature/top_p/top_k/max_new_tokens fallbacks for fields the
    request leaves unset. Both default to the client's."""
    from runbookai_tpu.engine.request import SamplingParams

    tokenizer = tokenizer if tokenizer is not None else client.tokenizer
    defaults = defaults if defaults is not None else client
    stop = body.get("stop") or []
    if isinstance(stop, str):
        stop = [stop]
    if not all(isinstance(s, str) for s in stop):
        raise ValueError("stop must be a string or list of strings")
    if len(stop) > 4:
        raise ValueError("at most 4 stop sequences")
    n = int(body.get("n", 1))
    if not 1 <= n <= 8:
        raise ValueError("n must be in [1, 8]")
    want_logprobs = bool(body.get("logprobs"))
    top_logprobs = int(body.get("top_logprobs") or 0)
    if top_logprobs and not want_logprobs:
        raise ValueError("top_logprobs requires logprobs: true")
    if not 0 <= top_logprobs <= 20:
        raise ValueError("top_logprobs must be 0..20")
    # `or 0.0`: OpenAI marks these nullable (null == default).
    presence = float(body.get("presence_penalty") or 0.0)
    frequency = float(body.get("frequency_penalty") or 0.0)
    if not -2.0 <= presence <= 2.0:
        raise ValueError("presence_penalty must be in [-2, 2]")
    if not -2.0 <= frequency <= 2.0:
        raise ValueError("frequency_penalty must be in [-2, 2]")
    seed = body.get("seed")
    if seed is not None:
        seed = int(seed)
    lb = body.get("logit_bias") or {}
    if not isinstance(lb, dict):
        raise ValueError("logit_bias must be an object of token_id -> bias")
    logit_bias = []
    for tok_id, b_val in lb.items():
        b_val = float(b_val)
        if not -100.0 <= b_val <= 100.0:
            raise ValueError("logit_bias values must be in [-100, 100]")
        tid = int(tok_id)
        if not 0 <= tid < tokenizer.vocab_size:
            raise ValueError(f"logit_bias token id {tid} out of vocab range")
        logit_bias.append((tid, b_val))
    sampling = SamplingParams(
        temperature=float(body.get("temperature", defaults.temperature)),
        top_p=float(body.get("top_p", defaults.top_p)),
        top_k=int(body.get("top_k", defaults.top_k)),
        max_new_tokens=int(body.get("max_tokens")
                           or body.get("max_completion_tokens")
                           or defaults.max_new_tokens),
        stop_token_ids=(tokenizer.eot_id, tokenizer.eos_id),
        stop_strings=tuple(stop),
        logprobs=((top_logprobs or 1) if want_logprobs else 0),
        presence_penalty=presence,
        frequency_penalty=frequency,
        seed=seed,
        logit_bias=tuple(logit_bias),
    )
    return sampling, n, top_logprobs


def _completion_payload(model: str, content: str, usage: dict,
                        finish: str = "stop") -> dict:
    return {
        "id": f"chatcmpl-{uuid.uuid4().hex[:12]}",
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{
            "index": 0,
            "message": {"role": "assistant", "content": content},
            "finish_reason": finish,
        }],
        "usage": {
            "prompt_tokens": usage.get("prompt_tokens", 0),
            "completion_tokens": usage.get("completion_tokens", 0),
            "total_tokens": (usage.get("prompt_tokens", 0)
                             + usage.get("completion_tokens", 0)),
        },
    }


def _chunk_payload(model: str, delta: dict, finish: Optional[str],
                   chunk_id: str) -> dict:
    return {
        "id": chunk_id,
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [{"index": 0, "delta": delta, "finish_reason": finish}],
    }


def make_handler(bridge: _EngineBridge, model_name: str,
                 request_timeout: float,
                 allow_runtime_adapters: bool = False,
                 embedder=None):
    client = bridge.client
    _embed_mutex = threading.Lock()
    started_at = time.time()
    registry = get_registry()
    requests_total = registry.counter(
        "runbook_requests_total", "HTTP requests served",
        labels=("route", "method", "status"))
    request_latency = registry.histogram(
        "runbook_request_latency_seconds", "HTTP request handling latency",
        labels=("route", "method"), buckets=REQUEST_LATENCY_BUCKETS)

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args) -> None:  # quiet; metrics via /metrics
            pass

        def send_response(self, code: int, message=None) -> None:
            # Every response (JSON, SSE, errors) echoes the correlation id;
            # the hook also records the status for the route metrics.
            super().send_response(code, message)
            self._status = code
            rid = getattr(self, "_request_id", None)
            if rid:
                self.send_header("x-request-id", rid)

        def _dispatch(self, method: str, fn) -> None:
            """Route wrapper: request-id propagation, tracer context, and
            per-route request/latency instrumentation."""
            self._request_id = (self.headers.get("x-request-id")
                                or f"req-{uuid.uuid4().hex[:16]}")
            self._status = 0
            # Route label from the bare path (query strings must neither
            # split the label cardinality nor 404 a known route).
            bare = self.path.partition("?")[0]
            route = bare if bare in _KNOWN_ROUTES else "other"
            tracer = get_tracer()
            tracer.set_context(request_id=self._request_id)
            t0 = time.perf_counter()
            try:
                with tracer.span("server.request", route=route,
                                 method=method):
                    fn()
            finally:
                tracer.clear_context()
                status = str(self._status or 500)
                requests_total.labels(
                    route=route, method=method,
                    status=status if status in _KNOWN_STATUSES
                    else "other").inc()
                request_latency.labels(route=route, method=method).observe(
                    time.perf_counter() - t0)

        def _json(self, code: int, payload: dict,
                  headers: Optional[dict] = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for key, value in (headers or {}).items():
                self.send_header(key, str(value))
            self.end_headers()
            self.wfile.write(body)

        def _error(self, code: int, message: str,
                   retry_after: Optional[float] = None,
                   err_type: str = "invalid_request_error") -> None:
            import math

            headers = None
            if retry_after is not None:
                # Both throttles (429) and sheds (503) tell the client
                # WHEN to come back — integer seconds, never 0 (a zero
                # would read as "retry immediately", i.e. a retry storm).
                headers = {"Retry-After": max(1, math.ceil(retry_after))}
            self._json(code, {"error": {"message": message,
                                        "type": err_type}},
                       headers=headers)

        def _api_key(self) -> Optional[str]:
            """Tenant key of this request: ``Authorization: Bearer`` wins,
            ``x-api-key`` is the fallback, absent = anonymous (pools
            under the default tenant)."""
            auth = self.headers.get("Authorization") or ""
            if auth.lower().startswith("bearer "):
                return auth[7:].strip() or None
            return self.headers.get("x-api-key")

        def _priority_override(self) -> Optional[int]:
            """Explicit ``x-priority`` header, or None to follow the
            tenant's configured class. Only the canonical class names
            are accepted from the NETWORK — arbitrary ints would let any
            client mint a priority class with an arbitrarily large
            scheduler weight (internal callers keep free-form ints on
            the engine API). Raises ValueError on junk (→ 400)."""
            hdr = self.headers.get("x-priority")
            if hdr is None:
                return None
            priority = class_priority(hdr)
            if priority not in CLASS_NAMES:
                raise ValueError(
                    f"x-priority must be one of "
                    f"{sorted(CLASS_NAMES.values())}, got {hdr!r}")
            return priority

        def _admit_tenant(self, prompt_tokens: int, max_new_tokens: int,
                          kv_pages: float = 0.0):
            """Tenant admission BEFORE enqueue (sched/tenants.py):
            returns ``(admission, priority)`` — admission is None when no
            governor is configured. A throttled request is answered 429 +
            Retry-After here and ``(None, None)`` is returned; the caller
            must then bail without touching the engine. ``kv_pages`` is
            the request's estimated worst-case KV footprint
            (ceil((prompt + n·max_new)/page_size)) — tenants with a
            kv_page_limit reserve it for the request's lifetime, and the
            429 names WHICH bucket refused."""
            # Header parse FIRST: a junk x-priority must 400 before any
            # bucket is charged (no refund bookkeeping for bad input).
            override = self._priority_override()  # caller catches ValueError
            governor = getattr(client, "tenants", None)
            admission = None
            if governor is not None:
                admission = governor.admit(self._api_key(), prompt_tokens,
                                           max_new_tokens,
                                           kv_pages=kv_pages)
                if not admission.allowed:
                    if admission.reason == "kv_pages_oversized":
                        # The request ALONE exceeds the tenant's page
                        # ledger: no amount of waiting admits it, so a
                        # retryable 429 would loop a compliant client
                        # forever — refuse it outright.
                        self._error(
                            400,
                            f"request exceeds tenant "
                            f"{admission.tenant!r}'s kv_page_limit "
                            f"(estimated pages > limit); shrink the "
                            f"prompt or max_tokens")
                        return None, None
                    limit = {"rate_limit": "rate limit",
                             "token_budget": "token budget",
                             "kv_pages": "kv page budget",
                             }.get(admission.reason, "limit")
                    self._error(
                        429,
                        f"tenant {admission.tenant!r} is over its {limit}; "
                        f"retry after {max(1.0, admission.retry_after_s):.0f}s",
                        retry_after=admission.retry_after_s,
                        err_type="rate_limit_error")
                    return None, None
            # Untenanted server traffic defaults to the interactive
            # class: a human is usually waiting on an HTTP response, and
            # batch tiers must OPT IN (tenant config or header).
            ceiling = (admission.priority if admission is not None
                       else PRIORITY_INTERACTIVE)
            if override is not None:
                # The header can DEMOTE a request below its tenant's
                # class, never promote past it — a tenant configured
                # batch must not self-escalate into the interactive tier
                # by setting a header.
                priority = min(override, ceiling)
            else:
                priority = ceiling
            return admission, priority

        def _settle_tenant(self, admission, actual_tokens: int) -> None:
            governor = getattr(client, "tenants", None)
            if governor is not None and admission is not None:
                governor.settle(admission, actual_tokens)

        def _resolve_model(self, requested):
            """Resolve the request's ``model`` field to the serving
            pieces: ``(model_out, adapter, engine, tokenizer,
            chat_format, page_size, sampling_defaults)`` — or ``None``
            with the error already sent (404 unknown model, 403
            tenant-pin violation).

            Multi-model fleets (``llm.models``) dispatch to the owning
            group: group name -> that group, adapter name -> its group
            with the adapter selected, absent -> the tenant's pinned
            group or the default. The single-model path is exactly the
            historical logic (adapter-as-model within the one engine).
            Everything downstream — prompt encoding, sampling limits,
            admission page estimates, the stream itself — uses the
            RESOLVED group's tokenizer/engine, so a request never mixes
            one model's tokenizer with another's replicas."""
            governor = getattr(client, "tenants", None)
            pinned = (governor.pinned_model(self._api_key())
                      if governor is not None else None)
            mm = getattr(client, "multi_model", None)
            if mm is not None:
                try:
                    group_name, adapter = mm.resolve(requested or pinned)
                except KeyError as e:
                    self._error(404, str(e.args[0]) if e.args else str(e))
                    return None
                if pinned is not None and group_name != pinned:
                    # Tenant-affine placement: the pin is an isolation
                    # boundary, not a default — a pinned tenant naming
                    # another group is refused, never silently re-routed.
                    self._error(
                        403,
                        f"tenant {governor.resolve(self._api_key())!r} "
                        f"is pinned to model {pinned!r}; requested "
                        f"{requested!r}", err_type="permission_error")
                    return None
                group = mm.groups[group_name]
                # The group's derived config supplies sampling
                # fallbacks (llm.models[].overrides — e.g. a per-group
                # max_new_tokens); client-level defaults otherwise.
                return ((requested or group_name), adapter, group.fleet,
                        group.tokenizer, group.chat_format,
                        group.page_size, group.llm_cfg or client)
            adapter = None
            if requested and requested != model_name:
                names = (client.core.lora.names
                         if client.core.lora is not None else [])
                if requested in names:
                    adapter = requested
                else:
                    # vLLM semantics: unknown model names are errors,
                    # not silent base-model serving.
                    self._error(404, f"model {requested!r} not found; "
                                     f"served: {[model_name] + names}")
                    return None
            return (requested or model_name, adapter, client.engine,
                    client.tokenizer, client.chat_format,
                    client.core.ecfg.page_size, client)

        def _read_json(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
            return body

        def do_GET(self) -> None:  # noqa: N802 — http.server API
            self._dispatch("GET", self._route_get)

        def do_POST(self) -> None:  # noqa: N802
            self._dispatch("POST", self._route_post)

        def _route_get(self) -> None:
            # Match every route on the bare path: a query string must not
            # 404 a known route the metrics just labeled as served.
            path, _, query = self.path.partition("?")
            if path == "/debug/steps":
                self._debug_steps(query)
                return
            if path == "/debug/workload":
                # Live workload fingerprints + plan-drift (obs/): per
                # served model group with a merged fleet-wide view.
                # Without a monitor the surface reports itself disabled
                # (not 404 — the CLI distinguishes "off" from "no
                # server"), matching /tenants.
                monitor = getattr(client, "workload_monitor", None)
                self._json(200, monitor.snapshot() if monitor is not None
                           else {"enabled": False, "models": {}})
                return
            if path == "/debug/query":
                self._debug_query(query)
                return
            if path == "/debug/incidents":
                # Live incident feed + captured-bundle listing
                # (obs/incident.py). Without a monitor the surface
                # reports itself disabled (not 404 — the CLI
                # distinguishes "off" from "no server"), matching
                # /debug/workload and /tenants.
                monitor = getattr(client, "incident_monitor", None)
                self._json(200, monitor.snapshot(full=True)
                           if monitor is not None
                           else {"enabled": False, "open": []})
                return
            if path == "/v1/models":
                mm = getattr(client, "multi_model", None)
                if mm is not None:
                    # Full served catalog: every model group (with its
                    # replica count) and every group's adapters, each
                    # adapter parented to its group.
                    self._json(200, {"object": "list",
                                     "data": mm.served_models()})
                    return
                models = [{"id": model_name, "object": "model",
                           "owned_by": "runbookai-tpu"}]
                if client.core.lora is not None:
                    # vLLM-style: LoRA adapters are served as model names.
                    models += [{"id": n, "object": "model",
                                "owned_by": "runbookai-tpu",
                                "parent": model_name}
                               for n in client.core.lora.names]
                self._json(200, {"object": "list", "data": models})
            elif path == "/healthz":
                # Snapshot under the engine's step lock: the loop thread
                # mutates several keys per step, so a lock-free shallow
                # copy could pair a new decode_tokens with an old
                # decode_time_s. Bounded wait only — a step that is busy
                # compiling a new batch shape can hold the lock for tens
                # of seconds, and a liveness probe that blocks that long
                # gets the pod killed mid-compile. A torn-but-live
                # snapshot beats a dead prober.
                body = {"status": "ok", "model": model_name,
                        "uptime_s": round(time.time() - started_at, 3)}
                snapshot = getattr(client.engine, "health_snapshot", None)
                if snapshot is not None:
                    # Engine fleet: summed metrics dict (the contract keys
                    # become fleet-wide totals), pooled KV stats, plus the
                    # per-replica breakdown and router state.
                    body.update(snapshot())
                else:
                    lock = getattr(client.engine, "_lock", None)
                    locked = lock is not None and lock.acquire(timeout=0.5)
                    try:
                        m = dict(client.core.metrics)
                    finally:
                        if locked:
                            lock.release()
                    kv = client.core.kv
                    body["kv"] = {
                        "pages_total": kv.allocator.num_pages,
                        "pages_in_use": kv.pages_in_use,
                        "pages_cached": kv.allocator.cached_pages,
                        "utilization": round(kv.utilization(), 4)}
                    body["metrics"] = m
                slo = getattr(client, "slo_monitor", None)
                if slo is not None and slo.objectives:
                    # Live SLO state (utils/slo.py): targets vs current
                    # percentiles and the burn ratio per objective — the
                    # feedback signal SLO-aware scheduling will consume.
                    body["slo"] = slo.evaluate()
                monitor = getattr(client, "workload_monitor", None)
                if monitor is not None:
                    # Live workload fingerprint + plan-drift (obs/):
                    # per-group for multi-model fleets, merged
                    # fleet-wide like debug_steps.
                    body["workload"] = monitor.snapshot()
                store = getattr(client, "tsdb", None)
                if store is not None:
                    # Metric-history accounting (obs/tsdb.py): series /
                    # sample / memory bounds of the embedded store that
                    # /debug/query evaluates against. Block present
                    # only when a store is attached (llm.obs.tsdb).
                    body["history"] = store.snapshot()
                incidents = getattr(client, "incident_monitor", None)
                if incidents is not None:
                    # Incident feed (obs/incident.py): open incidents +
                    # per-signal totals. Block present only when a
                    # monitor is attached, and totals carry only
                    # signals that HAVE incidents — absence-not-zero,
                    # the runbook_slo_* contract.
                    body["incidents"] = incidents.snapshot()
                self._json(200, body)
            elif path == "/tenants":
                # Tenant accounting state (sched/tenants.py): configured
                # policies, live bucket levels, admit/throttle counters —
                # the `runbook tenants` CLI renders this. Without a
                # governor the surface reports itself disabled (not 404:
                # the CLI distinguishes "off" from "no server").
                governor = getattr(client, "tenants", None)
                self._json(200, governor.snapshot() if governor is not None
                           else {"enabled": False, "tenants": {}})
            elif path == "/metrics":
                body = registry.render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._error(404, f"no route {self.path}")

        def _debug_steps(self, query: str) -> None:
            """``GET /debug/steps[?n=N]`` — the engine flight recorder's
            last N per-step records (dispatch kind, tokens, occupancy,
            queue depth, KV pressure, wall split). Single engine and
            fleet both serve it: ``AsyncFleet.debug_steps`` merges the
            replicas' rings into one ts-ordered timeline."""
            n = 128
            for part in query.split("&"):
                if part.startswith("n="):
                    try:
                        n = max(0, int(part[2:]))
                    except ValueError:
                        self._error(400, f"bad n value {part[2:]!r}")
                        return
            snap_fn = getattr(client.engine, "debug_steps", None)
            if snap_fn is None:
                self._error(404, "engine has no flight recorder")
                return
            self._json(200, snap_fn(n))

        def _debug_query(self, query: str) -> None:
            """``GET /debug/query?expr=EXPR[&range=5m]`` — PromQL-lite
            over the embedded time-series store (obs/tsdb.py +
            obs/query.py). The body is the evaluator's CANONICAL bytes
            (sorted keys, compact separators), so the query-determinism
            pin covers the HTTP surface too. Without a store the
            surface reports itself disabled (not 404 — the CLI
            distinguishes "off" from "no server"), matching
            /debug/workload."""
            import urllib.parse

            from runbookai_tpu.obs.query import (
                QueryError,
                evaluate,
                parse_duration,
                result_json,
            )

            store = getattr(client, "tsdb", None)
            params = urllib.parse.parse_qs(query)
            expr = (params.get("expr") or [""])[0]
            if store is None:
                self._json(200, {"enabled": False, "expr": expr,
                                 "result": []})
                return
            if not expr:
                self._error(400, "expr parameter is required")
                return
            range_s = None
            raw_range = (params.get("range") or [None])[0]
            try:
                if raw_range:
                    range_s = parse_duration(raw_range)
                doc = evaluate(store, expr,
                               **({"default_range_s": range_s}
                                  if range_s is not None else {}))
            except QueryError as e:
                self._error(400, str(e))
                return
            body = result_json(doc).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _route_post(self) -> None:
            if self.path == "/v1/adapters":
                self._load_adapter()
                return
            if self.path == "/v1/embeddings":
                self._embeddings()
                return
            if self.path == "/v1/completions":
                self._legacy_completions()
                return
            if self.path != "/v1/chat/completions":
                self._error(404, f"no route {self.path}")
                return
            try:
                body = self._read_json()
                messages = body.get("messages") or []
                if not messages:
                    raise ValueError("messages is required")
                system, history, user = messages_to_prompt_parts(messages)
                # Model-field routing: a multi-model fleet dispatches to
                # the owning group (unknown model -> 404, tenant pin ->
                # 403); single-model keeps vLLM-style adapter-as-model.
                resolved = self._resolve_model(body.get("model"))
                if resolved is None:
                    return  # 404/403 already sent
                (model_out, adapter, eng, tok, chat_fmt, page_size,
                 sp_defaults) = resolved
                # Client-supplied values: coercion failures are 400s too.
                sampling, n, top_logprobs = parse_openai_sampling(
                    body, client, tokenizer=tok, defaults=sp_defaults)
                # response_format json_object -> grammar-constrained
                # decoding (the engine's guided JSON automaton): output is
                # a valid-JSON prefix by construction, and a COMPLETE
                # parseable document whenever finish_reason != "length"
                # (max_tokens can still truncate mid-document).
                rf = body.get("response_format") or {}
                if not isinstance(rf, dict):
                    # {"response_format": "json_object"} is a common client
                    # mistake; coercing to text would silently drop the
                    # JSON guarantee the caller asked for.
                    raise ValueError("response_format must be an object "
                                     "like {\"type\": \"json_object\"}")
                rf_type = rf.get("type", "text")
                if rf_type not in ("text", "json_object"):
                    raise ValueError(
                        "response_format.type must be text or json_object")
                sampling.guided = ("json" if rf_type == "json_object"
                                   else None)
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._error(400, str(e))
                return

            import math

            from runbookai_tpu.model.chat_template import build_chat_prompt

            prompt = build_chat_prompt(system, user, history=history,
                                       fmt=chat_fmt)
            ids = tok.encode(prompt)

            # Tenant admission BEFORE the engine sees anything: a tenant
            # over its rate limit, token budget, or in-flight KV-page
            # ledger gets 429 + Retry-After and never consumes a slot, a
            # KV page, or a queue entry. The page estimate is the
            # request's worst case at the RESOLVED group's page size:
            # the n choices run as n CONCURRENT engine requests, each
            # holding its own live copy of the prompt's pages while it
            # decodes (in-flight prefills don't share; only retired
            # prefix pages do) — so the prompt counts n times here even
            # though the token budget counts it once.
            try:
                admission, priority = self._admit_tenant(
                    len(ids), n * sampling.max_new_tokens,
                    kv_pages=math.ceil(
                        n * (len(ids) + sampling.max_new_tokens)
                        / max(1, page_size)))
            except ValueError as e:  # junk x-priority header
                self._error(400, str(e))
                return
            if priority is None:
                return  # throttled; 429 already sent

            # Replica failover: while EVERY replica of the resolved
            # group is quarantined (supervisor mid-rebuild), nothing can
            # be placed — answer a real 503 with Retry-After now instead
            # of burning a shed/abort on a request that cannot be
            # served. Healthy siblings of a multi-model fleet are
            # unaffected (the check is per resolved group).
            failover = getattr(eng, "failing_over", None)
            if failover is not None and failover():
                self._settle_tenant(admission, 0)
                self._error(503, "replica failover in progress (no "
                                 "replica available; retry shortly)",
                            retry_after=_SHED_RETRY_AFTER_S)
                return
            try:
                if body.get("stream"):
                    if n != 1:
                        self._settle_tenant(admission, 0)
                        self._error(400, "stream with n > 1 is unsupported")
                        return
                    # Fleet shedding: refuse BEFORE committing SSE headers
                    # so a saturated pod answers a real 503 (the check-
                    # then-route race falls back to an in-stream error
                    # event inside _stream_response). The RESOLVED
                    # group's saturation is what matters — one model's
                    # flood must not shed a healthy sibling's stream.
                    saturated = getattr(eng, "is_saturated", None)
                    if saturated is not None and saturated():
                        self._settle_tenant(admission, 0)
                        self._error(503, "all fleet replicas are "
                                         "saturated (request shed)",
                                    retry_after=_SHED_RETRY_AFTER_S)
                        return
                    so = body.get("stream_options") or {}
                    self._stream_response(
                        ids, sampling, adapter,
                        top_logprobs=top_logprobs,
                        include_usage=bool(so.get("include_usage")),
                        priority=priority, admission=admission,
                        engine=eng, tokenizer=tok, model=model_out)
                else:
                    # The engine-side timeout ABORTS a stalled request
                    # (frees slot + KV pages) before raising; the bridge
                    # timeout is just a belt over a wedged loop thread.
                    # n > 1 choices submit concurrently: the engine batches
                    # them in one decode dispatch and the shared prompt
                    # prefix rides the page cache.
                    def _choice_sampling(i: int):
                        # A fixed seed must still produce n DISTINCT
                        # choices: choice i samples under seed+i (choice
                        # 0 reproduces the n=1 output for that seed).
                        if sampling.seed is None or i == 0:
                            return sampling
                        import dataclasses as _dc

                        return _dc.replace(sampling,
                                           seed=sampling.seed + i)

                    async def _gen_n():
                        # return_exceptions: every sibling runs to its own
                        # terminal state (each generate aborts itself on
                        # its engine-side timeout) — nothing keeps decoding
                        # unobserved after an error response.
                        return await asyncio.gather(*[
                            eng.generate(
                                ids, _choice_sampling(i),
                                timeout_s=request_timeout,
                                priority=priority, adapter=adapter,
                                request_id=self._request_id)
                            for i in range(n)], return_exceptions=True)

                    outs = bridge.run(_gen_n(), timeout=request_timeout + 60)
                    if any(isinstance(o, BaseException) for o in outs):
                        self._settle_tenant(admission, 0)
                        err = next(o for o in outs
                                   if isinstance(o, BaseException))
                        if isinstance(err, (TimeoutError, _FutTimeout)):
                            self._error(504, "generation timed out")
                        else:
                            raise err
                        return
                    if any(o.finish_reason.value == "aborted" for o in outs):
                        # Admission fail-fast (prompt can never fit), a
                        # fleet shed, or a mid-decode abort: an error, not
                        # a completion — and a failed request is never
                        # billed against the tenant's budget.
                        self._settle_tenant(admission, 0)
                        self._error(503, "request aborted by the engine "
                                         "(insufficient KV capacity)",
                                    retry_after=_SHED_RETRY_AFTER_S)
                        return
                    self._settle_tenant(
                        admission,
                        len(ids) + sum(o.decode_tokens for o in outs))

                    def choice(i, o):
                        c = {"index": i,
                             "message": {"role": "assistant",
                                         "content": o.text},
                             "finish_reason": ("length"
                                               if o.finish_reason.value
                                               == "max_tokens"
                                               else "stop")}
                        if o.logprobs is not None:
                            c["logprobs"] = {"content": [
                                _logprob_entry(tok, e, top_logprobs)
                                for e in o.logprobs]}
                        return c

                    payload = _completion_payload(
                        model_out, "",
                        {"prompt_tokens": len(ids),
                         "completion_tokens": sum(o.decode_tokens
                                                  for o in outs)})
                    # prompt_tokens is counted ONCE for n>1 (the choices
                    # share one prompt), so cached_tokens must stay a
                    # subset of it: max() = how much of that one counted
                    # prompt was cache-served. Later choices hitting the
                    # prefix the first published is internal dedupe, not
                    # request-level caching — summing it would report
                    # cached > prompt_tokens (negative uncached math for
                    # OpenAI-schema clients).
                    payload["usage"]["prompt_tokens_details"] = {
                        "cached_tokens": max(o.cached_tokens for o in outs)}
                    payload["choices"] = [choice(i, o)
                                          for i, o in enumerate(outs)]
                    self._json(200, payload)
            except (TimeoutError, _FutTimeout):
                self._settle_tenant(admission, 0)
                self._error(504, "generation timed out")
            except BrokenPipeError:
                # Client went away; engine abort handled in stream path.
                # The reservation is refunded (failed work isn't billed).
                self._settle_tenant(admission, 0)

        def _legacy_completions(self) -> None:
            """Legacy `/v1/completions`: raw-prompt text completion, no
            chat template. ``prompt`` may be a string or list of strings
            (OpenAI returns len(prompt) * n choices, prompt-major); all
            shared sampling fields apply, ``logprobs`` is the classic
            int (top-N per sampled token), and adapter-as-model routing
            matches the chat endpoint. Streaming is not offered on the
            legacy surface — use `/v1/chat/completions`."""
            admission = None
            try:
                body = self._read_json()
                if body.get("stream"):
                    raise ValueError(
                        "stream is not supported on /v1/completions; "
                        "use /v1/chat/completions")
                prompts = body.get("prompt")
                if isinstance(prompts, str):
                    prompts = [prompts]
                if (not prompts or not isinstance(prompts, list)
                        or not all(isinstance(p, str) for p in prompts)):
                    raise ValueError(
                        "prompt must be a string or list of strings")
                if len(prompts) > 8:
                    raise ValueError("at most 8 prompts per request")
                # Same routing policy as chat: model-field dispatch
                # (multi-model groups / adapter-as-model), unknown names
                # are 404s — never silent base-model serving.
                requested = body.get("model")
                resolved = self._resolve_model(requested)
                if resolved is None:
                    return  # 404/403 already sent
                (model_out, adapter, eng, tok, _fmt, page_size,
                 sp_defaults) = resolved
                sampling, n, _ = parse_openai_sampling(
                    body, client, tokenizer=tok, defaults=sp_defaults)
                # Classic logprobs is an int: top-N alternatives per token.
                lp_n = int(body.get("logprobs") or 0)
                if not 0 <= lp_n <= 5:
                    raise ValueError("logprobs must be 0..5")
                sampling.logprobs = lp_n
                echo = bool(body.get("echo"))
                # Tokenize each prompt ONCE: the same ids feed the engine
                # and the usage count, so they cannot disagree.
                all_ids = [tok.encode(p) for p in prompts]

                # Same tenant gate as the chat endpoint: the reservation
                # covers every prompt and all n completions per prompt
                # (tokens AND estimated KV pages — each of the n×len(
                # prompts) concurrent requests holds its own live prompt
                # copy, so prompts count n times in the page estimate).
                import math

                prompt_total = sum(len(ids) for ids in all_ids)
                reserve_new = n * len(all_ids) * sampling.max_new_tokens
                admission, priority = self._admit_tenant(
                    prompt_total, reserve_new,
                    kv_pages=math.ceil(
                        (n * prompt_total + reserve_new)
                        / max(1, page_size)))
                if priority is None:
                    return  # throttled; 429 + Retry-After already sent

                async def _gen_all():
                    import dataclasses as _dc

                    jobs = []
                    for ids in all_ids:
                        for i in range(n):
                            sp = sampling
                            if sampling.seed is not None and i:
                                sp = _dc.replace(sampling,
                                                 seed=sampling.seed + i)
                            jobs.append(eng.generate(
                                ids, sp, timeout_s=request_timeout,
                                priority=priority, adapter=adapter,
                                request_id=self._request_id))
                    return await asyncio.gather(*jobs,
                                                return_exceptions=True)

                outs = bridge.run(_gen_all(), timeout=request_timeout + 60)
                if any(isinstance(o, BaseException) for o in outs):
                    self._settle_tenant(admission, 0)
                    err = next(o for o in outs
                               if isinstance(o, BaseException))
                    if isinstance(err, (TimeoutError, _FutTimeout)):
                        self._error(504, "generation timed out")
                        return
                    raise err
                if any(o.finish_reason.value == "aborted" for o in outs):
                    self._settle_tenant(admission, 0)
                    self._error(503, "request aborted by the engine "
                                     "(insufficient KV capacity)",
                                retry_after=_SHED_RETRY_AFTER_S)
                    return
                self._settle_tenant(
                    admission,
                    prompt_total + sum(o.decode_tokens for o in outs))

                def legacy_lp(o, text_start: int):
                    if not lp_n or not o.logprobs:
                        return None
                    tokens, tlps, tops, offsets = [], [], [], []
                    off = text_start
                    for e in o.logprobs:
                        raw = tok.id_to_bytes(
                            e["token_id"]).decode("utf-8", "replace")
                        tokens.append(raw)
                        tlps.append(e["logprob"])
                        tops.append({
                            tok.id_to_bytes(t).decode(
                                "utf-8", "replace"): lp
                            for t, lp in e["top"][:lp_n]})
                        offsets.append(off)
                        off += len(raw)
                    return {"tokens": tokens, "token_logprobs": tlps,
                            "top_logprobs": tops, "text_offset": offsets}

                choices = []
                for pi, p in enumerate(prompts):
                    for i in range(n):
                        o = outs[pi * n + i]
                        choices.append({
                            "index": pi * n + i,
                            "text": (p + o.text) if echo else o.text,
                            "logprobs": legacy_lp(
                                o, len(p) if echo else 0),
                            "finish_reason": ("length"
                                              if o.finish_reason.value
                                              == "max_tokens" else "stop"),
                        })
                prompt_tokens = sum(len(ids) for ids in all_ids)
                completion_tokens = sum(o.decode_tokens for o in outs)
                self._json(200, {
                    "id": f"cmpl-{uuid.uuid4().hex[:12]}",
                    "object": "text_completion",
                    "created": int(time.time()),
                    "model": model_out,
                    "choices": choices,
                    "usage": {
                        "prompt_tokens": prompt_tokens,
                        "completion_tokens": completion_tokens,
                        "total_tokens": prompt_tokens + completion_tokens,
                    },
                })
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._settle_tenant(admission, 0)
                self._error(400, str(e))
            except (TimeoutError, _FutTimeout):
                self._settle_tenant(admission, 0)
                self._error(504, "generation timed out")
            except BrokenPipeError:
                self._settle_tenant(admission, 0)  # client went away

        def _embeddings(self) -> None:
            """OpenAI embeddings API over the on-device bge encoder (the
            same encoder the knowledge index uses)."""
            if embedder is None:
                self._error(400, "no embedder configured "
                                 "(knowledge.embedder.enabled + model_path)")
                return
            emb_model = getattr(embedder.cfg, "name", "bge")
            try:
                body = self._read_json()
                requested = body.get("model")
                if requested and requested != emb_model:
                    # Same policy as chat: no silent model substitution.
                    self._error(404, f"model {requested!r} not found; "
                                     f"embeddings model: {emb_model}")
                    return
                texts = body.get("input")
                if isinstance(texts, str):
                    texts = [texts]
                if (not isinstance(texts, list) or not texts
                        or not all(isinstance(t, str) for t in texts)):
                    raise ValueError(
                        "input must be a string or list of strings")
                if len(texts) > 256:
                    raise ValueError("at most 256 inputs per request")
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._error(400, str(e))
                return
            try:
                # One request at a time: encode bursts contend with decode
                # for the device, and the Embedder's cache/stats aren't
                # thread-safe across handler threads.
                with _embed_mutex:
                    vecs = embedder.embed_texts(texts)
                n_tokens = embedder.estimate_tokens(texts)
                self._json(200, {
                    "object": "list",
                    "model": emb_model,
                    "data": [{"object": "embedding", "index": i,
                              "embedding": [float(x) for x in v]}
                             for i, v in enumerate(vecs)],
                    "usage": {"prompt_tokens": n_tokens,
                              "total_tokens": n_tokens},
                })
            except BrokenPipeError:
                pass
            except Exception as e:  # noqa: BLE001 — compute failures -> 500
                self._error(500, f"embedding failed ({type(e).__name__})")

        def _load_adapter(self) -> None:
            """Hot-load a LoRA adapter into the running engine:
            ``POST /v1/adapters {"name": ..., "path": <PEFT dir>}``. The
            registry re-stacks and the engine swaps its params tree under
            the engine lock, so in-flight dispatches finish on the old
            tree and the next dispatch serves the new adapter."""
            if not allow_runtime_adapters:
                # Loading arbitrary server-side paths is an operator
                # action; gate it (vLLM gates its equivalent the same way).
                self._error(403, "runtime adapter loading is disabled; "
                                 "start with --allow-adapter-loading")
                return
            if getattr(client, "multi_model", None) is not None:
                # Runtime loads would need a target-group parameter and
                # per-group refresh; configure multi-model adapters in
                # llm.models[].adapters instead (loaded at startup).
                self._error(400, "runtime adapter loading is not "
                                 "supported with llm.models; configure "
                                 "llm.models[].adapters")
                return
            if client.core.lora is None:
                self._error(400, "engine has no LoRA registry (configure "
                                 "llm.lora_rank/lora_targets)")
                return
            try:
                body = self._read_json()
                name, path = body["name"], body["path"]
                if not isinstance(name, str) or not isinstance(path, str):
                    raise ValueError("name and path must be strings")
            except (ValueError, TypeError, KeyError,
                    json.JSONDecodeError) as e:
                self._error(400, f"expected {{name, path}}: {e}")
                return
            try:
                client.core.lora.load_peft_dir(name, path)
            except (OSError, TypeError, ValueError, KeyError) as e:
                # No raw OS error text: it would leak filesystem detail.
                import logging

                logging.getLogger(__name__).warning(
                    "adapter load %r failed: %s", name, e)
                self._error(400, f"could not load adapter {name!r} "
                                 f"({type(e).__name__})")
                return
            # Pre-stack on THIS thread (registry caches it); the engine
            # refresh then runs in a worker thread (loop stays live) and
            # only swaps the params dict. Even without it, submit()
            # detects a stale row count and refreshes safely.
            client.core.lora.stacked()
            try:
                bridge.run(client.engine.refresh_lora(), timeout=60)
            except (TimeoutError, _FutTimeout):
                self._error(504, f"adapter {name!r} registered but the "
                                 f"engine refresh timed out; it activates "
                                 f"on the next request")
                return
            self._json(200, {"loaded": name,
                             "adapters": client.core.lora.names})

        def _stream_response(self, ids, sampling, adapter=None,
                             top_logprobs: int = 0,
                             include_usage: bool = False,
                             priority: int = PRIORITY_INTERACTIVE,
                             admission=None, engine=None, tokenizer=None,
                             model: Optional[str] = None) -> None:
            from runbookai_tpu.model.jax_tpu import stream_text

            # The resolved model group's pieces (multi-model routing);
            # defaults keep the historical single-engine behavior for
            # direct callers.
            engine = engine if engine is not None else client.engine
            tokenizer = (tokenizer if tokenizer is not None
                         else client.tokenizer)
            model = model or model_name
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def send_chunk(payload: dict) -> None:
                data = f"data: {json.dumps(payload)}\n\n".encode()
                self.wfile.write(f"{len(data):x}\r\n".encode() + data
                                 + b"\r\n")
                self.wfile.flush()

            def send_terminator(extra: bytes = b"") -> None:
                done = extra + b"data: [DONE]\n\n"
                self.wfile.write(f"{len(done):x}\r\n".encode() + done
                                 + b"\r\n0\r\n\r\n")
                self.wfile.flush()

            chunk_id = f"chatcmpl-{uuid.uuid4().hex[:12]}"
            send_chunk(_chunk_payload(model, {"role": "assistant"},
                                      None, chunk_id))
            state: dict = {}
            # Shared with JaxTpuClient.chat_stream: one copy of the
            # incremental-UTF-8 / stop-token handling for all surfaces.
            # With logprobs, the live EngineRequest rides along (entries
            # accumulate on the engine thread; list reads are safe) and
            # each chunk carries the entries for tokens consumed since the
            # last chunk — OpenAI streams logprobs in the deltas.
            req_sink: list = []
            agen = stream_text(engine, tokenizer, ids,
                               sampling, state=state, priority=priority,
                               adapter=adapter, request_sink=req_sink,
                               request_id=getattr(self, "_request_id", None))
            lp_sent = 0

            def chunk_logprobs() -> Optional[dict]:
                nonlocal lp_sent
                if not sampling.logprobs or not req_sink:
                    return None
                entries = req_sink[0].out_logprobs
                upto = min(len(entries),
                           state.get("n_tokens", 0)
                           - (1 if state.get("saw_stop") else 0))
                if upto <= lp_sent:
                    return None
                out = {"content": [
                    _logprob_entry(tokenizer, e, top_logprobs)
                    for e in entries[lp_sent:upto]]}
                lp_sent = upto
                return out

            try:
                try:
                    for piece in bridge.stream(agen,
                                               timeout=request_timeout):
                        payload = _chunk_payload(
                            model, {"content": piece}, None, chunk_id)
                        lp = chunk_logprobs()
                        if lp is not None:
                            payload["choices"][0]["logprobs"] = lp
                        send_chunk(payload)
                finally:
                    # Settle the tenant reservation at the TRUE size: the
                    # tokens the client actually received are billed even
                    # on disconnect; the unused tail of the reservation is
                    # refunded. Zero generated tokens means the engine
                    # never served this request (shed / abort) — full
                    # refund (sched/tenants.py).
                    n_streamed = state.get("n_tokens", 0)
                    self._settle_tenant(
                        admission,
                        (len(ids) + n_streamed) if n_streamed else 0)
                # Mid-stream abort (a replica died after tokens were
                # already streamed, past the fleet's pre-token failover;
                # or a shed landed mid-flight): end the SSE body with an
                # explicit error event — a clean signal, never a silent
                # "stop" truncation and never a hang. The fleet path
                # appends the SERVING attempt's request last.
                live_req = req_sink[-1] if req_sink else None
                if live_req is not None and live_req.finish_reason \
                        is FinishReason.ABORTED:
                    send_terminator(
                        b'data: {"error": {"message": "stream aborted '
                        b'by the engine (replica failure or shed)"}}'
                        b'\n\n')
                    return
                # max_tokens truncation reports "length", like non-stream.
                finish = ("length"
                          if not state.get("saw_stop")
                          and state.get("n_tokens", 0)
                          >= sampling.max_new_tokens else "stop")
                final = _chunk_payload(model, {}, finish, chunk_id)
                lp_tail = chunk_logprobs()  # entries past the last piece
                if lp_tail is not None:
                    final["choices"][0]["logprobs"] = lp_tail
                send_chunk(final)
                if include_usage:
                    # stream_options.include_usage: one extra chunk after
                    # the finish chunk with empty choices (OpenAI shape).
                    n_out = state.get("n_tokens", 0)
                    send_chunk({
                        "id": chunk_id,
                        "object": "chat.completion.chunk",
                        "created": int(time.time()),
                        "model": model,
                        "choices": [],
                        "usage": {"prompt_tokens": len(ids),
                                  "completion_tokens": n_out,
                                  "total_tokens": len(ids) + n_out},
                    })
                send_terminator()
            except (BrokenPipeError, ConnectionResetError):
                # Client disconnected mid-stream: close the generator so
                # AsyncEngine aborts the request and frees its slot/pages.
                try:
                    bridge.run(agen.aclose(), timeout=10)
                except Exception:  # noqa: BLE001 — socket is gone anyway
                    pass
            except (TimeoutError, _FutTimeout):
                # bridge.stream already cancelled + closed the generator
                # (engine abort ran). Headers are out, so end the chunked
                # SSE body well-formed with an error event, never a 504.
                try:
                    send_terminator(b'data: {"error": {"message": '
                                    b'"generation timed out"}}\n\n')
                except OSError:
                    pass
            except FleetSaturated:
                # Lost the pre-header saturation race: the fleet shed this
                # placement after the 200/SSE headers went out. Same
                # well-formed-body policy as the timeout path.
                try:
                    send_terminator(b'data: {"error": {"message": '
                                    b'"all fleet replicas are saturated '
                                    b'(request shed)"}}\n\n')
                except OSError:
                    pass

    return Handler


class OpenAIServer:
    """Lifecycle wrapper: build, serve_forever (or background), shutdown."""

    def __init__(self, client, model_name: str, host: str = "127.0.0.1",
                 port: int = 8000, request_timeout: float = 600.0,
                 allow_runtime_adapters: bool = False, embedder=None):
        self.bridge = _EngineBridge(client)
        self.httpd = ThreadingHTTPServer(
            (host, port), make_handler(self.bridge, model_name,
                                       request_timeout,
                                       allow_runtime_adapters, embedder))
        self.model_name = model_name

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def client(self):
        """The serving client behind the handler closure (tests swap its
        ``slo_monitor`` to drive the /healthz SLO block)."""
        return self.bridge.client

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, name="openai-http",
                             daemon=True)
        t.start()
        return t

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self.bridge.shutdown()
