"""MCP server: knowledge tools over stdio JSON-RPC (no SDK dependency).

Parity target: reference ``src/mcp/server.ts`` — ``MCP_TOOLS`` (:75:
search_runbooks, get_known_issues, search_postmortems, get_knowledge_stats,
list_services), ``MCPServer`` (:386), stdio loop ``runStdioServer`` (:480).
Hand-rolled JSON-RPC 2.0 speaking the MCP initialize/tools/resources subset.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Optional

PROTOCOL_VERSION = "2024-11-05"


class MCPServer:
    def __init__(self, retriever, graph=None):
        self.retriever = retriever
        self.graph = graph

    @classmethod
    def from_config(cls, config) -> "MCPServer":
        from runbookai_tpu.knowledge.retriever import create_retriever
        from runbookai_tpu.knowledge.store.graph import ServiceGraph
        from runbookai_tpu.utils.config import load_services

        retriever = create_retriever(config)
        graph = ServiceGraph.from_services_config(load_services())
        return cls(retriever, graph)

    # ----------------------------------------------------------------- tools

    def list_tools(self) -> list[dict[str, Any]]:
        def schema(props: dict, req: Optional[list] = None) -> dict:
            s: dict[str, Any] = {"type": "object", "properties": props}
            if req:
                s["required"] = req
            return s

        q = {"query": {"type": "string"}}
        return [
            {"name": "search_runbooks",
             "description": "Search operational runbooks and procedures.",
             "inputSchema": schema({**q, "service": {"type": "string"}}, ["query"])},
            {"name": "get_known_issues",
             "description": "Find known issues matching symptoms or a service.",
             "inputSchema": schema({**q, "service": {"type": "string"}})},
            {"name": "search_postmortems",
             "description": "Search past incident postmortems.",
             "inputSchema": schema(q, ["query"])},
            {"name": "get_knowledge_stats",
             "description": "Knowledge base statistics.",
             "inputSchema": schema({})},
            {"name": "list_services",
             "description": "List known services and their dependencies.",
             "inputSchema": schema({"team": {"type": "string"}})},
        ]

    def call_tool(self, name: str, args: dict[str, Any]) -> Any:
        if name == "search_runbooks":
            return self._search(args, knowledge_type="runbook")
        if name == "get_known_issues":
            return self._search(args, knowledge_type="known-issue")
        if name == "search_postmortems":
            return self._search(args, knowledge_type="postmortem")
        if name == "get_knowledge_stats":
            return self.retriever.stats()
        if name == "list_services":
            if self.graph is None:
                return {"services": []}
            nodes = self.graph.filter(team=args.get("team"))
            return {"services": [
                {"name": n.name, "team": n.team, "tier": n.tier,
                 "depends_on": self.graph.dependencies_of(n.name)}
                for n in nodes
            ]}
        raise KeyError(f"unknown tool {name!r}")

    def _search(self, args: dict[str, Any], knowledge_type: str) -> dict[str, Any]:
        hits = self.retriever.hybrid.search(
            str(args.get("query", "")), limit=int(args.get("limit", 6)),
            knowledge_type=knowledge_type, service=args.get("service"))
        return {"results": [
            {"doc_id": h.doc.doc_id, "title": h.doc.title,
             "section": h.chunk.section, "content": h.chunk.content[:1000],
             "score": round(h.score, 4)}
            for h in hits
        ]}

    # ------------------------------------------------------------- resources

    def list_resources(self) -> list[dict[str, Any]]:
        stats = self.retriever.stats()
        return [{
            "uri": "runbook://knowledge/stats",
            "name": "knowledge-stats",
            "description": f"{stats.get('documents', 0)} documents indexed",
            "mimeType": "application/json",
        }]

    def read_resource(self, uri: str) -> dict[str, Any]:
        if uri == "runbook://knowledge/stats":
            return {"contents": [{"uri": uri, "mimeType": "application/json",
                                  "text": json.dumps(self.retriever.stats(), default=str)}]}
        raise KeyError(f"unknown resource {uri!r}")

    # -------------------------------------------------------------- JSON-RPC

    def handle(self, message: dict[str, Any]) -> Optional[dict[str, Any]]:
        msg_id = message.get("id")
        method = message.get("method", "")
        params = message.get("params") or {}

        def ok(result: Any) -> dict[str, Any]:
            return {"jsonrpc": "2.0", "id": msg_id, "result": result}

        def err(code: int, text: str) -> dict[str, Any]:
            return {"jsonrpc": "2.0", "id": msg_id,
                    "error": {"code": code, "message": text}}

        try:
            if method == "initialize":
                return ok({
                    "protocolVersion": PROTOCOL_VERSION,
                    "capabilities": {"tools": {}, "resources": {}},
                    "serverInfo": {"name": "runbookai-tpu", "version": "0.1.0"},
                })
            if method == "notifications/initialized":
                return None  # notification, no response
            if method == "tools/list":
                return ok({"tools": self.list_tools()})
            if method == "tools/call":
                result = self.call_tool(params.get("name", ""),
                                        params.get("arguments") or {})
                return ok({"content": [{"type": "text",
                                        "text": json.dumps(result, default=str)}]})
            if method == "resources/list":
                return ok({"resources": self.list_resources()})
            if method == "resources/read":
                return ok(self.read_resource(params.get("uri", "")))
            if method == "ping":
                return ok({})
            return err(-32601, f"method not found: {method}")
        except KeyError as exc:
            return err(-32602, str(exc))
        except Exception as exc:  # noqa: BLE001
            return err(-32603, f"{type(exc).__name__}: {exc}")


def run_stdio_server(server: MCPServer, stdin=None, stdout=None) -> None:
    """Line-delimited JSON-RPC loop (reference runStdioServer :480)."""
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    for line in stdin:
        line = line.strip()
        if not line:
            continue
        try:
            message = json.loads(line)
        except json.JSONDecodeError:
            continue
        response = server.handle(message)
        if response is not None:
            stdout.write(json.dumps(response) + "\n")
            stdout.flush()
