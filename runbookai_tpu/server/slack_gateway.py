"""Slack gateway: mention commands → agent runs → thread replies.

Parity target: reference ``src/slack/gateway.ts`` — mention command parser
(:95 — ``@runbookAI <infra|knowledge|deploy|investigate> …``), authorization
(channels/users/threaded :190), event dedupe cache (:70), request execution
through the agent (:312), HTTP events mode with signature verification;
``startSlackGateway`` (:531). Both transports are stdlib-only: HTTP events
mode with signature verification, and Socket Mode over the vendored RFC
6455 client (``server/slack_socket.py`` — no public endpoint needed).
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional

import urllib.request

from runbookai_tpu.server.webhook import verify_slack_signature

COMMANDS = ("infra", "knowledge", "deploy", "investigate", "help")


@dataclass
class SlackRequest:
    command: str
    text: str
    channel: str
    user: str
    thread_ts: Optional[str] = None


def parse_mention_command(text: str) -> Optional[tuple[str, str]]:
    """'<@U123> investigate PD-1 …' -> ('investigate', 'PD-1 …')."""
    words = [w for w in text.split() if not (w.startswith("<@") and w.endswith(">"))]
    if not words:
        return None
    head = words[0].lower()
    if head in COMMANDS:
        return head, " ".join(words[1:])
    # Bare questions default to the infra agent path.
    return "infra", " ".join(words)


class DedupeCache:
    """Slack re-delivers events; remember recently seen ids (gateway.ts:70)."""

    def __init__(self, ttl_s: float = 300.0, max_size: int = 500):
        self.ttl = ttl_s
        self.max_size = max_size
        self._seen: dict[str, float] = {}
        # Socket mode dispatches each envelope on its own thread; the
        # check-then-set below must be atomic or a Slack redelivery racing
        # the original starts a duplicate investigation (ADVICE r4).
        self._lock = threading.Lock()

    def seen(self, event_id: str) -> bool:
        now = time.time()
        with self._lock:
            if len(self._seen) > self.max_size:
                self._seen = {k: v for k, v in self._seen.items()
                              if now - v < self.ttl}
            if event_id in self._seen and now - self._seen[event_id] < self.ttl:
                return True
            self._seen[event_id] = now
            return False


@dataclass
class SlackGateway:
    config: Any
    run_request: Callable[[SlackRequest], Any]  # async: SlackRequest -> str
    post_message: Optional[Callable[[str, str, Optional[str]], None]] = None
    dedupe: DedupeCache = field(default_factory=DedupeCache)

    # ----------------------------------------------------------------- authz

    def authorized(self, channel: str, user: str, thread_ts: Optional[str]) -> Optional[str]:
        slack = self.config.incident.slack
        if slack.allowed_channels and channel not in slack.allowed_channels:
            return f"channel {channel} not allowed"
        if slack.allowed_users and user not in slack.allowed_users:
            return f"user {user} not allowed"
        if slack.require_thread and not thread_ts:
            return "mention me in a thread"
        return None

    # ---------------------------------------------------------------- events

    async def handle_event(self, event: dict[str, Any],
                           event_id: str = "") -> Optional[str]:
        if event_id and self.dedupe.seen(event_id):
            return None
        if event.get("type") != "app_mention":
            return None
        channel = event.get("channel", "")
        user = event.get("user", "")
        thread_ts = event.get("thread_ts") or event.get("ts")
        denial = self.authorized(channel, user, event.get("thread_ts"))
        if denial:
            return self._reply(channel, f"Not authorized: {denial}", thread_ts)
        parsed = parse_mention_command(event.get("text", ""))
        if parsed is None:
            return self._reply(channel, "Ask me something after the mention.",
                               thread_ts)
        command, text = parsed
        if command == "help":
            return self._reply(
                channel,
                "Commands: infra <question> | knowledge <query> | "
                "investigate <incident-id> | deploy <service>", thread_ts)
        request = SlackRequest(command=command, text=text, channel=channel,
                               user=user, thread_ts=thread_ts)
        answer = await self.run_request(request)
        return self._reply(channel, answer, thread_ts)

    def _reply(self, channel: str, text: str, thread_ts: Optional[str]) -> str:
        if self.post_message is not None:
            self.post_message(channel, text, thread_ts)
        elif self.config.incident.slack.bot_token:
            post_slack_message(self.config.incident.slack.bot_token,
                               channel, text, thread_ts)
        return text


def post_slack_message(token: str, channel: str, text: str,
                       thread_ts: Optional[str] = None) -> None:
    body = {"channel": channel, "text": text[:39_000]}
    if thread_ts:
        body["thread_ts"] = thread_ts
    req = urllib.request.Request(
        "https://slack.com/api/chat.postMessage",
        data=json.dumps(body).encode(),
        headers={"Authorization": f"Bearer {token}",
                 "Content-Type": "application/json"},
    )
    urllib.request.urlopen(req, timeout=15)


# --------------------------------------------------------------------------- #
# HTTP events mode                                                            #
# --------------------------------------------------------------------------- #


def make_http_handler(gateway: SlackGateway):
    secret = gateway.config.incident.slack.signing_secret

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def _send(self, code: int, payload: dict[str, Any]) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/health":
                self._send(200, {"status": "ok"})
            else:
                self._send(404, {})

        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            if secret and not verify_slack_signature(
                secret, self.headers.get("X-Slack-Request-Timestamp", ""),
                body, self.headers.get("X-Slack-Signature", "")):
                self._send(401, {"error": "invalid signature"})
                return
            try:
                payload = json.loads(body)
            except json.JSONDecodeError:
                self._send(400, {"error": "bad json"})
                return
            if payload.get("type") == "url_verification":
                self._send(200, {"challenge": payload.get("challenge", "")})
                return
            event = payload.get("event") or {}
            # Ack immediately; process the mention in the background thread.
            self._send(200, {"ok": True})
            asyncio.run(gateway.handle_event(event,
                                             payload.get("event_id", "")))

    return Handler


def run_slack_gateway(config, mode: str = "http", port: int = 3940) -> None:
    from runbookai_tpu.cli.runtime import build_agent, build_orchestrator, build_runtime

    runtime = build_runtime(config, interactive=False)

    async def run_request(request: SlackRequest) -> str:
        if request.command == "investigate":
            orch = build_orchestrator(runtime, incident_id=request.text.split()[0]
                                      if request.text else "")
            result = await orch.investigate(
                request.text.split()[0] if request.text else "", request.text)
            return (f"Root cause: {result.root_cause}\n"
                    f"Confidence: {result.confidence}\n"
                    f"Services: {', '.join(result.affected_services)}")
        if request.command == "knowledge":
            if runtime.knowledge is None:
                return "No knowledge base configured."
            hits = runtime.knowledge.hybrid.search(request.text, limit=5)
            return "\n".join(f"• {h.doc.title} §{h.chunk.section or '-'}"
                             for h in hits) or "No results."
        agent = build_agent(runtime)
        answer = ""
        async for ev in agent.run(request.text):
            if ev.kind == "answer":
                answer = ev.data["text"]
        return answer or "(no answer)"

    gateway = SlackGateway(config=config, run_request=run_request)
    if mode == "socket":
        # Socket Mode: outbound WebSocket (vendored RFC 6455 client —
        # server/slack_socket.py), no public endpoint or signing secret
        # needed; same mention handler as http-events.
        from runbookai_tpu.server.slack_socket import run_socket_mode

        def handle(event: dict) -> None:
            asyncio.run(gateway.handle_event(
                event, event.get("event_ts", "")))

        print("slack gateway (socket mode) connecting…")
        run_socket_mode(config, handle)
        return
    server = ThreadingHTTPServer(("0.0.0.0", port), make_http_handler(gateway))
    print(f"slack gateway (http events) on :{port}")
    server.serve_forever()
