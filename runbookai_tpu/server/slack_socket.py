"""Slack Socket Mode: outbound WebSocket, no public HTTP endpoint needed.

Reference parity: ``src/slack/gateway.ts:531`` runs the gateway in socket
or http-events mode; r3 shipped http-events only and errored on socket
(VERDICT missing #2). ``slack_sdk`` is not available in this environment,
so this module vendors the two pieces Socket Mode actually needs:

- :class:`MiniWebSocket` — a minimal RFC 6455 *client*: HTTP Upgrade
  handshake with ``Sec-WebSocket-Key`` verification, client-masked text
  frames, automatic ping→pong, 2/8-byte extended lengths, clean close.
  Stdlib only (socket/ssl/base64/hashlib/os).
- :class:`SocketModeClient` — the Slack envelope protocol over it:
  ``apps.connections.open`` (app token) → wss URL, then a receive loop
  that acks every envelope by ``envelope_id`` *before* dispatching
  (Slack retries unacked envelopes within seconds — ack-then-handle is
  the documented discipline) and reconnects on ``disconnect`` envelopes
  (Slack refreshes connections roughly hourly).

The connection opener and URL are injectable, so the test suite drives
the full handshake + envelope + ack cycle against an in-process fake
server with zero egress.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import socket
import ssl
import struct
import threading
import time
import urllib.parse
import urllib.request
from collections import deque
from typing import Any, Callable, Optional

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_TEXT = 0x1
OP_BIN = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


class MiniWebSocket:
    """Blocking RFC 6455 client, just enough for Slack Socket Mode."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._buf = b""

    # ------------------------------------------------------------ connect

    @classmethod
    def connect(cls, url: str, timeout: float = 30.0) -> "MiniWebSocket":
        u = urllib.parse.urlparse(url)
        secure = u.scheme == "wss"
        port = u.port or (443 if secure else 80)
        raw = socket.create_connection((u.hostname, port), timeout=timeout)
        if secure:
            raw = ssl.create_default_context().wrap_socket(
                raw, server_hostname=u.hostname)
        key = base64.b64encode(os.urandom(16)).decode()
        path = u.path or "/"
        if u.query:
            path += "?" + u.query
        raw.sendall(
            (f"GET {path} HTTP/1.1\r\n"
             f"Host: {u.hostname}\r\n"
             "Upgrade: websocket\r\n"
             "Connection: Upgrade\r\n"
             f"Sec-WebSocket-Key: {key}\r\n"
             "Sec-WebSocket-Version: 13\r\n\r\n").encode())
        # Post-handshake: a generous idle timeout (Slack pings well inside
        # it) so a genuinely dead connection is detected and treated as a
        # drop instead of blocking forever or keeping the 30s dial budget.
        raw.settimeout(120.0)
        ws = cls(raw)
        status, headers = ws._read_http_response()
        if status != 101:
            raise ConnectionError(f"websocket upgrade refused: {status}")
        want = base64.b64encode(
            hashlib.sha1((key + _WS_MAGIC).encode()).digest()).decode()
        if headers.get("sec-websocket-accept") != want:
            raise ConnectionError("bad Sec-WebSocket-Accept")
        return ws

    def _read_http_response(self) -> tuple[int, dict[str, str]]:
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise ConnectionError("socket closed during upgrade")
            data += chunk
        head, _, rest = data.partition(b"\r\n\r\n")
        self._buf = rest
        lines = head.decode("latin-1").split("\r\n")
        status = int(lines[0].split(" ", 2)[1])
        headers = {}
        for line in lines[1:]:
            if ":" in line:
                k, v = line.split(":", 1)
                headers[k.strip().lower()] = v.strip()
        return status, headers

    # ------------------------------------------------------------- frames

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("socket closed mid-frame")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def send_frame(self, opcode: int, payload: bytes) -> None:
        # Clients MUST mask (RFC 6455 §5.3).
        mask = os.urandom(4)
        head = bytes([0x80 | opcode])
        n = len(payload)
        if n < 126:
            head += bytes([0x80 | n])
        elif n < 1 << 16:
            head += bytes([0x80 | 126]) + struct.pack(">H", n)
        else:
            head += bytes([0x80 | 127]) + struct.pack(">Q", n)
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        self.sock.sendall(head + mask + masked)

    def send_text(self, text: str) -> None:
        self.send_frame(OP_TEXT, text.encode())

    def recv(self) -> tuple[int, bytes]:
        """Next complete message (ping answered, fragments reassembled —
        RFC 6455 §5.4 allows any text message to arrive fragmented)."""
        frag_op: int | None = None
        frag_buf = b""
        while True:
            b0, b1 = self._read_exact(2)
            fin = bool(b0 & 0x80)
            opcode = b0 & 0x0F
            masked = bool(b1 & 0x80)
            n = b1 & 0x7F
            if n == 126:
                n = struct.unpack(">H", self._read_exact(2))[0]
            elif n == 127:
                n = struct.unpack(">Q", self._read_exact(8))[0]
            mask = self._read_exact(4) if masked else b""
            payload = self._read_exact(n)
            if masked:
                payload = bytes(b ^ mask[i % 4]
                                for i, b in enumerate(payload))
            if opcode == OP_PING:
                self.send_frame(OP_PONG, payload)
                continue
            if opcode == OP_PONG:
                continue
            if opcode in (OP_TEXT, OP_BIN) and not fin:
                frag_op, frag_buf = opcode, payload
                continue
            if opcode == 0x0:  # continuation
                frag_buf += payload
                if not fin or frag_op is None:
                    continue
                opcode, payload = frag_op, frag_buf
                frag_op, frag_buf = None, b""
            return opcode, payload

    def close(self) -> None:
        try:
            self.send_frame(OP_CLOSE, struct.pack(">H", 1000))
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


# --------------------------------------------------------------------------- #
# Slack Socket Mode protocol                                                  #
# --------------------------------------------------------------------------- #


def _connections_open(app_token: str) -> str:
    """POST apps.connections.open → wss URL (requires an xapp- token)."""
    req = urllib.request.Request(
        "https://slack.com/api/apps.connections.open",
        data=b"", method="POST",
        headers={"Authorization": f"Bearer {app_token}",
                 "Content-Type": "application/x-www-form-urlencoded"})
    with urllib.request.urlopen(req, timeout=30) as r:
        body = json.loads(r.read())
    if not body.get("ok"):
        raise ConnectionError(
            f"apps.connections.open failed: {body.get('error')}")
    return body["url"]


class SocketModeClient:
    """Envelope loop: hello → (ack + dispatch)* → disconnect/reconnect."""

    def __init__(
        self,
        app_token: str,
        handler: Callable[[dict[str, Any]], Any],
        connections_open: Callable[[str], str] = _connections_open,
        connect: Callable[[str], MiniWebSocket] = MiniWebSocket.connect,
        max_reconnects: int = 1_000_000,
    ):
        self.app_token = app_token
        self.handler = handler
        self._open = connections_open
        self._connect = connect
        self.max_reconnects = max_reconnects
        self._stop_event = threading.Event()
        # Recent envelope ids, newest last (tests observe these; bounded —
        # the gateway runs for days at Slack event volume).
        self.acked: deque[str] = deque(maxlen=512)

    @property
    def _stop(self) -> bool:
        return self._stop_event.is_set()

    def stop(self) -> None:
        self._stop_event.set()

    def run(self) -> None:
        """Blocking receive loop with reconnect-on-disconnect.

        Connection establishment is fallible routine (Slack refreshes
        connections ~hourly; transient DNS/5xx happen): failures back off
        exponentially (1s → 30s) instead of crashing the gateway, and the
        backoff resets after any successfully-established connection."""
        reconnects = 0
        backoff = 1.0
        while not self._stop and reconnects <= self.max_reconnects:
            try:
                url = self._open(self.app_token)
                ws = self._connect(url)
            except Exception as e:  # noqa: BLE001 — URLError/OSError/Conn...
                logging.getLogger(__name__).warning(
                    "socket-mode connect failed (%s: %s); retrying in %.0fs",
                    type(e).__name__, e, min(backoff, 30.0))
                reconnects += 1
                # Event-based sleep: stop() interrupts the backoff instead
                # of delaying shutdown by up to 30s.
                if self._stop_event.wait(min(backoff, 30.0)):
                    return
                backoff = min(backoff * 2, 30.0)
                continue
            backoff = 1.0
            try:
                if self._run_connection(ws):
                    reconnects += 1
                    continue
                return  # clean stop / server close after stop()
            finally:
                ws.close()

    def _run_connection(self, ws: MiniWebSocket) -> bool:
        """One connection's envelopes; True = Slack asked to reconnect."""
        while not self._stop:
            try:
                opcode, payload = ws.recv()
            except OSError:
                # ConnectionError, socket.timeout, ssl errors alike:
                # the connection is gone — refresh it.
                return True
            if opcode == OP_CLOSE:
                # An unsolicited server close (no disconnect envelope —
                # e.g. a Slack-side deploy or an LB reset) must reconnect,
                # not silently end the gateway; clean exit is stop()'s.
                return not self._stop
            if opcode != OP_TEXT:
                continue
            try:
                env = json.loads(payload.decode())
            except (UnicodeDecodeError, json.JSONDecodeError):
                continue
            etype = env.get("type")
            if etype == "hello":
                continue
            if etype == "disconnect":
                return True  # Slack refreshes connections periodically
            env_id = env.get("envelope_id")
            if env_id:
                # Ack FIRST: Slack redelivers unacked envelopes within
                # seconds, and the handler may run an investigation. A
                # connection dying between recv and ack is a drop like
                # any other — reconnect, don't crash.
                try:
                    ws.send_text(json.dumps({"envelope_id": env_id}))
                except OSError:
                    return True
                self.acked.append(env_id)
            if etype == "events_api":
                event = (env.get("payload") or {}).get("event") or {}
                if event:
                    # Off-thread: a long investigation must not stall the
                    # receive loop (unanswered pings get the connection
                    # torn down; http mode likewise handles per-thread).
                    threading.Thread(target=self.handler, args=(event,),
                                     daemon=True).start()
        return False


def run_socket_mode(config, handle_event,
                    app_token: Optional[str] = None) -> None:
    """Gateway entry: block on the Socket Mode loop.

    ``handle_event(event_dict)`` is the same mention handler the
    http-events mode uses (``slack_gateway.SlackGateway.handle_event`` via
    an asyncio bridge) — the two modes differ only in transport.
    """
    token = app_token or getattr(config.incident.slack, "app_token", None)
    if not token:
        raise SystemExit(
            "socket mode needs incident.slack.app_token (an xapp- token "
            "with connections:write); or use --mode http")
    client = SocketModeClient(token, handle_event)
    client.run()
