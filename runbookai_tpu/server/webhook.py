"""Slack approval webhook server.

Parity target: reference ``src/webhooks/slack-webhook.ts`` — Slack signature
verification, approve/reject button handling writing response files the
approval flow polls, pending-approval list/cleanup (:322-349), ``/health``;
``startWebhookServer`` (:278). stdlib ``http.server`` — no framework.

Flow: the approval layer writes ``pending/<id>.json`` and polls
``responses/<id>.json``; Slack button clicks POST here and produce the
response file.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any, Optional

PENDING_TTL_S = 3600.0


class ApprovalFileStore:
    """File-based pending/response exchange between webhook and approval flow."""

    def __init__(self, root: str | Path = ".runbook/approvals"):
        self.root = Path(root)
        (self.root / "pending").mkdir(parents=True, exist_ok=True)
        (self.root / "responses").mkdir(parents=True, exist_ok=True)

    def create_pending(self, approval_id: str, payload: dict[str, Any]) -> Path:
        path = self.root / "pending" / f"{approval_id}.json"
        path.write_text(json.dumps({"created_at": time.time(), **payload}))
        return path

    def list_pending(self) -> list[str]:
        self.cleanup()
        return sorted(p.stem for p in (self.root / "pending").glob("*.json"))

    def respond(self, approval_id: str, approved: bool, user: str = "") -> bool:
        pending = self.root / "pending" / f"{approval_id}.json"
        if not pending.is_file():
            return False
        # Atomic write (tmp + rename): the approval race polls this path
        # every ~0.5s, and a half-written file must never be readable.
        final = self.root / "responses" / f"{approval_id}.json"
        tmp = final.with_suffix(".tmp")
        tmp.write_text(json.dumps({
            "approved": approved, "user": user, "ts": time.time()}))
        tmp.replace(final)
        pending.unlink()
        return True

    def discard_pending(self, approval_id: str) -> None:
        """Retire a decided request: the CLI/timeout leg of the approval
        race resolved it, so the pending file (and any unread response)
        must go — a late Slack click then correctly reports 'expired'."""
        for path in (self.root / "pending" / f"{approval_id}.json",
                     self.root / "responses" / f"{approval_id}.json"):
            try:
                path.unlink(missing_ok=True)
            except OSError:
                pass

    def poll_response(self, approval_id: str) -> Optional[dict[str, Any]]:
        path = self.root / "responses" / f"{approval_id}.json"
        if path.is_file():
            try:
                data = json.loads(path.read_text())
            except json.JSONDecodeError:
                return None  # mid-write on a non-atomic FS: retry next tick
            path.unlink()
            return data
        return None

    def cleanup(self, ttl: float = PENDING_TTL_S) -> int:
        removed = 0
        now = time.time()
        for p in (self.root / "pending").glob("*.json"):
            try:
                created = json.loads(p.read_text()).get("created_at", 0)
            except json.JSONDecodeError:
                created = 0
            if now - created > ttl:
                p.unlink()
                removed += 1
        return removed


def verify_slack_signature(signing_secret: str, timestamp: str, body: bytes,
                           signature: str, tolerance_s: float = 300.0) -> bool:
    """Slack v0 signature scheme with replay-window check."""
    try:
        if abs(time.time() - float(timestamp)) > tolerance_s:
            return False
    except (TypeError, ValueError):
        return False
    base = f"v0:{timestamp}:".encode() + body
    expected = "v0=" + hmac.new(signing_secret.encode(), base,
                                hashlib.sha256).hexdigest()
    return hmac.compare_digest(expected, signature or "")


def make_handler(store: ApprovalFileStore, signing_secret: Optional[str]):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet
            pass

        def _send(self, code: int, payload: dict[str, Any]) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/health":
                self._send(200, {"status": "ok",
                                 "pending": store.list_pending()})
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            if signing_secret:
                ok = verify_slack_signature(
                    signing_secret,
                    self.headers.get("X-Slack-Request-Timestamp", ""),
                    body,
                    self.headers.get("X-Slack-Signature", ""),
                )
                if not ok:
                    self._send(401, {"error": "invalid signature"})
                    return
            if self.path == "/slack/actions":
                payload = self._parse_actions(body)
                if payload is None:
                    self._send(400, {"error": "bad payload"})
                    return
                action, approval_id, user = payload
                handled = store.respond(approval_id, action == "approve", user)
                self._send(200, {"ok": handled,
                                 "text": f"{'Approved' if action == 'approve' else 'Rejected'}"
                                         f" by {user}" if handled else
                                         "approval not found or expired"})
            else:
                self._send(404, {"error": "not found"})

        @staticmethod
        def _parse_actions(body: bytes):
            """Slack interactive payloads arrive form-encoded under payload=."""
            try:
                form = urllib.parse.parse_qs(body.decode())
                payload = json.loads(form.get("payload", ["{}"])[0])
                action = payload["actions"][0]
                action_id = action.get("action_id", "")
                approval_id = action.get("value", "")
                user = payload.get("user", {}).get("username", "unknown")
                if action_id not in ("approve", "reject"):
                    return None
                return action_id, approval_id, user
            except (KeyError, IndexError, json.JSONDecodeError, UnicodeDecodeError):
                return None

    return Handler


def make_server(config, port: int = 3939,
                store: Optional[ApprovalFileStore] = None) -> ThreadingHTTPServer:
    store = store or ApprovalFileStore(f"{config.runbook_dir}/approvals")
    secret = config.incident.slack.signing_secret
    return ThreadingHTTPServer(("0.0.0.0", port), make_handler(store, secret))


def run_webhook_server(config, port: int = 3939) -> None:
    server = make_server(config, port=port)
    print(f"webhook server on :{port} (/health, /slack/actions)")
    server.serve_forever()
