"""Investigation state machine: phases, hypothesis tree, evaluations.

Parity target: reference ``src/agent/state-machine.ts`` — phases (:15-23),
valid transitions (:299-311), ``maxHypotheses=10`` / ``maxDepth=4`` (:184-185),
``maxIterations=20`` (:206), ``addHypothesis`` (:329), ``getNextHypothesis``
(:413 priority/depth sort), ``applyEvaluation`` (:461 —
branch/prune/confirm/continue), ``getSummary`` (:566), event listeners
(:167-177), per-phase error buffer (:549-561).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Optional


class Phase(str, Enum):
    IDLE = "idle"
    TRIAGE = "triage"
    HYPOTHESIZE = "hypothesize"
    INVESTIGATE = "investigate"
    EVALUATE = "evaluate"
    CONCLUDE = "conclude"
    REMEDIATE = "remediate"
    COMPLETE = "complete"
    FAILED = "failed"


VALID_TRANSITIONS: dict[Phase, tuple[Phase, ...]] = {
    Phase.IDLE: (Phase.TRIAGE,),
    Phase.TRIAGE: (Phase.HYPOTHESIZE, Phase.FAILED),
    Phase.HYPOTHESIZE: (Phase.INVESTIGATE, Phase.CONCLUDE, Phase.FAILED),
    Phase.INVESTIGATE: (Phase.EVALUATE, Phase.CONCLUDE, Phase.FAILED),
    Phase.EVALUATE: (Phase.INVESTIGATE, Phase.HYPOTHESIZE, Phase.CONCLUDE, Phase.FAILED),
    Phase.CONCLUDE: (Phase.REMEDIATE, Phase.COMPLETE, Phase.FAILED),
    Phase.REMEDIATE: (Phase.COMPLETE, Phase.FAILED),
    Phase.COMPLETE: (),
    Phase.FAILED: (),
}


class EvaluationAction(str, Enum):
    CONTINUE = "continue"  # keep investigating this hypothesis
    BRANCH = "branch"  # spawn sub-hypotheses
    PRUNE = "prune"  # discard this hypothesis
    CONFIRM = "confirm"  # root cause found


@dataclass
class FSMHypothesis:
    id: str
    statement: str
    priority: float = 0.5
    depth: int = 0
    parent_id: Optional[str] = None
    status: str = "open"  # open | investigating | confirmed | pruned
    confidence: float = 0.0
    evidence: list[dict[str, Any]] = field(default_factory=list)
    children: list[str] = field(default_factory=list)
    cycles: int = 0  # investigation cycles spent on this node


@dataclass
class EvidenceRecord:
    hypothesis_id: str
    query: str
    tool: str
    result_summary: str
    supports: bool
    strength: str = "weak"
    ts: float = field(default_factory=time.time)


@dataclass
class RemediationStep:
    description: str
    action: str = ""  # skill/tool to run
    params: dict[str, Any] = field(default_factory=dict)
    risk: str = "read"
    requires_approval: bool = True
    status: str = "pending"  # pending | approved | executed | rejected | failed
    result: Optional[str] = None


class InvestigationStateMachine:
    def __init__(self, incident_id: str = "", max_hypotheses: int = 10,
                 max_depth: int = 4, max_iterations: int = 20):
        self.incident_id = incident_id or f"inv-{uuid.uuid4().hex[:8]}"
        self.max_hypotheses = max_hypotheses
        self.max_depth = max_depth
        self.max_iterations = max_iterations
        self.phase = Phase.IDLE
        self.iterations = 0
        self.hypotheses: dict[str, FSMHypothesis] = {}
        self.evidence: list[EvidenceRecord] = []
        self.remediation_plan: list[RemediationStep] = []
        self.root_cause: Optional[str] = None
        self.conclusion_confidence: Optional[str] = None
        self.affected_services: list[str] = []
        self.symptoms: list[str] = []
        self.errors: dict[str, list[str]] = {}
        self.started_at = time.time()
        self._listeners: dict[str, list[Callable[..., None]]] = {}

    # ---------------------------------------------------------------- events

    def on(self, event: str, callback: Callable[..., None]) -> None:
        self._listeners.setdefault(event, []).append(callback)

    def _emit(self, event: str, *args: Any) -> None:
        for cb in self._listeners.get(event, []):
            cb(*args)

    def record_error(self, message: str) -> None:
        """Buffer per-phase errors without crashing (state-machine.ts:549)."""
        self.errors.setdefault(self.phase.value, []).append(message)
        if self._listeners.get("error"):
            self._emit("error", self.phase.value, message)

    # ----------------------------------------------------------- transitions

    def start(self) -> None:
        self.transition(Phase.TRIAGE)

    def can_transition(self, to: Phase) -> bool:
        return to in VALID_TRANSITIONS[self.phase]

    def transition(self, to: Phase) -> None:
        if not self.can_transition(to):
            raise ValueError(f"invalid transition {self.phase.value} -> {to.value}")
        old = self.phase
        self.phase = to
        self._emit("phaseChange", old.value, to.value)

    def can_continue(self) -> bool:
        if self.phase in (Phase.COMPLETE, Phase.FAILED, Phase.CONCLUDE,
                          Phase.REMEDIATE):
            return False
        if self.iterations >= self.max_iterations:
            return False
        return True

    # ------------------------------------------------------------ hypotheses

    def add_hypothesis(self, statement: str, priority: float = 0.5,
                       parent_id: Optional[str] = None) -> Optional[FSMHypothesis]:
        if len(self.hypotheses) >= self.max_hypotheses:
            self.record_error(f"hypothesis cap {self.max_hypotheses} reached")
            return None
        depth = 0
        if parent_id:
            parent = self.hypotheses.get(parent_id)
            if parent is None:
                return None
            depth = parent.depth + 1
            if depth > self.max_depth:
                self.record_error(f"depth cap {self.max_depth} reached")
                return None
        h = FSMHypothesis(
            id=f"H{len(self.hypotheses) + 1}", statement=statement,
            priority=priority, depth=depth, parent_id=parent_id,
        )
        self.hypotheses[h.id] = h
        if parent_id:
            self.hypotheses[parent_id].children.append(h.id)
        self._emit("hypothesisCreated", h)
        return h

    def get_next_hypothesis(self) -> Optional[FSMHypothesis]:
        """Highest (priority, -depth) open hypothesis (state-machine.ts:413)."""
        candidates = [
            h for h in self.hypotheses.values()
            if h.status in ("open", "investigating")
        ]
        if not candidates:
            return None
        return max(candidates, key=lambda h: (h.priority, -h.depth, -h.cycles))

    def add_evidence(self, record: EvidenceRecord) -> None:
        self.evidence.append(record)
        h = self.hypotheses.get(record.hypothesis_id)
        if h is not None:
            h.evidence.append({
                "query": record.query, "tool": record.tool,
                "summary": record.result_summary, "supports": record.supports,
                "strength": record.strength,
            })
        self._emit("evidenceAdded", record)

    def apply_evaluation(
        self,
        hypothesis_id: str,
        action: EvaluationAction,
        confidence: float = 0.0,
        sub_hypotheses: Optional[list[dict[str, Any]]] = None,
        reason: str = "",
    ) -> list[FSMHypothesis]:
        """Apply an evaluation verdict; returns newly created sub-hypotheses."""
        h = self.hypotheses.get(hypothesis_id)
        if h is None:
            self.record_error(f"unknown hypothesis {hypothesis_id}")
            return []
        h.confidence = confidence
        h.cycles += 1
        created: list[FSMHypothesis] = []
        if action == EvaluationAction.CONFIRM:
            h.status = "confirmed"
        elif action == EvaluationAction.PRUNE:
            h.status = "pruned"
            for child_id in h.children:
                child = self.hypotheses[child_id]
                if child.status == "open":
                    child.status = "pruned"
        elif action == EvaluationAction.BRANCH:
            h.status = "investigating"
            for sub in sub_hypotheses or []:
                child = self.add_hypothesis(
                    str(sub.get("statement", "")),
                    priority=float(sub.get("priority", h.priority)),
                    parent_id=hypothesis_id,
                )
                if child:
                    created.append(child)
        else:
            h.status = "investigating"
        self._emit("hypothesisUpdated", h, action.value, reason)
        return created

    def confirmed_hypothesis(self) -> Optional[FSMHypothesis]:
        confirmed = [h for h in self.hypotheses.values() if h.status == "confirmed"]
        return max(confirmed, key=lambda h: h.confidence) if confirmed else None

    def open_count(self) -> int:
        return sum(1 for h in self.hypotheses.values()
                   if h.status in ("open", "investigating"))

    # --------------------------------------------------------------- summary

    def get_summary(self) -> dict[str, Any]:
        return {
            "incident_id": self.incident_id,
            "phase": self.phase.value,
            "iterations": self.iterations,
            "elapsed_s": round(time.time() - self.started_at, 2),
            "hypotheses": {
                "total": len(self.hypotheses),
                "confirmed": sum(1 for h in self.hypotheses.values() if h.status == "confirmed"),
                "pruned": sum(1 for h in self.hypotheses.values() if h.status == "pruned"),
                "open": self.open_count(),
            },
            "evidence_count": len(self.evidence),
            "root_cause": self.root_cause,
            "confidence": self.conclusion_confidence,
            "affected_services": self.affected_services,
            "remediation_steps": [
                {"description": s.description, "status": s.status}
                for s in self.remediation_plan
            ],
            "errors": self.errors,
        }

    def hypothesis_tree_markdown(self) -> str:
        lines = ["## Hypotheses"]
        icons = {"confirmed": "[CONFIRMED]", "pruned": "[pruned]",
                 "open": "[open]", "investigating": "[investigating]"}

        def render(hid: str, indent: int) -> None:
            h = self.hypotheses[hid]
            lines.append("  " * indent + f"- {icons[h.status]} {h.id}: {h.statement} "
                         f"(priority {h.priority:.2f}, confidence {h.confidence:.2f})")
            for child in h.children:
                render(child, indent + 1)

        for h in self.hypotheses.values():
            if h.parent_id is None:
                render(h.id, 0)
        return "\n".join(lines)
