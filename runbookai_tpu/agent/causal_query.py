"""Causal query generation: hypothesis statement → targeted tool queries.

Parity target: reference ``src/agent/causal-query.ts`` — ``FAILURE_PATTERNS``
(:30-240: high_latency, high_error_rate, memory_issues, cpu_issues,
connectivity_issues, deployment_issues, database_issues, scaling_issues),
``generateQueriesForHypothesis`` (:241), ``isQueryTooBroad`` (:333),
``suggestQueryRefinements`` (:359), ``prioritizeQueries`` (:397),
``summarizeQueryResults`` (:435).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class CausalQuery:
    tool: str
    params: dict[str, Any]
    expected_outcome: str
    relevance: float = 0.5  # 0-1
    pattern: str = ""


@dataclass
class FailurePattern:
    name: str
    keywords: tuple[str, ...]
    queries: list[CausalQuery] = field(default_factory=list)


def _q(tool: str, params: dict[str, Any], expected: str, relevance: float,
       pattern: str = "") -> CausalQuery:
    return CausalQuery(tool=tool, params=params, expected_outcome=expected,
                       relevance=relevance, pattern=pattern)


FAILURE_PATTERNS: list[FailurePattern] = [
    FailurePattern(
        "high_latency",
        ("latency", "slow", "p99", "p95", "response time", "timeout", "timeouts", "slo"),
        [
            _q("datadog", {"action": "metrics", "query": "latency"},
               "latency series showing when the spike started", 0.9),
            _q("cloudwatch_alarms", {"state": "ALARM"},
               "latency/response-time alarms in ALARM", 0.8),
            _q("cloudwatch_logs", {"log_group": "{log_group}", "filter_pattern": "timeout"},
               "timeout or slow-request log lines", 0.7),
        ],
    ),
    FailurePattern(
        "high_error_rate",
        ("error rate", "5xx", "errors", "failing", "failures", "exceptions", "500"),
        [
            _q("cloudwatch_alarms", {"state": "ALARM"},
               "error-count alarms firing", 0.85),
            _q("cloudwatch_logs", {"log_group": "{log_group}", "filter_pattern": "error"},
               "error/exception log lines with stack traces", 0.85),
            _q("datadog", {"action": "metrics", "query": "error"},
               "error-rate series", 0.7),
        ],
    ),
    FailurePattern(
        "memory_issues",
        ("memory", "oom", "out of memory", "heap", "leak", "swap"),
        [
            _q("kubernetes_query", {"action": "pods"},
               "pods OOMKilled or restarting", 0.85),
            _q("datadog", {"action": "metrics", "query": "memory"},
               "memory utilization trending up", 0.8),
        ],
    ),
    FailurePattern(
        "cpu_issues",
        ("cpu", "throttl", "saturation", "load"),
        [
            _q("datadog", {"action": "metrics", "query": "cpu"},
               "cpu utilization/throttling series", 0.8),
            _q("kubernetes_query", {"action": "nodes"},
               "node cpu pressure", 0.6),
        ],
    ),
    FailurePattern(
        "connectivity_issues",
        ("connection", "connections", "refused", "dns", "network", "unreachable", "pool"),
        [
            _q("cloudwatch_logs", {"log_group": "{log_group}", "filter_pattern": "connection"},
               "connection failures / pool exhaustion lines", 0.9),
            _q("aws_query", {"service": "rds"},
               "db connection counts vs limits", 0.75),
        ],
    ),
    FailurePattern(
        "deployment_issues",
        ("deploy", "deployment", "release", "rollout", "version", "config change", "changed"),
        [
            _q("kubernetes_query", {"action": "deployments"},
               "recently updated deployments and replica health", 0.9),
            _q("datadog", {"action": "events"},
               "deploy events near incident start", 0.85),
            _q("aws_query", {"service": "ecs"},
               "ECS services mid-deployment or unstable", 0.7),
        ],
    ),
    FailurePattern(
        "database_issues",
        ("database", "db", "sql", "postgres", "mysql", "rds", "query", "deadlock", "replica"),
        [
            _q("aws_query", {"service": "rds"},
               "db instance status, connections, storage", 0.9),
            _q("cloudwatch_logs", {"log_group": "{log_group}", "filter_pattern": "SQL"},
               "slow queries / db errors in app logs", 0.65),
        ],
    ),
    FailurePattern(
        "scaling_issues",
        ("scaling", "autoscal", "capacity", "replicas", "throughput", "queue depth", "backlog"),
        [
            _q("kubernetes_query", {"action": "deployments"},
               "replica counts vs desired", 0.8),
            _q("aws_query", {"service": "ecs"},
               "running vs desired task counts", 0.75),
        ],
    ),
]


def match_patterns(statement: str) -> list[FailurePattern]:
    s = statement.lower()
    matched = [p for p in FAILURE_PATTERNS if any(k in s for k in p.keywords)]
    return matched


def generate_queries_for_hypothesis(
    statement: str,
    log_group: Optional[str] = None,
    available_tools: Optional[set[str]] = None,
    max_queries: int = 3,
) -> list[CausalQuery]:
    """Pattern-match the hypothesis and emit up to N targeted queries."""
    queries: list[CausalQuery] = []
    seen: set[str] = set()
    for pattern in match_patterns(statement):
        for q in pattern.queries:
            params = dict(q.params)
            if params.get("log_group") == "{log_group}":
                if not log_group:
                    continue
                params["log_group"] = log_group
            key = f"{q.tool}:{sorted(params.items())}"
            if key in seen:
                continue
            seen.add(key)
            queries.append(CausalQuery(
                tool=q.tool, params=params, expected_outcome=q.expected_outcome,
                relevance=q.relevance, pattern=pattern.name,
            ))
    if not queries:
        # Generic fallback: look at alarms + recent deploy state.
        queries = [
            _q("cloudwatch_alarms", {"state": "ALARM"}, "any firing alarms", 0.5, "generic"),
            _q("kubernetes_query", {"action": "events"}, "recent cluster events", 0.4, "generic"),
        ]
    if available_tools is not None:
        queries = [q for q in queries if q.tool in available_tools] or queries
    return prioritize_queries(queries)[:max_queries]


def is_query_too_broad(query: CausalQuery) -> bool:
    """Anti-broad-query detection (causal-query.ts:333)."""
    params = query.params
    if query.tool == "aws_query" and params.get("service") in (None, "all", ""):
        return True
    if query.tool == "cloudwatch_logs" and not params.get("filter_pattern"):
        return True
    if query.tool == "datadog" and params.get("action") == "metrics" \
            and not params.get("query"):
        return True
    return False


def suggest_query_refinements(query: CausalQuery,
                              services: Optional[list[str]] = None) -> CausalQuery:
    """Narrow a too-broad query using known context (causal-query.ts:359)."""
    params = dict(query.params)
    if query.tool == "aws_query" and params.get("service") in (None, "all", ""):
        params["service"] = (services or ["ecs"])[0]
    if query.tool == "cloudwatch_logs" and not params.get("filter_pattern"):
        params["filter_pattern"] = "error"
    if query.tool == "datadog" and not params.get("query"):
        params["query"] = (services or ["latency"])[0]
    return CausalQuery(tool=query.tool, params=params,
                       expected_outcome=query.expected_outcome,
                       relevance=query.relevance, pattern=query.pattern)


def prioritize_queries(queries: list[CausalQuery]) -> list[CausalQuery]:
    return sorted(queries, key=lambda q: q.relevance, reverse=True)


def summarize_query_results(results: list[tuple[CausalQuery, Any, Optional[str]]]) -> str:
    """Render (query, result, error) triples for the evaluation prompt."""
    lines = []
    for query, result, error in results:
        head = f"- {query.tool}({query.params}) [expected: {query.expected_outcome}]"
        if error:
            lines.append(f"{head}\n  ERROR: {error}")
            continue
        text = str(result)
        if len(text) > 1200:
            text = text[:1200] + "…"
        lines.append(f"{head}\n  {text}")
    return "\n".join(lines) if lines else "(no query results)"
