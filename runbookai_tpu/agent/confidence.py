"""Evidence-strength classification and multi-factor confidence scoring.

Parity target: reference ``src/agent/confidence.ts`` — factor-weighted score
(`calculateConfidence` :22-46: chain depth, corroboration, contradiction,
temporal, historical, direct; high >=70, medium >=40), classification prompt
(:51) with tolerant fallback parsing (:91), temporal correlation check (:123),
and the confidence display/aggregation utilities (:159-307) used by the
terminal UI and markdown reports.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass

HIGH_THRESHOLD = 70.0
MEDIUM_THRESHOLD = 40.0

CONFIDENCE_DESCRIPTIONS = {
    "high": "High confidence - Strong evidence chain with corroborating signals",
    "medium": ("Medium confidence - Evidence supports this conclusion but some "
               "uncertainty remains"),
    "low": "Low confidence - Limited evidence, consider additional investigation",
}


@dataclass(frozen=True)
class ConfidenceFactors:
    """Signals gathered while evaluating a hypothesis."""

    evidence_chain_depth: int = 0
    corroborating_signals: int = 0
    contradicting_signals: int = 0
    temporal_correlation: bool = False
    historical_pattern_match: bool = False
    direct_evidence: bool = False


def confidence_score(factors: ConfidenceFactors) -> float:
    """Weighted 0-100+ score (confidence.ts:22-46 weights)."""
    score = 0.0
    score += min(factors.evidence_chain_depth * 15, 30)
    score += min(factors.corroborating_signals * 20, 40)
    score -= factors.contradicting_signals * 25
    if factors.temporal_correlation:
        score += 15
    if factors.historical_pattern_match:
        score += 15
    if factors.direct_evidence:
        score += 20
    return score


def calculate_confidence(factors: ConfidenceFactors) -> str:
    return level_from_value(confidence_score(factors))


def level_from_value(value: float, high: float = HIGH_THRESHOLD,
                     medium: float = MEDIUM_THRESHOLD) -> str:
    if value >= high:
        return "high"
    if value >= medium:
        return "medium"
    return "low"


EVIDENCE_CLASSIFICATION_PROMPT = """\
You are evaluating evidence for a hypothesis about an incident.

Given:
- Hypothesis: {hypothesis}
- Query executed: {query}
- Query result: {result}

Classify the evidence strength:

STRONG: The data directly supports this hypothesis with clear, unambiguous
signals (error rate spiked at the incident time, connection pool at 100%,
OOM killer events, service returning 503s).

WEAK: The data somewhat supports the hypothesis but could have other
explanations (metrics slightly elevated but within normal range, low-volume
errors, timing approximately but not exactly aligned).

NONE: The data does not support this hypothesis or actively contradicts it
(all metrics normal, no relevant errors, timeline mismatch, different
service affected).

Respond with JSON:
{{
  "strength": "strong" | "weak" | "none",
  "reasoning": "Brief explanation of why this evidence supports or refutes the hypothesis"
}}
"""


def parse_evidence_classification(response: str) -> tuple[str, str]:
    """(strength, reasoning) with keyword fallback (confidence.ts:91-118)."""
    match = re.search(r"\{[\s\S]*\}", response)
    if match:
        try:
            parsed = json.loads(match.group(0))
            strength = str(parsed.get("strength", "")).lower()
            if strength in ("strong", "weak", "none"):
                return strength, parsed.get("reasoning") or "No reasoning provided"
        except (json.JSONDecodeError, AttributeError, TypeError):
            pass
    lower = response.lower()
    # Negations first: "no strong evidence" / "not strong" must not inflate
    # confidence via the bare "strong" substring.
    # Contrast markers (but/yet/however) break the negation scope, so
    # "not weak but strong" still classifies as strong; intensifiers
    # (only/just/merely/simply) do too — "not only strong but overwhelming"
    # is an affirmation, not a negation.
    if re.search(r"\b(no|not|without|lacks?|lacking)\s+"
                 r"((?!(?:but|yet|however|only|just|merely|simply)\b)\w+\s+){0,3}strong",
                 lower):
        return ("weak", response) if "weak" in lower else ("none", response)
    if "strong" in lower:
        return "strong", response
    if "weak" in lower:
        return "weak", response
    return "none", response


def has_temporal_correlation(incident_ts: float, event_ts: float,
                             tolerance_minutes: float = 5.0) -> bool:
    """Events align in time within tolerance (confidence.ts:123-131)."""
    return abs(incident_ts - event_ts) <= tolerance_minutes * 60.0


def format_confidence_text(value: float, width: int = 10,
                           show_label: bool = True,
                           show_percentage: bool = True) -> str:
    """Text bar for non-TTY output, e.g. ``████████░░ 82% (High)``."""
    clamped = max(0.0, min(100.0, value))
    filled = round(clamped / 100.0 * width)
    bar = "█" * filled + "░" * (width - filled)
    parts = [bar]
    if show_percentage:
        parts.append(f"{clamped:.0f}%")
    if show_label:
        parts.append(f"({level_from_value(clamped).capitalize()})")
    return " ".join(parts)


def format_confidence_badge(value: float) -> str:
    return f"{level_from_value(value).capitalize()} ({value:.0f}%)"


def format_confidence_markdown(value: float, width: int = 10) -> str:
    clamped = max(0.0, min(100.0, value))
    filled = round(clamped / 100.0 * width)
    bar = "█" * filled + "░" * (width - filled)
    return f"**{level_from_value(clamped).capitalize()}** ({clamped:.0f}%) {bar}"


def confidence_color(value: float) -> str:
    return {"high": "green", "medium": "yellow", "low": "red"}[
        level_from_value(value)]


def parse_confidence_value(text: str) -> float | None:
    """Parse '85%', '85', 'high', 'High (85%)' → numeric (confidence.ts:272)."""
    match = re.search(r"(\d+)%?", text)
    if match:
        value = int(match.group(1))
        if 0 <= value <= 100:
            return float(value)
    lower = text.lower()
    if "high" in lower:
        return 85.0
    if "medium" in lower:
        return 55.0
    if "low" in lower:
        return 25.0
    return None


def aggregate_confidence(values: list[float],
                         weights: list[float] | None = None) -> float:
    if not values:
        return 0.0
    if weights and len(weights) == len(values):
        total = sum(weights)
        if total == 0:
            return 0.0
        return round(sum(v * w for v, w in zip(values, weights)) / total)
    return round(sum(values) / len(values))


_CONTEXT_DESCRIPTIONS = {
    "investigation": {
        "high": ("Strong evidence supports this conclusion. Multiple data "
                 "points corroborate the finding."),
        "medium": ("Evidence supports this conclusion with some uncertainty. "
                   "Additional validation recommended."),
        "low": ("Limited evidence available. This is a preliminary assessment "
                "that requires further investigation."),
    },
    "hypothesis": {
        "high": "This hypothesis is well-supported by gathered evidence.",
        "medium": "This hypothesis has partial support. Some evidence is inconclusive.",
        "low": "This hypothesis needs more evidence to be confirmed or refuted.",
    },
    "general": {
        "high": "High confidence in this result.",
        "medium": "Moderate confidence in this result.",
        "low": "Low confidence in this result.",
    },
}


def describe_confidence(value: float, context: str = "general") -> str:
    return _CONTEXT_DESCRIPTIONS[context][level_from_value(value)]
