"""Knowledge context manager: compact doc index in the system prompt.

Parity target: reference ``src/agent/knowledge-context.ts`` (:106) — maintains
a compact index of available runbooks / known issues for the system prompt and
re-queries when new services/symptoms appear mid-investigation.
"""

from __future__ import annotations

from typing import Optional

from runbookai_tpu.agent.types import RetrievedKnowledge


class KnowledgeContextManager:
    def __init__(self, retriever, max_index_entries: int = 12):
        self.retriever = retriever
        self.max_entries = max_index_entries
        self._seen_terms: set[str] = set()
        self._index: dict[str, str] = {}  # doc_id -> "title (type)"

    async def prime(self, query: str) -> RetrievedKnowledge:
        knowledge = await self.retriever.retrieve(query)
        self.absorb(knowledge, query=query)
        return knowledge

    def absorb(self, knowledge: RetrievedKnowledge,
               query: str = "") -> None:
        """Fold an already-retrieved result into the index — callers that
        retrieved themselves (Agent.run does, for the prompt block) use
        this instead of :meth:`prime`, so the search isn't run twice."""
        self._absorb(knowledge)
        if query:
            self._seen_terms.update(query.lower().split())

    def _absorb(self, knowledge: RetrievedKnowledge) -> None:
        for item in knowledge.all():
            if len(self._index) >= self.max_entries:
                break
            self._index.setdefault(
                item.doc_id, f"{item.title} ({item.knowledge_type})")

    async def observe_terms(self, terms: list[str]) -> Optional[RetrievedKnowledge]:
        """Re-query when genuinely new services/symptoms appear."""
        new = [t for t in terms if t and t.lower() not in self._seen_terms]
        if not new:
            return None
        self._seen_terms.update(t.lower() for t in new)
        knowledge = await self.retriever.retrieve(" ".join(new))
        if knowledge.empty:
            return None
        self._absorb(knowledge)
        return knowledge

    def system_prompt_block(self) -> str:
        if not self._index:
            return ""
        lines = ["# Available knowledge (cite as [doc-id])"]
        for doc_id, label in self._index.items():
            lines.append(f"- [{doc_id}] {label}")
        lines.append(
            "Use search_knowledge for details on any of these before "
            "querying live infrastructure for procedural questions.")
        return "\n".join(lines)
