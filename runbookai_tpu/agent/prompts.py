"""Prompt construction for the free-form agent path.

Parity target: reference ``src/agent/prompts.ts`` — ``buildSystemPrompt``
(:37-223: investigation methodology, tool policy, mandatory visualization
policy, safety rules), iteration prompt (:228), knowledge prompt (:271),
final-answer prompt (:349), context-aware variants (:524-651). The behavioral
content (methodology steps, policies) is re-expressed; wording is tuned for an
open instruction-tuned model rather than hosted frontier models.
"""

from __future__ import annotations

from typing import Optional

from runbookai_tpu.agent.types import RetrievedKnowledge

SYSTEM_PROMPT = """\
You are RunbookAI, an expert SRE agent that investigates production incidents
and answers infrastructure questions with evidence.

# Methodology
1. Understand the question or incident symptom.
2. Check retrieved knowledge (runbooks, postmortems, known issues) first —
   if a runbook answers the question, use it and cite it.
3. Form explicit hypotheses about likely causes; prefer recent changes,
   resource exhaustion, dependencies, and configuration issues.
4. Gather evidence with tools. Query the MOST SPECIFIC scope you can
   (a service, a time window) rather than broad scans.
5. Corroborate before concluding: one signal is a hint, two are evidence.
6. Conclude with the root cause, affected services, confidence (high /
   medium / low), and concrete remediation steps.

# Tool policy
- Call tools only when you need evidence you do not already have.
- Never repeat an identical tool call; refine the arguments instead.
- Prefer narrow queries with service names and short time windows.
- If a tool fails or is unavailable, try an equivalent signal from another
  tool rather than giving up.

# Visualization policy
When you present numeric time-series or comparisons in your final answer,
render them with the visualization tools (visualize_metrics, generate_flowchart)
so operators can see the shape of the problem in the terminal.

# Safety rules
- Read-only queries are always allowed.
- Mutations (scaling, restarts, deployments) happen ONLY through tools that
  gate on explicit approval. Never describe a mutation as done unless the
  tool result confirms it.
- When evidence is inconclusive, say so; do not invent metrics or log lines.
"""


def build_system_prompt(
    extra_context: Optional[list[str]] = None,
) -> str:
    parts = [SYSTEM_PROMPT]
    for block in extra_context or []:
        if block:
            parts.append(block)
    return "\n\n".join(parts)


def render_knowledge(knowledge: RetrievedKnowledge, max_chars: int = 6000) -> str:
    """Knowledge block for the prompt (reference prompts.ts:271)."""
    if knowledge.empty:
        return ""
    sections = []
    for label, items in (
        ("Runbooks", knowledge.runbooks),
        ("Known issues", knowledge.known_issues),
        ("Postmortems", knowledge.postmortems),
        ("Architecture notes", knowledge.architecture),
    ):
        if not items:
            continue
        lines = [f"## {label}"]
        for item in items[:3]:
            lines.append(f"### {item.title} [{item.doc_id}]")
            lines.append(item.content[:1500])
        sections.append("\n".join(lines))
    text = "# Retrieved knowledge\n\n" + "\n\n".join(sections)
    return text[:max_chars]


def build_iteration_prompt(
    query: str,
    scratchpad_context: str,
    knowledge_block: str,
    iteration: int,
    max_iterations: int,
    warnings: Optional[list[str]] = None,
    memory_block: str = "",
) -> str:
    parts = [f"# Task\n{query}"]
    if knowledge_block:
        parts.append(knowledge_block)
    if memory_block:
        parts.append(memory_block)
    if scratchpad_context:
        parts.append(f"# Evidence gathered so far\n{scratchpad_context}")
    if warnings:
        parts.append("# Warnings\n" + "\n".join(f"- {w}" for w in warnings))
    parts.append(
        f"# Instructions\nIteration {iteration + 1} of {max_iterations}. "
        "Either request the tool calls you need next (JSON tool_calls form), "
        "or, if you have enough evidence, answer in plain text."
    )
    return "\n\n".join(parts)


def build_final_answer_prompt(
    query: str,
    scratchpad_context: str,
    knowledge_block: str,
    memory_block: str = "",
) -> str:
    """Reference prompts.ts:349 — the no-more-tools synthesis call."""
    parts = [f"# Task\n{query}"]
    if knowledge_block:
        parts.append(knowledge_block)
    if memory_block:
        parts.append(memory_block)
    if scratchpad_context:
        parts.append(f"# Evidence gathered\n{scratchpad_context}")
    parts.append(
        "# Instructions\nWrite your final answer now, in plain text. "
        "Summarize findings, state the root cause (or best hypothesis with "
        "confidence high/medium/low), affected services, and next steps. "
        "Cite runbook ids like [doc-id] where knowledge informed the answer. "
        "Do not request any more tool calls."
    )
    return "\n\n".join(parts)


def build_knowledge_only_prompt(query: str, knowledge_block: str) -> str:
    """Fast path for procedural queries answerable from knowledge alone
    (reference agent.ts:356-390)."""
    return (
        f"# Task\n{query}\n\n{knowledge_block}\n\n# Instructions\n"
        "Answer directly from the retrieved knowledge above. Cite documents "
        "as [doc-id]. If the knowledge does not answer the question, say "
        "exactly: KNOWLEDGE_INSUFFICIENT"
    )


def is_procedural_query(query: str) -> bool:
    """Heuristic for the knowledge-only fast path: how-to/procedure questions
    that don't name a live incident."""
    q = query.lower()
    procedural = any(
        kw in q
        for kw in ("how do i", "how to", "what is the procedure", "runbook for",
                   "steps to", "what's the process", "where is the documentation")
    )
    live = any(
        kw in q
        for kw in ("right now", "currently", "is down", "firing", "alert",
                   "incident", "outage", "error rate", "latency spike")
    )
    return procedural and not live
