"""Service graph context: dependencies + blast radius in prompts.

Parity target: reference ``src/agent/service-context.ts`` (:86) — injects
service-graph context (dependencies, dependents, blast radius) for services
mentioned in the conversation.
"""

from __future__ import annotations


from runbookai_tpu.knowledge.store.graph import ServiceGraph


class ServiceContextManager:
    def __init__(self, graph: ServiceGraph, max_services: int = 5):
        self.graph = graph
        self.max_services = max_services
        self._active: list[str] = []

    def observe_services(self, services: list[str]) -> list[str]:
        """Track mentioned services that exist in the graph; returns new ones."""
        added = []
        for svc in services:
            if svc in self.graph.nodes and svc not in self._active:
                self._active.append(svc)
                added.append(svc)
        self._active = self._active[-self.max_services:]
        return added

    def system_prompt_block(self) -> str:
        if not self._active:
            return ""
        lines = ["# Service topology"]
        for svc in self._active:
            deps = self.graph.dependencies_of(svc)
            blast = self.graph.downstream_impact(svc, max_depth=3)
            node = self.graph.nodes[svc]
            detail = []
            if node.team:
                detail.append(f"team {node.team}")
            if node.tier is not None:
                detail.append(f"tier {node.tier}")
            suffix = f" ({', '.join(detail)})" if detail else ""
            lines.append(f"- {svc}{suffix}")
            if deps:
                lines.append(f"  depends on: {', '.join(deps[:6])}")
            if blast:
                lines.append(f"  blast radius if degraded: {', '.join(blast[:6])}")
        return "\n".join(lines)

    def blast_radius(self, service: str) -> list[str]:
        return self.graph.downstream_impact(service)
