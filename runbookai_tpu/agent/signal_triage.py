"""Cross-modality signal triage: active vs stale vs recovered, ranked.

The adversarial simulator splits (``simulate/generator.py``) encode the
failure modes of keyword-matching investigations: a louder-but-stale
red herring on a visible service, an unrelated concurrent fault, a
missing telemetry modality. This module is the deterministic reasoning
that defeats them — the same checks a good on-call walks through before
believing any single signal:

1. **Timeline.** Every signal is dated against the paged incident's
   start. Signals that predate it by more than a margin are STALE;
   a matching recovery/resolved event afterwards marks the story
   RECOVERED. Historical noise stops outranking live evidence.
2. **Topology.** Log lines mentioning calls to other services define a
   symptom graph; candidates are ranked by reachability from the PAGED
   service and by position: a service whose active symptoms point at
   another symptomatic service is a relay, not a root. The
   downstream-most service with severe active evidence wins.
3. **Modality accounting.** Empty/missing modalities are reported as
   facts ("no log group for X; log shipper degraded") instead of being
   silently absent, so the investigation pivots to what survives.

No reference counterpart: ``causal-query.ts`` patterns fire on keywords
alone (SURVEY §2.1); this is the layer the adversarial eval showed was
missing. Exposed as the ``signal_triage`` tool and injected into the
orchestrator's triage context.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional

STALE_MARGIN_MIN = 45.0  # older than incident start by this → historical

_SEVERE = ("ERROR", "FATAL", "CRITICAL")
# Alarm metrics that describe SYMPTOMS (propagation), not causes.
_SYMPTOM_METRICS = ("TargetResponseTime", "Latency", "ResponseTime")
_CALL_RE = re.compile(
    r"(?:upstream call to|call to|backend|outbound call to)\s+"
    r"([a-z0-9][a-z0-9-]+)", re.IGNORECASE)


@dataclass
class SignalNote:
    service: str
    kind: str       # alarm | log | pod | prom
    at: Optional[str]
    status: str     # active | stale | recovered
    severity: str   # severe | symptom | info
    summary: str
    why: str = ""


@dataclass
class TriageReport:
    incident_start: Optional[str]
    paged_service: Optional[str]
    candidates: list[dict[str, Any]] = field(default_factory=list)
    signals: list[SignalNote] = field(default_factory=list)
    modality_notes: list[str] = field(default_factory=list)

    def render(self) -> str:
        lines = [f"signal triage (incident start {self.incident_start}, "
                 f"paged service {self.paged_service}):"]
        if self.modality_notes:
            lines.append("  missing/degraded telemetry:")
            lines += [f"    - {n}" for n in self.modality_notes]
        lines.append("  root-cause candidates, best first:")
        for c in self.candidates[:5]:
            lines.append(f"    {c['service']}  score={c['score']:.1f}  "
                         f"({'; '.join(c['reasons'])})")
        discounted = [s for s in self.signals if s.status != "active"]
        if discounted:
            lines.append("  discounted signals (historical, NOT live "
                         "evidence):")
            for s in discounted[:6]:
                lines.append(f"    - [{s.status}] {s.service} {s.kind}: "
                             f"{s.summary[:80]} ({s.why})")
        return "\n".join(lines)


def _before(ts: Optional[str], ref: Optional[str],
            margin_min: float = 0.0) -> bool:
    """ts < ref - margin, on ISO-8601Z strings (lexicographic-safe)."""
    if not ts or not ref:
        return False
    if margin_min:
        import time as _t

        try:
            ref_s = _t.mktime(_t.strptime(ref, "%Y-%m-%dT%H:%M:%SZ"))
            ts_s = _t.mktime(_t.strptime(ts, "%Y-%m-%dT%H:%M:%SZ"))
            return ts_s < ref_s - margin_min * 60
        except ValueError:
            return ts < ref
    return ts < ref


def triage_signals(
    *,
    alarms: Iterable[dict] = (),
    logs: Optional[dict[str, list[dict]]] = None,
    dd_events: Iterable[dict] = (),
    pods: Iterable[dict] = (),
    prom_alerts: Iterable[dict] = (),
    incident: Optional[dict] = None,
    known_services: Iterable[str] = (),
    stale_margin_min: float = STALE_MARGIN_MIN,
) -> TriageReport:
    """Classify every signal and rank root-cause candidates."""
    logs = logs or {}
    incident = incident or {}
    start = incident.get("createdAt")
    paged = incident.get("service")
    report = TriageReport(incident_start=start, paged_service=paged)

    # Recovery stories: service -> latest recovery-event timestamp.
    recovered_at: dict[str, str] = {}
    for ev in dd_events:
        title = str(ev.get("title", ""))
        text = f"{title} {ev.get('text', '')}".lower()
        if "recover" in text or "resolved" in text:
            for svc in _services_in(f"{title} {ev.get('tags', '')}",
                                    known_services):
                recovered_at[svc] = max(ev.get("ts", ""),
                                        recovered_at.get(svc, ""))

    def classify(svc: str, ts: Optional[str]) -> tuple[str, str]:
        if ts and svc in recovered_at and ts <= recovered_at[svc]:
            return "recovered", (f"a recovery event at {recovered_at[svc]} "
                                 f"closes this story")
        if _before(ts, start, stale_margin_min):
            return "stale", (f"predates incident start {start} by "
                             f">{stale_margin_min:.0f}m")
        return "active", ""

    edges: set[tuple[str, str]] = set()
    svc_names = set(known_services) | {a.get("service", "") for a in alarms}
    svc_names |= {g.split("/")[-1] for g in logs}
    svc_names.discard("")

    for a in alarms:
        svc = a.get("service") or str(a.get("alarmName", "")).split("-")[0]
        status, why = classify(svc, a.get("stateChangedAt"))
        severity = ("symptom" if any(m in str(a.get("metric", ""))
                                     for m in _SYMPTOM_METRICS) else "severe")
        report.signals.append(SignalNote(
            svc, "alarm", a.get("stateChangedAt"), status, severity,
            f"{a.get('metric')}={a.get('currentValue')} "
            f"(threshold {a.get('threshold')})", why))

    for group, entries in logs.items():
        svc = group.split("/")[-1]
        for e in entries:
            level = str(e.get("level", "")).upper()
            msg = str(e.get("message", ""))
            status, why = classify(svc, e.get("ts"))
            severity = ("severe" if level in _SEVERE
                        else "symptom" if "timing out" in msg
                        or "timeout" in msg.lower() else "info")
            report.signals.append(SignalNote(
                svc, "log", e.get("ts"), status, severity,
                f"{level}: {msg}", why))
            if status == "active":
                for callee in _services_in(msg, svc_names):
                    if callee != svc:
                        edges.add((svc, callee))

    for p in pods:
        svc = str(p.get("name", "")).rsplit("-", 2)[0]
        bad = p.get("status") not in (None, "Running") or p.get("restarts", 0)
        if bad:
            report.signals.append(SignalNote(
                svc, "pod", None, "active", "severe",
                f"{p.get('status')} restarts={p.get('restarts', 0)}"))

    for al in prom_alerts:
        svc = (al.get("labels") or {}).get("service", "")
        status, why = classify(svc, al.get("activeAt"))
        report.signals.append(SignalNote(
            svc, "prom", al.get("activeAt"), status, "severe",
            f"{al.get('name')} {al.get('state')}", why))

    # Modality accounting: say what is MISSING, with its meta-signal.
    if not list(alarms):
        report.modality_notes.append(
            "no CloudWatch alarms at all — alarm delivery may be degraded; "
            "rely on prometheus/metrics")
    symptomatic = {s.service for s in report.signals if s.status == "active"}
    for svc in sorted(symptomatic):
        if f"/ecs/{svc}" not in logs and any(
                s.service == svc and s.kind in ("alarm", "prom", "pod")
                for s in report.signals):
            report.modality_notes.append(
                f"no log group for {svc} despite other live signals — "
                f"check the log shipper before concluding from silence")

    # Rank: severe active evidence, reachability from the paged service,
    # relay discount (symptoms pointing at another symptomatic service).
    reachable = _reachable(paged, edges) if paged else set()
    scores: dict[str, float] = {}
    reasons: dict[str, list[str]] = {}
    for s in report.signals:
        if s.status != "active" or not s.service:
            continue
        w = {"severe": 2.0, "symptom": 0.5, "info": 0.2}[s.severity]
        w *= {"pod": 1.5, "alarm": 1.0, "log": 1.0, "prom": 0.8}[s.kind]
        scores[s.service] = scores.get(s.service, 0.0) + w
    for svc in list(scores):
        r = reasons.setdefault(svc, [])
        sev = sum(1 for s in report.signals
                  if s.service == svc and s.status == "active"
                  and s.severity == "severe")
        r.append(f"{sev} severe live signals")
        if svc in reachable or svc == paged:
            scores[svc] += 2.0
            r.append("on the paged incident's symptom path")
        else:
            r.append("NOT on the paged symptom path — may be an "
                     "unrelated concurrent fault")
        if any(src == svc and dst in scores for src, dst in edges):
            scores[svc] *= 0.4
            r.append("its symptoms point at another symptomatic "
                     "service (relay, not root)")
        in_edges = sum(1 for src, dst in edges if dst == svc)
        if in_edges:
            scores[svc] += 1.5 * in_edges
            r.append(f"{in_edges} service(s) report failures calling it")
        stale_n = sum(1 for s in report.signals
                      if s.service == svc and s.status != "active")
        if stale_n:
            r.append(f"{stale_n} older signals discounted as historical")
    report.candidates = sorted(
        ({"service": svc, "score": round(sc, 2), "reasons": reasons[svc]}
         for svc, sc in scores.items()),
        key=lambda c: -c["score"])
    return report


def _services_in(text: str, known: Iterable[str]) -> list[str]:
    found = [m.group(1) for m in _CALL_RE.finditer(text)]
    known_set = set(known)
    out = [f for f in found if not known_set or f in known_set]
    for svc in known_set:
        if svc and svc in text and svc not in out:
            out.append(svc)
    return out


def _reachable(start: Optional[str], edges: set[tuple[str, str]]) -> set:
    seen = {start} if start else set()
    while True:
        nxt = [dst for src, dst in edges if src in seen and dst not in seen]
        if not nxt:
            return seen
        seen.update(nxt)
