"""Hypothesis tree engine used by the free-form agent path.

Parity target: reference ``src/agent/hypothesis.ts`` — depth-limited tree
(``addHypothesis`` :58, ``prune`` :117, ``confirm`` :137), multi-factor
confidence (``calculateConfidence`` :192-222), markdown export (:251), JSON
round-trip (:367). Evidence strength classes and the confidence thresholds
(high ≥70, medium ≥40) follow ``src/agent/confidence.ts:22-46``.
"""

from __future__ import annotations

import json
import time
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional


class HypothesisStatus(str, Enum):
    OPEN = "open"
    INVESTIGATING = "investigating"
    CONFIRMED = "confirmed"
    PRUNED = "pruned"


class EvidenceStrength(str, Enum):
    STRONG_SUPPORT = "strong_support"
    WEAK_SUPPORT = "weak_support"
    NEUTRAL = "neutral"
    WEAK_CONTRADICT = "weak_contradict"
    STRONG_CONTRADICT = "strong_contradict"


@dataclass
class Evidence:
    description: str
    strength: EvidenceStrength = EvidenceStrength.NEUTRAL
    source: str = ""  # tool name / result_id
    ts: float = field(default_factory=time.time)


@dataclass
class Hypothesis:
    id: str
    statement: str
    parent_id: Optional[str] = None
    depth: int = 0
    priority: float = 0.5
    status: HypothesisStatus = HypothesisStatus.OPEN
    evidence: list[Evidence] = field(default_factory=list)
    children: list[str] = field(default_factory=list)
    prune_reason: Optional[str] = None


# Weights mirroring the reference's multi-factor scoring
# (confidence.ts:22-46): chain depth, corroboration, contradiction, direct.
_STRENGTH_SCORE = {
    EvidenceStrength.STRONG_SUPPORT: 30.0,
    EvidenceStrength.WEAK_SUPPORT: 12.0,
    EvidenceStrength.NEUTRAL: 0.0,
    EvidenceStrength.WEAK_CONTRADICT: -15.0,
    EvidenceStrength.STRONG_CONTRADICT: -35.0,
}


def confidence_score(h: Hypothesis) -> float:
    """0-100 score; ≥70 high, ≥40 medium (reference thresholds)."""
    score = 25.0  # prior for a plausible hypothesis
    supports = sum(1 for e in h.evidence if "support" in e.strength.value)
    contradictions = sum(1 for e in h.evidence if "contradict" in e.strength.value)
    for e in h.evidence:
        score += _STRENGTH_SCORE[e.strength]
    if supports >= 2:
        score += 10.0  # corroboration bonus
    if contradictions and supports:
        score -= 5.0  # mixed-signal penalty
    score += min(10.0, 3.0 * h.depth)  # deeper chains earn specificity credit
    return max(0.0, min(100.0, score))


def confidence_label(score: float) -> str:
    if score >= 70:
        return "high"
    if score >= 40:
        return "medium"
    return "low"


class HypothesisEngine:
    def __init__(self, max_depth: int = 4, max_hypotheses: int = 10):
        self.max_depth = max_depth
        self.max_hypotheses = max_hypotheses
        self.nodes: dict[str, Hypothesis] = {}
        self.root_ids: list[str] = []

    def add(self, statement: str, parent_id: Optional[str] = None,
            priority: float = 0.5) -> Optional[Hypothesis]:
        if len(self.nodes) >= self.max_hypotheses:
            return None
        depth = 0
        if parent_id is not None:
            parent = self.nodes[parent_id]
            depth = parent.depth + 1
            if depth > self.max_depth:
                return None
        h = Hypothesis(id=f"h{len(self.nodes) + 1}-{uuid.uuid4().hex[:6]}",
                       statement=statement, parent_id=parent_id, depth=depth,
                       priority=priority)
        self.nodes[h.id] = h
        if parent_id is None:
            self.root_ids.append(h.id)
        else:
            self.nodes[parent_id].children.append(h.id)
        return h

    def add_evidence(self, hypothesis_id: str, evidence: Evidence) -> None:
        self.nodes[hypothesis_id].evidence.append(evidence)

    def prune(self, hypothesis_id: str, reason: str) -> None:
        node = self.nodes[hypothesis_id]
        node.status = HypothesisStatus.PRUNED
        node.prune_reason = reason
        for child in node.children:
            if self.nodes[child].status == HypothesisStatus.OPEN:
                self.prune(child, f"parent pruned: {reason}")

    def confirm(self, hypothesis_id: str) -> None:
        self.nodes[hypothesis_id].status = HypothesisStatus.CONFIRMED

    def open_hypotheses(self) -> list[Hypothesis]:
        """Open/investigating nodes, highest (priority, confidence) first."""
        candidates = [
            h for h in self.nodes.values()
            if h.status in (HypothesisStatus.OPEN, HypothesisStatus.INVESTIGATING)
        ]
        return sorted(candidates,
                      key=lambda h: (h.priority, confidence_score(h)), reverse=True)

    def best(self) -> Optional[Hypothesis]:
        confirmed = [h for h in self.nodes.values() if h.status == HypothesisStatus.CONFIRMED]
        if confirmed:
            return max(confirmed, key=confidence_score)
        alive = self.open_hypotheses()
        return alive[0] if alive else None

    # ------------------------------------------------------------ export

    def to_markdown(self) -> str:
        lines = ["## Hypothesis tree"]
        icons = {HypothesisStatus.CONFIRMED: "[CONFIRMED]",
                 HypothesisStatus.PRUNED: "[pruned]",
                 HypothesisStatus.OPEN: "[open]",
                 HypothesisStatus.INVESTIGATING: "[investigating]"}

        def render(node_id: str, indent: int) -> None:
            h = self.nodes[node_id]
            score = confidence_score(h)
            lines.append(
                "  " * indent
                + f"- {icons[h.status]} {h.statement} "
                + f"(confidence {score:.0f}/{confidence_label(score)}, "
                + f"{len(h.evidence)} evidence)"
            )
            for e in h.evidence[:3]:
                lines.append("  " * (indent + 1) + f"- {e.strength.value}: {e.description[:120]}")
            for child in h.children:
                render(child, indent + 1)

        for rid in self.root_ids:
            render(rid, 0)
        return "\n".join(lines)

    def to_json(self) -> str:
        return json.dumps(
            {
                "max_depth": self.max_depth,
                "max_hypotheses": self.max_hypotheses,
                "root_ids": self.root_ids,
                "nodes": {
                    nid: {
                        "id": h.id, "statement": h.statement, "parent_id": h.parent_id,
                        "depth": h.depth, "priority": h.priority, "status": h.status.value,
                        "prune_reason": h.prune_reason, "children": h.children,
                        "evidence": [
                            {"description": e.description, "strength": e.strength.value,
                             "source": e.source, "ts": e.ts}
                            for e in h.evidence
                        ],
                    }
                    for nid, h in self.nodes.items()
                },
            }
        )

    @classmethod
    def from_json(cls, payload: str) -> "HypothesisEngine":
        data = json.loads(payload)
        engine = cls(max_depth=data["max_depth"], max_hypotheses=data["max_hypotheses"])
        engine.root_ids = list(data["root_ids"])
        for nid, raw in data["nodes"].items():
            engine.nodes[nid] = Hypothesis(
                id=raw["id"], statement=raw["statement"], parent_id=raw["parent_id"],
                depth=raw["depth"], priority=raw["priority"],
                status=HypothesisStatus(raw["status"]),
                prune_reason=raw.get("prune_reason"), children=list(raw["children"]),
                evidence=[
                    Evidence(description=e["description"],
                             strength=EvidenceStrength(e["strength"]),
                             source=e.get("source", ""), ts=e.get("ts", 0.0))
                    for e in raw["evidence"]
                ],
            )
        return engine
