"""Importance-scored context compaction.

Parity target: reference ``src/agent/context-compactor.ts`` — six score
components (recency, query relevance, error signals, hypothesis relevance,
service relevance, cited-in-notes) combined by per-preset weights into a
0-1 score (:106-365), a plan with full/compact/clear tiers bounded by
``max_full_results``/``max_compact_results`` and the ``min_score_for_full``/
``min_score_to_keep`` thresholds (:376-470), estimated tokens saved,
``explain_score`` debugging (:560-590), and the ``createCompactor`` presets
(:598: incident weights errors+hypotheses, research weights query+recency,
balanced is the default config).

The plan maps ``result_id -> tier`` and is applied by
``Scratchpad.apply_compaction_plan`` when the estimated context exceeds the
threshold (reference ``agent.ts:414-441``).
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field, replace

from runbookai_tpu.agent.scratchpad import TIER_CLEARED, TIER_COMPACT, TIER_FULL, Scratchpad

_CRITICAL_RE = re.compile(r"error|failed|exception|critical|alarm", re.IGNORECASE)
_WARNING_RE = re.compile(r"warning|timeout|unhealthy|degraded", re.IGNORECASE)


@dataclass(frozen=True)
class ScoreWeights:
    recency: float = 0.2
    query_relevance: float = 0.2
    error_signals: float = 0.2
    hypothesis_relevance: float = 0.15
    service_relevance: float = 0.1
    cited_in_notes: float = 0.15


@dataclass(frozen=True)
class CompactorConfig:
    weights: ScoreWeights = field(default_factory=ScoreWeights)
    max_full_results: int = 10
    max_compact_results: int = 15
    min_score_for_full: float = 0.6
    min_score_to_keep: float = 0.2
    tokens_per_full_result: int = 2000
    tokens_per_compact_result: int = 150


PRESETS: dict[str, CompactorConfig] = {
    # Incident investigation: prioritize errors and hypothesis relevance.
    "incident": CompactorConfig(
        weights=ScoreWeights(recency=0.15, query_relevance=0.15,
                             error_signals=0.3, hypothesis_relevance=0.2,
                             service_relevance=0.1, cited_in_notes=0.1),
        max_full_results=15, min_score_for_full=0.5),
    # Research: prioritize query relevance and recency.
    "research": CompactorConfig(
        weights=ScoreWeights(recency=0.25, query_relevance=0.3,
                             error_signals=0.1, hypothesis_relevance=0.1,
                             service_relevance=0.1, cited_in_notes=0.15),
        max_full_results=8, min_score_for_full=0.6),
    "balanced": CompactorConfig(),
}


@dataclass
class ScoredResult:
    result_id: str
    score: float
    components: dict[str, float]
    keep_full: bool


class ContextCompactor:
    def __init__(self, preset: str | CompactorConfig = "balanced"):
        self.config = (PRESETS[preset] if isinstance(preset, str) else preset)

    # ------------------------------------------------------------- components

    @staticmethod
    def _score_recency(rank_from_newest: int, total: int) -> float:
        if total <= 1:
            return 1.0
        return 1.0 - rank_from_newest / (total - 1)

    @staticmethod
    def _score_query_relevance(entry, query: str) -> float:
        q_words = {w for w in re.findall(r"\w{4,}", (query or "").lower())}
        if not q_words:
            return 0.0
        text = (json.dumps(entry.args, default=str)
                + json.dumps(entry.full, default=str)[:4000]).lower()
        matches = sum(1 for w in q_words if w in text)
        return min(1.0, matches / len(q_words))

    @staticmethod
    def _score_error_signals(entry) -> float:
        compact = entry.compact or {}
        if compact.get("has_errors"):
            return 1.0
        health = compact.get("health_status")
        if health == "critical":
            return 1.0
        if health == "degraded":
            return 0.7
        text = json.dumps(entry.full, default=str)[:20000]
        if _CRITICAL_RE.search(text):
            return 1.0
        if _WARNING_RE.search(text):
            return 0.6
        return 0.0

    @staticmethod
    def _score_hypothesis_relevance(entry, hypotheses, symptoms) -> float:
        """Evidence tied to an active hypothesis (or a symptom it names)
        outranks incidental results (context-compactor.ts:150-200)."""
        if not hypotheses and not symptoms:
            return 0.0
        text = (json.dumps(entry.args, default=str)
                + (entry.compact or {}).get("summary", "")
                + json.dumps(entry.full, default=str)[:4000]).lower()
        for statement in hypotheses or []:
            words = [w for w in re.findall(r"\w{4,}", statement.lower())][:8]
            if words and sum(1 for w in words if w in text) >= max(2, len(words) // 2):
                return 1.0
        for symptom in symptoms or []:
            if symptom and symptom.lower()[:20] in text:
                return 0.5
        return 0.0

    @staticmethod
    def _score_service_relevance(entry, services) -> float:
        if not services:
            return 0.0
        compact_services = [s.lower() for s in (entry.compact or {}).get("services", [])]
        text = (json.dumps(entry.args, default=str)
                + json.dumps(entry.full, default=str)[:4000]).lower()
        for service in services:
            s = service.lower()
            if any(s in cs for cs in compact_services):
                return 1.0
            if s in text:
                return 0.8
        return 0.0

    @staticmethod
    def _score_cited(entry, cited_ids, findings) -> float:
        if cited_ids and entry.result_id in cited_ids:
            return 1.0
        # Fallback: a finding that names this result's summary content.
        # Word-boundary match: ids are sequential (r1, r2, ...), so a bare
        # substring test would let r1 false-match a finding citing r12.
        summary = (entry.compact or {}).get("summary", "")
        id_re = re.compile(rf"\b{re.escape(entry.result_id)}\b")
        for finding in findings or []:
            if id_re.search(finding):
                return 1.0
            words = [w for w in re.findall(r"\w{5,}", finding.lower())][:6]
            if words and summary and all(w in summary.lower() for w in words[:2]):
                return 0.5
        return 0.0

    # ---------------------------------------------------------------- scoring

    def score(self, entry, rank_from_newest: int, query: str, total: int = 1,
              hypotheses=None, services=None, symptoms=None,
              cited_ids=None, findings=None) -> ScoredResult:
        components = {
            "recency": self._score_recency(rank_from_newest, total),
            "query_relevance": self._score_query_relevance(entry, query),
            "error_signals": self._score_error_signals(entry),
            "hypothesis_relevance": self._score_hypothesis_relevance(
                entry, hypotheses, symptoms),
            "service_relevance": self._score_service_relevance(entry, services),
            "cited_in_notes": self._score_cited(entry, cited_ids, findings),
        }
        w = self.config.weights
        total_score = (components["recency"] * w.recency
                       + components["query_relevance"] * w.query_relevance
                       + components["error_signals"] * w.error_signals
                       + components["hypothesis_relevance"] * w.hypothesis_relevance
                       + components["service_relevance"] * w.service_relevance
                       + components["cited_in_notes"] * w.cited_in_notes)
        return ScoredResult(entry.result_id, total_score, components,
                            keep_full=total_score >= self.config.min_score_for_full)

    def plan(self, scratchpad: Scratchpad, query: str,
             memory=None, hypotheses=None, cited_ids=None) -> dict[str, str]:
        """Score all tool results and assign tiers.

        ``memory`` is an ``InvestigationMemory`` (services/symptoms/findings
        feed the hypothesis/service/cited components); ``hypotheses`` is a
        list of active hypothesis statements; ``cited_ids`` result ids known
        to be cited in notes/answers.
        """
        services = list(getattr(memory, "services", []) or [])
        symptoms = list(getattr(memory, "symptoms", []) or [])
        findings = list(getattr(memory, "findings", []) or [])
        entries = [scratchpad.results[rid] for rid in scratchpad.list_result_ids()]
        n = len(entries)
        scored = [
            self.score(e, rank_from_newest=n - 1 - i, query=query, total=n,
                       hypotheses=hypotheses, services=services,
                       symptoms=symptoms, cited_ids=cited_ids,
                       findings=findings)
            for i, e in enumerate(entries)
        ]
        scored.sort(key=lambda s: s.score, reverse=True)

        cfg = self.config
        plan: dict[str, str] = {}
        full = compact = 0
        for s in scored:
            if s.score >= cfg.min_score_for_full and full < cfg.max_full_results:
                plan[s.result_id] = TIER_FULL
                full += 1
            elif s.score >= cfg.min_score_to_keep and compact < cfg.max_compact_results:
                # Includes full-bucket overflow: still-important results
                # demote to compact rather than vanish.
                plan[s.result_id] = TIER_COMPACT
                compact += 1
            else:
                plan[s.result_id] = TIER_CLEARED
        # Never clear everything: the newest result stays at least compact.
        if entries and all(t == TIER_CLEARED for t in plan.values()):
            plan[entries[-1].result_id] = TIER_COMPACT
        return plan

    def estimated_tokens_saved(self, plan: dict[str, str]) -> int:
        cfg = self.config
        saved = 0
        for tier in plan.values():
            if tier == TIER_COMPACT:
                saved += cfg.tokens_per_full_result - cfg.tokens_per_compact_result
            elif tier == TIER_CLEARED:
                saved += cfg.tokens_per_full_result
        return saved

    def explain_score(self, scored: ScoredResult) -> str:
        """Debugging view of a score (context-compactor.ts:575)."""
        w = self.config.weights
        lines = [f"Total Score: {scored.score:.3f}",
                 f"Keep Full: {scored.keep_full}", "", "Components:"]
        for name, value in scored.components.items():
            lines.append(f"  {name}: {value:.2f} x {getattr(w, name)}")
        return "\n".join(lines)


def create_compactor(preset: str = "balanced",
                     **overrides) -> ContextCompactor:
    """Reference ``createCompactor`` presets (context-compactor.ts:598);
    keyword overrides patch the preset config (e.g. ``max_full_results=4``)."""
    cfg = PRESETS[preset]
    if overrides:
        cfg = replace(cfg, **overrides)
    return ContextCompactor(cfg)
