"""Importance-scored context compaction.

Parity target: reference ``src/agent/context-compactor.ts`` (:106 scoring —
recency, error signals, query relevance, size; presets ``incident`` /
``research`` / ``balanced`` :598). Emits a ``{result_id: tier}`` plan applied
by ``Scratchpad.apply_compaction_plan`` when the estimated context exceeds the
threshold (reference ``agent.ts:414-441``).
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass

from runbookai_tpu.agent.scratchpad import TIER_CLEARED, TIER_COMPACT, TIER_FULL, Scratchpad

_ERROR_RE = re.compile(r"error|fail|timeout|exception|5\d\d|critical", re.IGNORECASE)


@dataclass(frozen=True)
class CompactorPreset:
    name: str
    keep_full: int  # top-K results kept full
    keep_compact: int  # next-K kept compact; the rest cleared
    recency_weight: float
    error_weight: float
    relevance_weight: float
    size_penalty: float


PRESETS = {
    # Incidents favor fresh signals; research favors breadth of retained detail.
    "incident": CompactorPreset("incident", keep_full=4, keep_compact=8,
                                recency_weight=3.0, error_weight=2.0,
                                relevance_weight=1.0, size_penalty=1.0),
    "research": CompactorPreset("research", keep_full=8, keep_compact=12,
                                recency_weight=1.0, error_weight=1.0,
                                relevance_weight=2.0, size_penalty=0.5),
    "balanced": CompactorPreset("balanced", keep_full=6, keep_compact=10,
                                recency_weight=2.0, error_weight=1.5,
                                relevance_weight=1.5, size_penalty=0.8),
}


class ContextCompactor:
    def __init__(self, preset: str = "balanced"):
        self.preset = PRESETS[preset]

    def score(self, entry, rank_from_newest: int, query: str) -> float:
        p = self.preset
        recency = p.recency_weight / (1.0 + rank_from_newest)
        text = json.dumps(entry.full, default=str) if entry.full is not None else ""
        errors = p.error_weight * min(3, len(_ERROR_RE.findall(text[:20000]))) / 3.0
        q_words = {w for w in re.findall(r"\w{4,}", query.lower())}
        arg_text = (json.dumps(entry.args, default=str) + text[:2000]).lower()
        overlap = sum(1 for w in q_words if w in arg_text)
        relevance = p.relevance_weight * min(1.0, overlap / max(1, len(q_words)))
        size_penalty = p.size_penalty * min(1.0, len(text) / 50_000)
        return recency + errors + relevance - size_penalty

    def plan(self, scratchpad: Scratchpad, query: str) -> dict[str, str]:
        """Score all tool results and assign tiers by rank."""
        entries = [scratchpad.results[rid] for rid in scratchpad.list_result_ids()]
        n = len(entries)
        scored = [
            (self.score(e, rank_from_newest=n - 1 - i, query=query), e)
            for i, e in enumerate(entries)
        ]
        scored.sort(key=lambda t: t[0], reverse=True)
        plan: dict[str, str] = {}
        for rank, (_, entry) in enumerate(scored):
            if rank < self.preset.keep_full:
                plan[entry.result_id] = TIER_FULL
            elif rank < self.preset.keep_full + self.preset.keep_compact:
                plan[entry.result_id] = TIER_COMPACT
            else:
                plan[entry.result_id] = TIER_CLEARED
        return plan


def create_compactor(preset: str = "balanced") -> ContextCompactor:
    """Reference ``createCompactor`` presets (context-compactor.ts:598)."""
    return ContextCompactor(preset)
