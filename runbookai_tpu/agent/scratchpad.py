"""Scratchpad: per-session JSONL audit trail + tiered tool-result storage.

Parity target: reference ``src/agent/scratchpad.ts`` — JSONL under
``.runbook/scratchpad/`` (:84-137), tiered full→compact→cleared storage of tool
results with drill-down by ``result_id`` (:327), graceful per-tool call limits
that warn but never block (:173), similar-query detection, tiered context
build (:382) and compaction-plan application (:271). The JSONL trail is
load-bearing for the product's auditability claim and is kept verbatim.
"""

from __future__ import annotations

import json
import time
import uuid
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

from runbookai_tpu.agent.types import ToolCall

# Storage tiers for tool results.
TIER_FULL = "full"
TIER_COMPACT = "compact"
TIER_CLEARED = "cleared"

# Default graceful limits per tool (reference scratchpad.ts:33-47 spirit:
# generous defaults; limits warn, never block).
DEFAULT_TOOL_CALL_LIMIT = 15


def _json_default(obj: Any) -> Any:
    if hasattr(obj, "__dict__"):
        return obj.__dict__
    return str(obj)


@dataclass
class ToolResultEntry:
    result_id: str
    tool: str
    args: dict[str, Any]
    tier: str = TIER_FULL
    full: Any = None
    compact: Optional[dict[str, Any]] = None  # summary/highlights/itemCount/...
    error: Optional[str] = None
    duration_ms: float = 0.0
    ts: float = field(default_factory=time.time)

    def context_text(self) -> str:
        """Render for the prompt according to the current tier."""
        header = f"[{self.result_id}] {self.tool}({json.dumps(self.args, default=_json_default)})"
        if self.error:
            return f"{header} -> ERROR: {self.error}"
        if self.tier == TIER_CLEARED:
            return (
                f"{header} -> (result cleared to save context; "
                f"use get_full_result with result_id={self.result_id!r} to retrieve)"
            )
        if self.tier == TIER_COMPACT and self.compact is not None:
            summary = self.compact.get("summary", "")
            highlights = self.compact.get("highlights") or []
            parts = [f"{header} -> {summary}"]
            if isinstance(highlights, dict):  # per-tool structured highlights
                for k, v in list(highlights.items())[:5]:
                    parts.append(f"  - {k}: {json.dumps(v, default=_json_default)[:160]}")
            else:
                for h in highlights[:5]:
                    parts.append(f"  - {h}")
            parts.append(f"  (compacted; drill down via get_full_result {self.result_id})")
            return "\n".join(parts)
        return f"{header} ->\n{json.dumps(self.full, indent=2, default=_json_default)[:8000]}"


class Scratchpad:
    """Append-only session log + in-memory tiered tool-result store."""

    def __init__(
        self,
        session_id: Optional[str] = None,
        root: str | Path = ".runbook/scratchpad",
        tool_limits: Optional[dict[str, int]] = None,
        default_limit: int = DEFAULT_TOOL_CALL_LIMIT,
        persist: bool = True,
    ):
        self.session_id = session_id or f"session-{uuid.uuid4().hex[:10]}"
        self.root = Path(root)
        self.persist = persist
        self.path = self.root / f"{self.session_id}.jsonl"
        self.tool_limits = tool_limits or {}
        self.default_limit = default_limit
        self.entries: list[dict[str, Any]] = []
        self.results: dict[str, ToolResultEntry] = {}
        self._result_order: list[str] = []
        self._tool_counts: dict[str, int] = {}
        self._call_signatures: list[str] = []
        if self.persist:
            self.root.mkdir(parents=True, exist_ok=True)
        self.append("init", {"session_id": self.session_id})

    # ----------------------------------------------------------------- JSONL

    def append(self, kind: str, data: dict[str, Any]) -> None:
        entry = {"kind": kind, "ts": time.time(), **data}
        self.entries.append(entry)
        if self.persist:
            with self.path.open("a") as f:
                f.write(json.dumps(entry, default=_json_default) + "\n")

    def append_thinking(self, text: str) -> None:
        self.append("thinking", {"text": text})

    # ------------------------------------------------------------ tool calls

    @staticmethod
    def call_signature(call: ToolCall) -> str:
        return f"{call.name}:{json.dumps(call.args, sort_keys=True, default=_json_default)}"

    def record_call_signature(self, call: ToolCall) -> int:
        """Track exact-repeat calls; returns how many times this signature has
        now been seen (agent loop warns at >2 — reference agent.ts:529-548)."""
        sig = self.call_signature(call)
        self._call_signatures.append(sig)
        return self._call_signatures.count(sig)

    def can_call_tool(self, tool: str) -> tuple[bool, Optional[str]]:
        """Graceful limit check: always allows, returns a warning string once
        the per-tool limit is exceeded (reference scratchpad.ts:173)."""
        limit = self.tool_limits.get(tool, self.default_limit)
        count = self._tool_counts.get(tool, 0)
        if count >= limit:
            return True, (
                f"Tool {tool!r} has been called {count} times (soft limit {limit}). "
                "Consider concluding with the evidence gathered."
            )
        return True, None

    def append_tool_result(
        self,
        call: ToolCall,
        result: Any = None,
        error: Optional[str] = None,
        duration_ms: float = 0.0,
        compact: Optional[dict[str, Any]] = None,
    ) -> ToolResultEntry:
        self._tool_counts[call.name] = self._tool_counts.get(call.name, 0) + 1
        result_id = f"r{len(self._result_order) + 1}"
        entry = ToolResultEntry(
            result_id=result_id,
            tool=call.name,
            args=call.args,
            full=result,
            compact=compact,
            error=error,
            duration_ms=duration_ms,
        )
        self.results[result_id] = entry
        self._result_order.append(result_id)
        self.append(
            "tool_result",
            {
                "result_id": result_id,
                "tool": call.name,
                "args": call.args,
                "error": error,
                "duration_ms": duration_ms,
                # Persist the full result in the audit trail even when the
                # in-context tier later degrades — the JSONL is the audit log.
                "result": result,
            },
        )
        return entry

    # ------------------------------------------------------------- drilldown

    def get_result_by_id(self, result_id: str) -> Optional[ToolResultEntry]:
        return self.results.get(result_id)

    def list_result_ids(self) -> list[str]:
        return list(self._result_order)

    def list_results(self) -> list[dict[str, Any]]:
        return [
            {
                "result_id": r.result_id,
                "tool": r.tool,
                "tier": r.tier,
                "error": r.error,
                "summary": (r.compact or {}).get("summary"),
            }
            for r in (self.results[rid] for rid in self._result_order)
        ]

    # ------------------------------------------------------------ compaction

    def clear_oldest_tool_results(self, keep_last: int = 5) -> int:
        """Degrade oldest results to cleared, keeping the newest K full."""
        cleared = 0
        for rid in self._result_order[:-keep_last] if keep_last else self._result_order:
            entry = self.results[rid]
            if entry.tier != TIER_CLEARED:
                entry.tier = TIER_CLEARED
                cleared += 1
        return cleared

    def apply_compaction_plan(self, plan: dict[str, str]) -> None:
        """Apply {result_id: tier} from the ContextCompactor
        (reference scratchpad.ts:271)."""
        for rid, tier in plan.items():
            entry = self.results.get(rid)
            if entry and tier in (TIER_FULL, TIER_COMPACT, TIER_CLEARED):
                entry.tier = tier
        self.append("compaction", {"plan": plan})

    # --------------------------------------------------------------- context

    def build_tiered_context(self, max_chars: Optional[int] = None) -> str:
        """Render all tool results for the iteration prompt, honoring tiers
        (reference scratchpad.ts:382)."""
        blocks = [self.results[rid].context_text() for rid in self._result_order]
        text = "\n\n".join(blocks)
        if max_chars is not None and len(text) > max_chars:
            text = text[-max_chars:]
        return text

    def get_tool_usage_status(self) -> dict[str, dict[str, int]]:
        return {
            tool: {"count": count, "limit": self.tool_limits.get(tool, self.default_limit)}
            for tool, count in sorted(self._tool_counts.items())
        }

    @classmethod
    def load(cls, session_id: str, root: str | Path = ".runbook/scratchpad") -> "Scratchpad":
        """Rehydrate a scratchpad from its JSONL (replayable audit log)."""
        pad = cls(session_id=session_id, root=root, persist=False)
        path = Path(root) / f"{session_id}.jsonl"
        if path.is_file():
            for line in path.read_text().splitlines():
                try:
                    entry = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if entry.get("kind") == "tool_result":
                    call = ToolCall(
                        id="replay", name=entry["tool"], args=entry.get("args") or {}
                    )
                    pad.append_tool_result(
                        call,
                        result=entry.get("result"),
                        error=entry.get("error"),
                        duration_ms=entry.get("duration_ms", 0.0),
                    )
        pad.persist = False
        return pad
