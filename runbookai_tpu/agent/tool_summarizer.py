"""Per-tool compact summaries keeping the agent's context small.

Parity target: reference ``src/agent/tool-summarizer.ts`` — the
``CompactToolResult`` contract (:13-28: summary, highlights, itemCount,
hasErrors, services, healthStatus) and the per-tool summarizer registry
(:724-731: aws_query, cloudwatch_alarms, cloudwatch_logs, pagerduty get/list,
datadog, prometheus, search_knowledge + default). Field extraction is
re-derived for THIS build's tool result shapes (e.g. ``tools/aws.py``
returns ``{service, category, count, resources}`` per service;
``search_knowledge`` returns ranked chunk hits), not copied.

Summaries are pure functions of the result payload — no LLM call — and the
``result_id`` kept by the scratchpad enables drill-down via
``get_full_result``. These are load-bearing for long investigations: context
stays small *because* the compact tier preserves the decision-relevant
fields (alarm states, error counts, notable resource names), not a prefix.
"""

from __future__ import annotations

import json
import re
from typing import Any, Callable, Optional

_ERROR_WORDS = re.compile(
    r"\b(error|exception|fail(?:ed|ure)?|timeout|throttl|oom|denied|refused|5\d\d|crit)\w*",
    re.IGNORECASE,
)

HEALTHY, DEGRADED, CRITICAL, UNKNOWN = "healthy", "degraded", "critical", "unknown"


def _walk_strings(obj: Any, limit: int = 400):
    stack = [obj]
    seen = 0
    while stack and seen < limit:
        cur = stack.pop()
        if isinstance(cur, str):
            seen += 1
            yield cur
        elif isinstance(cur, dict):
            stack.extend(cur.values())
        elif isinstance(cur, (list, tuple)):
            stack.extend(cur)


def _count_items(result: Any) -> int:
    if isinstance(result, list):
        return len(result)
    if isinstance(result, dict):
        for key in ("items", "results", "alarms", "events", "logs", "instances",
                    "pods", "incidents", "series", "resources", "documents",
                    "alerts", "monitors", "deployments", "nodes"):
            v = result.get(key)
            if isinstance(v, list):
                return len(v)
        return len(result)
    return 1


def _find_services(result: Any) -> list[str]:
    found: set[str] = set()
    for key in ("service", "serviceName", "service_name", "name", "functionName",
                "cluster", "namespace", "deployment"):
        stack = [result]
        while stack:
            cur = stack.pop()
            if isinstance(cur, dict):
                v = cur.get(key)
                if isinstance(v, str) and 0 < len(v) < 80:
                    found.add(v)
                stack.extend(cur.values())
            elif isinstance(cur, list):
                stack.extend(cur[:50])
    return sorted(found)[:10]


def _has_error_signals(result: Any) -> bool:
    for s in _walk_strings(result):
        if _ERROR_WORDS.search(s):
            return True
    return False


def _health_from_signals(result: Any) -> str:
    """Generic fallback health: count error-looking strings."""
    text_signals = 0
    for s in _walk_strings(result):
        if _ERROR_WORDS.search(s):
            text_signals += 1
        if text_signals >= 3:
            return CRITICAL
    return DEGRADED if text_signals else HEALTHY


def _text_highlights(result: Any, max_items: int = 5) -> list[str]:
    out = []
    for s in _walk_strings(result):
        if _ERROR_WORDS.search(s) and len(s) > 10:
            out.append(s[:200])
            if len(out) >= max_items:
                break
    return out


def _compact(summary: str, highlights: Any, item_count: int, services: list[str],
             health: str, result: Any, has_errors: Optional[bool] = None) -> dict:
    return {
        "summary": summary,
        "highlights": highlights,
        "item_count": item_count,
        "services": services,
        "health_status": health,
        "has_errors": (_has_error_signals(result)
                       if has_errors is None else has_errors),
        "size_bytes": len(json.dumps(result, default=str)) if result is not None else 0,
    }


# --------------------------------------------------------------------------- #
# per-tool summarizers (tool-summarizer.ts:190-721, re-derived for our shapes)
# --------------------------------------------------------------------------- #


def _resources_of(payload: Any) -> list:
    """A service's resource list in either tool shape: the real executor's
    ``{count, resources: [...]}`` payload or the simulated flat list."""
    if isinstance(payload, dict) and isinstance(payload.get("resources"), list):
        return payload["resources"]
    if isinstance(payload, list):
        return payload
    return []


_NOTABLE_KEYS = ("name", "service", "serviceName", "functionName", "instanceId",
                 "alarmName", "DBInstanceIdentifier", "clusterName", "id")


def _notable_name(resource: Any) -> Optional[str]:
    if isinstance(resource, dict):
        for key in _NOTABLE_KEYS:
            v = resource.get(key)
            if isinstance(v, str) and v:
                return v
    return None


def _summarize_aws_query(args: dict, result: Any) -> dict:
    if not isinstance(result, dict):
        return _summarize_default("aws_query", args, result)
    if "error" in result:
        return _compact(f"aws_query error: {str(result['error'])[:150]}", {},
                        0, [], UNKNOWN, result, has_errors=True)
    # Normalize: single-service answers ({service: [...], note}) and
    # multi-service fan-outs ({sid: payload, ...}) both become sid -> payload.
    per_service = {k: v for k, v in result.items()
                   if k not in ("note",) and isinstance(v, (list, dict))}
    total = 0
    errors = 0
    notable: list[str] = []
    highlights: dict[str, Any] = {}
    for sid, payload in per_service.items():
        if isinstance(payload, dict) and "error" in payload:
            errors += 1
            highlights[sid] = {"error": str(payload["error"])[:120]}
            continue
        resources = _resources_of(payload)
        total += len(resources)
        names = [n for n in (_notable_name(r) for r in resources[:10]) if n][:3]
        notable.extend(f"{sid}/{n}" for n in names)
        highlights[sid] = {"count": len(resources), "notable": names,
                           "sample": resources[:2]}
    notable = list(dict.fromkeys(notable))[:3]
    summary = (f"Queried {len(per_service)} AWS service(s), "
               f"found {total} resource(s).")
    if notable:
        summary += f" Notable: {', '.join(notable)}."
    if errors:
        summary += f" {errors} error(s)."
    return _compact(summary, highlights, total, _find_services(result),
                    _health_from_signals(result), result,
                    has_errors=errors > 0 or _has_error_signals(result))


def _summarize_cloudwatch_alarms(args: dict, result: Any) -> dict:
    alarms = result.get("alarms", []) if isinstance(result, dict) else []
    in_alarm = [a for a in alarms
                if isinstance(a, dict) and a.get("state") in ("ALARM", "alarm")]
    names = [a.get("alarmName", "?") for a in in_alarm[:5] if isinstance(a, dict)]
    health = HEALTHY if not in_alarm else (CRITICAL if len(in_alarm) > 2 else DEGRADED)
    summary = f"{len(alarms)} alarm(s). {len(in_alarm)} in ALARM state."
    if names:
        summary += f" Top: {', '.join(names[:3])}."
    return _compact(summary,
                    {"total": len(alarms), "alarming": len(in_alarm),
                     "alarm_names": names},
                    len(alarms), _find_services(result), health, result,
                    has_errors=bool(in_alarm))


def _summarize_cloudwatch_logs(args: dict, result: Any) -> dict:
    group = args.get("log_group", "logs")
    pattern = args.get("filter_pattern", "")
    if not isinstance(result, dict) or "error" in result:
        err = result.get("error") if isinstance(result, dict) else str(result)
        return _compact(f"Log search in {group} failed: {str(err)[:120]}",
                        {}, 0, [], UNKNOWN, result, has_errors=True)
    events = result.get("events", [])
    error_events = [e for e in events if isinstance(e, dict)
                    and _ERROR_WORDS.search(str(e.get("message", "")))]
    samples = [str(e.get("message", ""))[:100] for e in error_events[:2]
               if isinstance(e, dict)] or \
              [str(e.get("message", ""))[:100] for e in events[:2]
               if isinstance(e, dict)]
    summary = (f"Found {len(events)} log event(s) in {group}"
               + (f' matching "{pattern}"' if pattern else "")
               + f". {len(error_events)} error(s).")
    return _compact(summary,
                    {"count": len(events), "error_count": len(error_events),
                     "samples": samples},
                    len(events), _find_services(result),
                    DEGRADED if error_events else HEALTHY, result,
                    has_errors=bool(error_events))


def _summarize_pd_incident(args: dict, result: Any) -> dict:
    if not isinstance(result, dict) or "error" in result:
        err = result.get("error") if isinstance(result, dict) else str(result)
        return _compact(f"PagerDuty incident lookup failed: {str(err)[:120]}",
                        {}, 0, [], UNKNOWN, result, has_errors=True)
    inc = result.get("incident", result)  # tolerate both wrappers
    if not isinstance(inc, dict):  # malformed wrapper: summarize the outer
        inc = result
    status = inc.get("status", "unknown")
    urgency = inc.get("urgency", "unknown")
    title = str(inc.get("title", inc.get("summary", "incident")))[:50]
    service = inc.get("service")
    alerts = inc.get("alerts", result.get("alerts", []))
    health = HEALTHY if status == "resolved" else (
        CRITICAL if urgency == "high" else DEGRADED)
    services = _find_services(result)
    if isinstance(service, str) and service not in services:
        services.append(service)
    return _compact(
        f'Incident "{title}": {status} ({urgency}). {len(alerts)} alert(s).',
        {"id": inc.get("id"), "status": status, "urgency": urgency,
         "service": service, "alert_count": len(alerts)},
        1, services, health, result, has_errors=status != "resolved")


def _summarize_pd_list(args: dict, result: Any) -> dict:
    incidents = result.get("incidents", []) if isinstance(result, dict) else []
    by = {"triggered": 0, "acknowledged": 0, "resolved": 0}
    for inc in incidents:
        if isinstance(inc, dict) and inc.get("status") in by:
            by[inc["status"]] += 1
    health = HEALTHY if by["triggered"] == 0 else (
        CRITICAL if by["triggered"] > 2 else DEGRADED)
    return _compact(
        f"{len(incidents)} incident(s): {by['triggered']} triggered, "
        f"{by['acknowledged']} acknowledged.",
        {"total": len(incidents), **by},
        len(incidents), _find_services(result), health, result,
        has_errors=by["triggered"] > 0)


def _monitor_state(m: dict) -> str:
    """Monitor state across shapes: the real /v1/monitor API uses
    ``overall_state``, the simulated tool ``status``."""
    return str(m.get("overall_state") or m.get("status") or m.get("state") or "")


def _summarize_datadog(args: dict, result: Any) -> dict:
    action = args.get("action", "query")
    # The real client returns the bare /v1/monitor list; simulated wraps it.
    monitors = (result if isinstance(result, list) and action == "monitors"
                else result.get("monitors") if isinstance(result, dict) else None)
    if monitors is not None:
        monitors = monitors or []
        firing = [m for m in monitors if isinstance(m, dict)
                  and _monitor_state(m).lower() in ("alert", "firing",
                                                    "triggered", "warn")]
        health = HEALTHY if not firing else (
            CRITICAL if len(firing) > 2 else DEGRADED)
        return _compact(
            f"{len(firing)} triggered Datadog monitor(s) of {len(monitors)}.",
            {"count": len(firing),
             "monitors": [{"name": m.get("name"), "state": _monitor_state(m)}
                          for m in monitors[:3] if isinstance(m, dict)]},
            len(monitors), _find_services(result), health, result,
            has_errors=bool(firing))
    if isinstance(result, dict) and "series" in result:
        series = result["series"]
        n = len(series) if isinstance(series, (list, dict)) else 1
        return _compact(f"Datadog metrics: {n} series.",
                        {"series": list(series)[:5] if isinstance(series, dict)
                         else n},
                        n, _find_services(result),
                        _health_from_signals(result), result)
    if isinstance(result, dict) and "events" in result:
        events = result["events"] or []
        return _compact(f"Found {len(events)} Datadog event(s).",
                        {"count": len(events)},
                        len(events), _find_services(result),
                        _health_from_signals(result), result)
    return _summarize_default(f"datadog {action}", args, result)


def _alert_name(a: dict) -> Any:
    labels = a.get("labels", {}) if isinstance(a.get("labels"), dict) else {}
    return a.get("name") or labels.get("alertname")


def _alert_severity(a: dict) -> Any:
    labels = a.get("labels", {}) if isinstance(a.get("labels"), dict) else {}
    return labels.get("severity") or a.get("severity")


def _summarize_prometheus(args: dict, result: Any) -> dict:
    action = args.get("action", "query")
    # The real client returns the API envelope {"status", "data": {...}};
    # the simulated tool returns the inner dict directly.
    data = result.get("data", result) if isinstance(result, dict) else {}
    if isinstance(data, dict) and "alerts" in data:
        alerts = data["alerts"] or []
        firing = [a for a in alerts if isinstance(a, dict)
                  and a.get("state", "firing") == "firing"]
        health = HEALTHY if not firing else (
            CRITICAL if len(firing) > 2 else DEGRADED)
        return _compact(
            f"{len(firing)} firing Prometheus alert(s).",
            {"count": len(firing),
             "alerts": [{"name": _alert_name(a), "severity": _alert_severity(a)}
                        for a in firing[:3] if isinstance(a, dict)]},
            len(alerts), _find_services(result), health, result,
            has_errors=bool(firing))
    targets = (data.get("activeTargets") or data.get("targets")
               if isinstance(data, dict) else None)
    if targets is not None:
        unhealthy = [t for t in targets if isinstance(t, dict)
                     and t.get("health") not in ("up", "healthy", None)]
        health = HEALTHY if not unhealthy else (
            CRITICAL if len(unhealthy) > len(targets) / 2 else DEGRADED)
        return _compact(
            f"Prometheus targets: {len(targets) - len(unhealthy)} healthy, "
            f"{len(unhealthy)} unhealthy.",
            {"healthy": len(targets) - len(unhealthy),
             "unhealthy": len(unhealthy)},
            len(targets), _find_services(result), health, result,
            has_errors=bool(unhealthy))
    return _summarize_default(f"prometheus {action}", args, result)


def _summarize_kubernetes(args: dict, result: Any) -> dict:
    action = args.get("action", "status")
    if not isinstance(result, dict) or "error" in result:
        err = result.get("error") if isinstance(result, dict) else str(result)
        return _compact(f"kubernetes_query failed: {str(err)[:120]}", {},
                        0, [], UNKNOWN, result, has_errors=True)
    if "pods" in result:
        pods = result["pods"] or []
        bad = [p for p in pods if isinstance(p, dict) and p.get("status")
               not in ("Running", "Succeeded", "Completed", None)]
        restarts = sum(_as_int(p.get("restarts")) for p in pods
                       if isinstance(p, dict))
        health = HEALTHY if not bad else (
            CRITICAL if len(bad) > 2 else DEGRADED)
        return _compact(
            f"{len(pods)} pod(s); {len(bad)} not Running; "
            f"{restarts} restart(s) total.",
            {"pods": len(pods), "not_running": len(bad), "restarts": restarts,
             "bad": [{"name": p.get("name"), "status": p.get("status")}
                     for p in bad[:3]]},
            len(pods), _find_services(result), health, result,
            has_errors=bool(bad))
    if "nodes" in result:
        nodes = result["nodes"] or []
        not_ready = [n for n in nodes if isinstance(n, dict)
                     and n.get("status") != "Ready"]
        health = HEALTHY if not not_ready else CRITICAL
        return _compact(
            f"{len(nodes)} node(s); {len(not_ready)} not Ready.",
            {"nodes": len(nodes), "not_ready": len(not_ready)},
            len(nodes), _find_services(result), health, result,
            has_errors=bool(not_ready))
    key = next((k for k in result if isinstance(result[k], list)), None)
    items = result.get(key, []) if key else []
    return _compact(f"kubernetes {action}: {len(items)} {key or 'item'}(s).",
                    {key or "items": len(items)},
                    len(items), _find_services(result),
                    _health_from_signals(result), result)


def _summarize_knowledge(args: dict, result: Any) -> dict:
    hits = result.get("results", []) if isinstance(result, dict) else []
    by_type: dict[str, int] = {}
    titles = []
    for h in hits:
        if isinstance(h, dict):
            by_type[h.get("type", "doc")] = by_type.get(h.get("type", "doc"), 0) + 1
            if h.get("type") == "runbook" and len(titles) < 2:
                titles.append(h.get("title"))
    type_bits = ", ".join(f"{n} {t}(s)" for t, n in sorted(by_type.items()))
    return _compact(
        f"Found {len(hits)} doc(s)" + (f": {type_bits}." if type_bits else "."),
        {"runbooks": titles, **by_type},
        len(hits), _find_services(result), UNKNOWN, result, has_errors=False)


def _summarize_default(tool: str, args: dict, result: Any) -> dict:
    items = _count_items(result)
    services = _find_services(result)
    health = _health_from_signals(result)
    if isinstance(result, dict):
        keys = ", ".join(list(result)[:5])
        summary = f"{tool}: {items} item(s). Keys: {keys}"
    elif isinstance(result, list):
        summary = f"{tool}: {items} item(s)."
    else:
        s = str(result)
        summary = f"{tool}: {s[:200]}{'...' if len(s) > 200 else ''}"
    return _compact(summary, {"errors": _text_highlights(result)},
                    items, services, health, result)


_SUMMARIZERS: dict[str, Callable[[dict, Any], dict]] = {
    "aws_query": _summarize_aws_query,
    "cloudwatch_alarms": _summarize_cloudwatch_alarms,
    "cloudwatch_logs": _summarize_cloudwatch_logs,
    "pagerduty_get_incident": _summarize_pd_incident,
    "pagerduty_list_incidents": _summarize_pd_list,
    "datadog": _summarize_datadog,
    "prometheus": _summarize_prometheus,
    "kubernetes_query": _summarize_kubernetes,
    "search_knowledge": _summarize_knowledge,
}


def _as_int(value: Any) -> int:
    try:
        return int(value)
    except (TypeError, ValueError):
        return 0


def summarize_tool_result(tool: str, args: dict[str, Any], result: Any) -> dict[str, Any]:
    """Build the compact representation stored in the scratchpad tier
    (per-tool registry dispatch, reference tool-summarizer.ts:758-763)."""
    fn = _SUMMARIZERS.get(tool)
    if fn is not None:
        try:
            return fn(args or {}, result)
        except Exception:  # noqa: BLE001 — ADVICE r2: a malformed payload
            # (e.g. 'incident' as a string, restarts as None) must degrade
            # to the generic summary, never crash the agent loop — the
            # summarizer runs unguarded in agent.py's result handling.
            return _summarize_default(tool, args or {}, result)
    return _summarize_default(tool, args or {}, result)
