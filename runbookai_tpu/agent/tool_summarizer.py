"""Per-tool compact summaries keeping the agent's context small.

Parity target: reference ``src/agent/tool-summarizer.ts`` (``CompactToolResult``
:13-28 — summary, highlights, itemCount, services, healthStatus; per-tool
summarizer classes :742). Summaries are pure functions of the result payload —
no LLM call — and the ``result_id`` enables drill-down via ``get_full_result``.
"""

from __future__ import annotations

import json
import re
from typing import Any, Optional

_ERROR_WORDS = re.compile(
    r"\b(error|exception|fail(?:ed|ure)?|timeout|throttl|oom|denied|refused|5\d\d|crit)\w*",
    re.IGNORECASE,
)


def _walk_strings(obj: Any, limit: int = 400):
    stack = [obj]
    seen = 0
    while stack and seen < limit:
        cur = stack.pop()
        if isinstance(cur, str):
            seen += 1
            yield cur
        elif isinstance(cur, dict):
            stack.extend(cur.values())
        elif isinstance(cur, (list, tuple)):
            stack.extend(cur)


def _count_items(result: Any) -> int:
    if isinstance(result, list):
        return len(result)
    if isinstance(result, dict):
        for key in ("items", "results", "alarms", "events", "logs", "instances",
                    "pods", "incidents", "series", "resources", "documents"):
            v = result.get(key)
            if isinstance(v, list):
                return len(v)
        return len(result)
    return 1


def _find_services(result: Any) -> list[str]:
    found: set[str] = set()
    for key in ("service", "serviceName", "service_name", "name", "functionName",
                "cluster", "namespace", "deployment"):
        stack = [result]
        while stack:
            cur = stack.pop()
            if isinstance(cur, dict):
                v = cur.get(key)
                if isinstance(v, str) and 0 < len(v) < 80:
                    found.add(v)
                stack.extend(cur.values())
            elif isinstance(cur, list):
                stack.extend(cur[:50])
    return sorted(found)[:10]


def _health_status(result: Any) -> str:
    text_signals = 0
    for s in _walk_strings(result):
        if _ERROR_WORDS.search(s):
            text_signals += 1
        if text_signals >= 3:
            return "unhealthy"
    return "degraded" if text_signals else "healthy"


def _highlights(result: Any, max_items: int = 5) -> list[str]:
    out = []
    for s in _walk_strings(result):
        if _ERROR_WORDS.search(s) and len(s) > 10:
            out.append(s[:200])
            if len(out) >= max_items:
                break
    return out


def summarize_tool_result(tool: str, args: dict[str, Any], result: Any) -> dict[str, Any]:
    """Build the compact representation stored in the scratchpad tier."""
    items = _count_items(result)
    services = _find_services(result)
    health = _health_status(result)
    highlights = _highlights(result)
    size = len(json.dumps(result, default=str)) if result is not None else 0

    bits = [f"{tool}: {items} item(s)"]
    if services:
        bits.append(f"services: {', '.join(services[:4])}")
    bits.append(f"signal: {health}")
    summary = "; ".join(bits)

    return {
        "summary": summary,
        "highlights": highlights,
        "item_count": items,
        "services": services,
        "health_status": health,
        "size_bytes": size,
    }
