"""Agent memories: running investigation state + chat conversation memory.

Parity targets: reference ``src/agent/investigation-memory.ts`` (:147 —
services discovered, symptoms, findings extracted from model output; persisted;
feeds prompts and knowledge re-query triggers ``agent.ts:771-786``) and
``src/agent/conversation-memory.ts`` (:77 — turn history with summarization
after N messages, mentioned-services extraction, serialize/deserialize).
"""

from __future__ import annotations

import json
import re
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

_SERVICE_RE = re.compile(
    r"\b([a-z][a-z0-9]*(?:-[a-z0-9]+)+)\b"  # kebab-case names like payment-api
)
_SYMPTOM_WORDS = (
    "latency", "timeout", "error", "5xx", "4xx", "oom", "crash", "restart",
    "throttl", "saturat", "cpu", "memory", "disk", "connection", "queue",
    "backlog", "degraded", "unavailable", "slow",
)
_FINDING_RE = re.compile(
    r"(?:found|observed|confirmed|detected|indicates?|shows?) (.{10,160})",
    re.IGNORECASE,
)


def extract_services(text: str) -> list[str]:
    return sorted({m.group(1) for m in _SERVICE_RE.finditer(text or "")})[:20]


def extract_symptoms(text: str) -> list[str]:
    low = (text or "").lower()
    return [w for w in _SYMPTOM_WORDS if w in low]


class InvestigationMemory:
    """Distilled running state of one investigation."""

    def __init__(self, session_id: str, root: str | Path = ".runbook/memory",
                 persist: bool = True):
        self.session_id = session_id
        self.path = Path(root) / f"{session_id}.json"
        self.persist = persist
        self.services: list[str] = []
        self.symptoms: list[str] = []
        self.findings: list[str] = []
        self.incident_id: Optional[str] = None
        self.started_at = time.time()

    def observe(self, text: str) -> tuple[list[str], list[str]]:
        """Ingest model/tool text; returns (new_services, new_symptoms) — the
        knowledge re-query trigger (reference agent.ts:771-786)."""
        new_services = [s for s in extract_services(text) if s not in self.services]
        new_symptoms = [s for s in extract_symptoms(text) if s not in self.symptoms]
        self.services.extend(new_services)
        self.symptoms.extend(new_symptoms)
        for m in _FINDING_RE.finditer(text or ""):
            finding = m.group(1).strip()
            if finding not in self.findings and len(self.findings) < 30:
                self.findings.append(finding)
        return new_services, new_symptoms

    def to_prompt_block(self) -> str:
        if not (self.services or self.symptoms or self.findings):
            return ""
        parts = ["# Investigation memory"]
        if self.services:
            parts.append("Services in play: " + ", ".join(self.services[:12]))
        if self.symptoms:
            parts.append("Symptoms observed: " + ", ".join(self.symptoms[:12]))
        if self.findings:
            parts.append("Key findings:")
            parts.extend(f"- {f}" for f in self.findings[:8])
        return "\n".join(parts)

    def save(self) -> None:
        if not self.persist:
            return
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.path.write_text(json.dumps({
            "session_id": self.session_id, "services": self.services,
            "symptoms": self.symptoms, "findings": self.findings,
            "incident_id": self.incident_id, "started_at": self.started_at,
        }, indent=2))

    @classmethod
    def load(cls, session_id: str, root: str | Path = ".runbook/memory") -> "InvestigationMemory":
        mem = cls(session_id, root=root, persist=True)
        if mem.path.is_file():
            data = json.loads(mem.path.read_text())
            mem.services = data.get("services", [])
            mem.symptoms = data.get("symptoms", [])
            mem.findings = data.get("findings", [])
            mem.incident_id = data.get("incident_id")
            mem.started_at = data.get("started_at", mem.started_at)
        return mem


@dataclass
class Turn:
    role: str
    content: str
    ts: float = field(default_factory=time.time)


class ConversationMemory:
    """Chat-mode memory: rolling turns + summary after N messages."""

    def __init__(self, summarize_after_messages: int = 16, keep_recent: int = 6):
        self.summarize_after = summarize_after_messages
        self.keep_recent = keep_recent
        self.turns: list[Turn] = []
        self.summary: str = ""
        self.mentioned_services: list[str] = []
        self.mentioned_incidents: list[str] = []

    def add(self, role: str, content: str) -> None:
        self.turns.append(Turn(role=role, content=content))
        for s in extract_services(content):
            if s not in self.mentioned_services:
                self.mentioned_services.append(s)
        for m in re.finditer(r"\b((?:PD|INC|OG)-\d+)\b", content):
            if m.group(1) not in self.mentioned_incidents:
                self.mentioned_incidents.append(m.group(1))

    @property
    def needs_summarization(self) -> bool:
        return len(self.turns) >= self.summarize_after

    async def summarize(self, llm) -> None:
        """Fold older turns into the summary via one completion call."""
        if not self.needs_summarization:
            return
        old = self.turns[: -self.keep_recent]
        transcript = "\n".join(f"{t.role}: {t.content[:500]}" for t in old)
        prompt = (
            "Summarize this operations conversation in under 150 words, "
            "keeping service names, incident ids, decisions, and open actions:\n\n"
            + (f"Previous summary: {self.summary}\n\n" if self.summary else "")
            + transcript
        )
        self.summary = (await llm.complete(prompt)).strip()
        self.turns = self.turns[-self.keep_recent :]

    def context_block(self) -> str:
        parts = []
        if self.summary:
            parts.append(f"# Conversation summary\n{self.summary}")
        if self.turns:
            recent = "\n".join(f"{t.role}: {t.content[:800]}" for t in self.turns)
            parts.append(f"# Recent turns\n{recent}")
        if self.mentioned_services:
            parts.append("Known services: " + ", ".join(self.mentioned_services[:10]))
        return "\n\n".join(parts)

    def serialize(self) -> str:
        return json.dumps({
            "summary": self.summary,
            "turns": [{"role": t.role, "content": t.content, "ts": t.ts} for t in self.turns],
            "mentioned_services": self.mentioned_services,
            "mentioned_incidents": self.mentioned_incidents,
        })

    @classmethod
    def deserialize(cls, payload: str, **kw) -> "ConversationMemory":
        data = json.loads(payload)
        mem = cls(**kw)
        mem.summary = data.get("summary", "")
        mem.turns = [Turn(**t) for t in data.get("turns", [])]
        mem.mentioned_services = data.get("mentioned_services", [])
        mem.mentioned_incidents = data.get("mentioned_incidents", [])
        return mem


def create_memory(**kw) -> ConversationMemory:
    return ConversationMemory(**kw)
