"""Regex-based log analysis: error patterns, service mentions, hypotheses.

Parity target: reference ``src/agent/log-analyzer.ts`` — ``ERROR_PATTERNS``
(:14, 11 categories), ``parseLogLine`` (:230), ``analyzePatterns`` (:274),
``extractServiceMentions`` (:327), ``generateHypothesesFromPatterns`` (:415),
``analyzeLogs`` (:473), time/level filters (:584-622). Optionally merged with
LLM analysis by the orchestrator.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional

# 11 error categories (reference log-analyzer.ts:14).
ERROR_PATTERNS: dict[str, re.Pattern] = {
    "connection_failure": re.compile(
        r"connection (?:refused|reset|timed? ?out|is not available)|"
        r"remaining connection slots|pool (?:exhaust|timeout|size)|ECONNREFUSED",
        re.IGNORECASE),
    "timeout": re.compile(r"\btim(?:ed?|e) ?out\b|deadline exceeded|ETIMEDOUT", re.IGNORECASE),
    "memory": re.compile(r"out of memory|OOM[- ]?Kill|heap (?:space|exhaust)|memory limit", re.IGNORECASE),
    "cpu_throttle": re.compile(r"cpu throttl|high load|saturat", re.IGNORECASE),
    "disk": re.compile(r"no space left|disk full|I/O error|read-only file system", re.IGNORECASE),
    "auth": re.compile(r"access denied|unauthoriz|forbidden|401|403|invalid credentials|expired token", re.IGNORECASE),
    "rate_limit": re.compile(r"rate limit|too many requests|429|throttlingexception", re.IGNORECASE),
    "dns": re.compile(r"dns|name resolution|getaddrinfo|NXDOMAIN", re.IGNORECASE),
    "database": re.compile(r"SQL(?:state)?|deadlock|postgres|mysql|PSQLException|ORA-\d+", re.IGNORECASE),
    "http_5xx": re.compile(r"\b5\d\d\b|internal server error|bad gateway|service unavailable", re.IGNORECASE),
    "crash": re.compile(r"panic|segfault|core dump|fatal|CrashLoopBackOff|exit code [1-9]", re.IGNORECASE),
}

_LEVEL_RE = re.compile(r"\b(TRACE|DEBUG|INFO|WARN(?:ING)?|ERROR|FATAL|CRIT(?:ICAL)?)\b", re.IGNORECASE)
_TS_RE = re.compile(r"\d{4}-\d{2}-\d{2}[T ]\d{2}:\d{2}:\d{2}")
_SERVICE_RE = re.compile(r"\b([a-z][a-z0-9]*(?:-[a-z0-9]+)+)\b")

_CATEGORY_HYPOTHESES = {
    "connection_failure": ("Connection pool or downstream connectivity exhaustion", 0.85),
    "timeout": ("A downstream dependency is timing out under load", 0.7),
    "memory": ("Memory exhaustion (leak or undersized limits)", 0.8),
    "cpu_throttle": ("CPU saturation or throttling degrading throughput", 0.6),
    "disk": ("Disk exhaustion or I/O failure", 0.7),
    "auth": ("Credential/permission misconfiguration after a change", 0.6),
    "rate_limit": ("An upstream dependency is rate-limiting requests", 0.6),
    "dns": ("DNS resolution failures breaking service discovery", 0.6),
    "database": ("Database errors (locks, capacity, or bad queries)", 0.8),
    "http_5xx": ("A backend is returning 5xx under fault or overload", 0.6),
    "crash": ("Process crash-loop from a bad build or config", 0.8),
}


@dataclass
class ParsedLogLine:
    raw: str
    timestamp: Optional[str] = None
    level: Optional[str] = None
    message: str = ""
    categories: list[str] = field(default_factory=list)


@dataclass
class LogAnalysisResult:
    lines_analyzed: int = 0
    error_lines: int = 0
    pattern_counts: dict[str, int] = field(default_factory=dict)
    services: list[str] = field(default_factory=list)
    notable_lines: list[str] = field(default_factory=list)
    hypotheses: list[dict[str, Any]] = field(default_factory=list)


def parse_log_line(raw: str) -> ParsedLogLine:
    level_match = _LEVEL_RE.search(raw)
    ts_match = _TS_RE.search(raw)
    categories = [name for name, pattern in ERROR_PATTERNS.items() if pattern.search(raw)]
    return ParsedLogLine(
        raw=raw,
        timestamp=ts_match.group(0) if ts_match else None,
        level=level_match.group(1).upper() if level_match else None,
        message=raw.strip(),
        categories=categories,
    )


def extract_service_mentions(lines: list[str]) -> list[str]:
    counts: dict[str, int] = {}
    for line in lines:
        for m in _SERVICE_RE.finditer(line):
            name = m.group(1)
            counts[name] = counts.get(name, 0) + 1
    return [s for s, _ in sorted(counts.items(), key=lambda kv: kv[1], reverse=True)][:10]


def filter_lines(
    parsed: list[ParsedLogLine],
    min_level: Optional[str] = None,
    since: Optional[str] = None,
) -> list[ParsedLogLine]:
    """Level/time filters (log-analyzer.ts:584-622)."""
    order = ["TRACE", "DEBUG", "INFO", "WARN", "WARNING", "ERROR", "FATAL", "CRIT", "CRITICAL"]
    out = parsed
    if min_level:
        threshold = order.index(min_level.upper())
        out = [p for p in out if p.level and order.index(p.level) >= threshold]
    if since:
        out = [p for p in out if p.timestamp is None or p.timestamp >= since]
    return out


def analyze_logs(
    lines: list[str],
    min_level: Optional[str] = None,
    since: Optional[str] = None,
    max_notable: int = 8,
) -> LogAnalysisResult:
    parsed = [parse_log_line(l) for l in lines if l.strip()]
    parsed = filter_lines(parsed, min_level=min_level, since=since)
    result = LogAnalysisResult(lines_analyzed=len(parsed))
    for p in parsed:
        if p.categories or (p.level in ("ERROR", "FATAL", "CRIT", "CRITICAL")):
            result.error_lines += 1
            if len(result.notable_lines) < max_notable:
                result.notable_lines.append(p.raw[:240])
        for cat in p.categories:
            result.pattern_counts[cat] = result.pattern_counts.get(cat, 0) + 1
    result.services = extract_service_mentions([p.raw for p in parsed])
    for cat, count in sorted(result.pattern_counts.items(), key=lambda kv: kv[1], reverse=True):
        statement, priority = _CATEGORY_HYPOTHESES[cat]
        result.hypotheses.append({
            "statement": statement,
            "priority": min(1.0, priority + 0.05 * min(count, 3)),
            "category": cat,
            "occurrences": count,
        })
    return result
