"""The free-form tool-calling agent loop.

Parity target: reference ``src/agent/agent.ts`` ``Agent.run()`` (:279-855) —
an async generator of :class:`AgentEvent`:

retrieve knowledge → (knowledge-only fast path for procedural queries
:356-390) → iterate up to ``max_iterations``: build prompt → ``llm.chat`` with
tools → validate calls (repeat-signature guard :529-548, unknown tools,
graceful limits) → execute (LRU cache :589-603, parallel :626-687 or
sequential) → summarize + append to scratchpad (tiered) → update memories and
re-query knowledge on new services/symptoms (:771-786) → final answer
(:819-821) + hypothesis markdown + citations (:824-845).

The LLM here is the in-tree TPU engine; tool I/O overlaps decode via asyncio.
"""

from __future__ import annotations

import uuid
from typing import Any, AsyncIterator, Optional

from runbookai_tpu.agent.citation import CitationContext
from runbookai_tpu.agent.context_compactor import ContextCompactor
from runbookai_tpu.agent.hypothesis import HypothesisEngine
from runbookai_tpu.agent.memory import InvestigationMemory
from runbookai_tpu.agent.parallel_executor import ParallelToolExecutor
from runbookai_tpu.agent.prompts import (
    build_final_answer_prompt,
    build_iteration_prompt,
    build_knowledge_only_prompt,
    build_system_prompt,
    is_procedural_query,
    render_knowledge,
)
from runbookai_tpu.agent.scratchpad import Scratchpad
from runbookai_tpu.agent.tool_cache import LRUToolCache
from runbookai_tpu.agent.tool_summarizer import summarize_tool_result
from runbookai_tpu.agent.types import (
    AgentEvent,
    LLMResponse,
    RetrievedKnowledge,
    RiskLevel,
    Tool,
    ToolCall,
    ToolResult,
)
from runbookai_tpu.utils.metrics import get_registry
from runbookai_tpu.utils.tokens import estimate_tokens

# LLM/token accounting for the agent loop, in the same registry the serving
# stack scrapes — an operator watching /metrics sees tool latency AND what
# the loop spent on inference for the same investigation.
_LLM_CALLS = get_registry().counter(
    "runbook_agent_llm_calls_total", "LLM chat calls made by the agent loop")
_LLM_PROMPT_TOKENS = get_registry().counter(
    "runbook_agent_llm_prompt_tokens_total",
    "Prompt tokens consumed by agent-loop LLM calls")
_LLM_COMPLETION_TOKENS = get_registry().counter(
    "runbook_agent_llm_completion_tokens_total",
    "Completion tokens generated for agent-loop LLM calls")
_TOOL_CACHE_HITS = get_registry().counter(
    "runbook_agent_tool_cache_hits_total",
    "Tool calls served from the LRU result cache", labels=("tool",))


class NullKnowledge:
    """Knowledge adapter used when no retriever is configured."""

    async def retrieve(self, query: str, services: Optional[list[str]] = None) -> RetrievedKnowledge:
        return RetrievedKnowledge()


class Agent:
    def __init__(
        self,
        llm,
        tools: list[Tool],
        knowledge: Optional[Any] = None,
        max_iterations: int = 10,
        context_threshold_tokens: int = 100_000,
        explain_mode: bool = False,
        parallel_tools: bool = True,
        scratchpad_root: str = ".runbook/scratchpad",
        persist: bool = True,
        compactor_preset: str = "balanced",
        cache_ttl_seconds: float = 300.0,
        cache_size: int = 100,
        tokenizer: Optional[Any] = None,
        context_managers: Optional[list] = None,
        stream_tokens: bool = True,
    ):
        self.llm = llm
        self.tools = {t.name: t for t in tools}
        self.knowledge = knowledge or NullKnowledge()
        self.max_iterations = max_iterations
        self.context_threshold = context_threshold_tokens
        self.explain_mode = explain_mode
        self.scratchpad_root = scratchpad_root
        self.persist = persist
        self.compactor = ContextCompactor(compactor_preset)
        self.cache = LRUToolCache(max_size=cache_size, ttl_seconds=cache_ttl_seconds)
        self.executor = ParallelToolExecutor() if parallel_tools else None
        self.tokenizer = tokenizer
        # Knowledge/Service/Infra context managers (reference agent.ts:293-340):
        # primed before the loop, re-observed as services/symptoms surface, and
        # injected into every system prompt via their system_prompt_block().
        self.context_managers = list(context_managers or [])
        # Token streaming (reference streams AgentEvents into a live Ink
        # tree, src/cli.tsx:116): every LLM call in the loop emits
        # ``token`` delta events as the model samples, so surfaces paint
        # text tens of seconds before the full decode lands. Deltas are
        # the RAW sampled stream (tool-call markup included — it cannot
        # be parsed out until the document completes); the parsed
        # response still arrives in the usual answer/tool_call events.
        self.stream_tokens = stream_tokens and hasattr(llm, "chat_stream")

    async def _chat_events(self, system: str, prompt: str, tools=None):
        """LLM chat as an event stream: ``token`` AgentEvents per sampled
        delta, then one ``_response`` AgentEvent carrying the parsed
        LLMResponse (consumed by :meth:`run`, never surfaced)."""
        if not self.stream_tokens:
            resp = await self.llm.chat(system, prompt, tools)
            self._count_llm_usage(resp)
            yield AgentEvent("_response", {"response": resp})
            return
        resp = None
        parts: list[str] = []
        async for ev in self.llm.chat_stream(system, prompt, tools):
            if ev.get("type") == "text":
                delta = ev.get("delta", "")
                parts.append(delta)
                yield AgentEvent("token", {"delta": delta})
            elif ev.get("type") == "done":
                resp = ev.get("response")
        if resp is None:
            # Stream ended without a 'done' event. The user has already
            # seen the streamed deltas — re-sampling via chat() could
            # paint a DIFFERENT answer over them (and doubles inference
            # cost), so parse the accumulated raw text into the response
            # instead (ADVICE r4).
            from runbookai_tpu.model.chat_template import parse_assistant_output

            content, tool_calls, thinking = parse_assistant_output(
                "".join(parts))
            resp = LLMResponse(content=content, tool_calls=tool_calls,
                               thinking=thinking)
        self._count_llm_usage(resp)
        yield AgentEvent("_response", {"response": resp})

    @staticmethod
    def _count_llm_usage(resp) -> None:
        _LLM_CALLS.inc()
        usage = getattr(resp, "usage", None) or {}
        if usage.get("prompt_tokens"):
            _LLM_PROMPT_TOKENS.inc(usage["prompt_tokens"])
        if usage.get("completion_tokens"):
            _LLM_COMPLETION_TOKENS.inc(usage["completion_tokens"])

    # ------------------------------------------------------------------ run

    async def run(
        self,
        query: str,
        session_id: Optional[str] = None,
        incident_id: Optional[str] = None,
        extra_context: Optional[list[str]] = None,
    ) -> AsyncIterator[AgentEvent]:
        session_id = session_id or f"ask-{uuid.uuid4().hex[:10]}"
        pad = Scratchpad(session_id=session_id, root=self.scratchpad_root,
                         persist=self.persist)
        memory = InvestigationMemory(session_id, persist=False)
        memory.incident_id = incident_id
        hypotheses = HypothesisEngine() if incident_id else None
        citations = CitationContext()
        # Expose the live scratchpad to the drill-down context tools.
        from runbookai_tpu.tools import context as context_tools

        context_tools.set_active_scratchpad(pad)

        yield AgentEvent("start", {"session_id": session_id, "query": query})

        knowledge = await self.knowledge.retrieve(query)
        citations.track(knowledge)
        knowledge_block = render_knowledge(knowledge)
        if not knowledge.empty:
            yield AgentEvent("knowledge_retrieved", {
                "counts": {
                    "runbooks": len(knowledge.runbooks),
                    "postmortems": len(knowledge.postmortems),
                    "known_issues": len(knowledge.known_issues),
                    "architecture": len(knowledge.architecture),
                },
            })

        # Context managers: seed the knowledge index from the retrieval we
        # just did (no second search) / pre-discover infra before the first
        # LLM call (reference agent.ts:293-340).
        for cm in self.context_managers:
            try:
                if hasattr(cm, "absorb"):
                    cm.absorb(knowledge, query=query)
                elif hasattr(cm, "prime"):
                    await cm.prime(query)
                if hasattr(cm, "discover"):
                    await cm.discover()
            except Exception as e:  # noqa: BLE001 — context is best-effort
                yield AgentEvent("warning", {
                    "text": f"context manager {type(cm).__name__} failed: {e}"})

        def system_prompt() -> str:
            blocks = [b for b in (cm.system_prompt_block()
                                  for cm in self.context_managers) if b]
            return build_system_prompt([*(extra_context or []), *blocks])

        # Knowledge-only fast path (reference agent.ts:356-390). This is a
        # PROBE — the response is discarded when the model answers
        # KNOWLEDGE_INSUFFICIENT — so it must buffer, not stream: live
        # deltas would paint the sentinel and an abandoned draft answer
        # ahead of the real one.
        if knowledge_block and is_procedural_query(query):
            resp = await self.llm.chat(
                system_prompt(),
                build_knowledge_only_prompt(query, knowledge_block),
            )
            if "KNOWLEDGE_INSUFFICIENT" not in resp.content:
                answer = resp.content + citations.sources_section(resp.content)
                pad.append("answer", {"text": answer, "fast_path": True})
                yield AgentEvent("answer", {"text": answer, "fast_path": True})
                yield AgentEvent("done", {"iterations": 0})
                return

        memory.observe(query)
        tool_schemas = [t.schema() for t in self.tools.values()]
        warnings: list[str] = []
        final_text: Optional[str] = None

        for iteration in range(self.max_iterations):
            # Context budget check → compaction (reference agent.ts:414-441).
            context_text = pad.build_tiered_context()
            if estimate_tokens(context_text, self.tokenizer) > self.context_threshold:
                plan = self.compactor.plan(
                    pad, query, memory=memory,
                    hypotheses=([h.statement for h in hypotheses.open_hypotheses()]
                                if hypotheses else None),
                )
                pad.apply_compaction_plan(plan)
                context_text = pad.build_tiered_context()
                yield AgentEvent("phase", {"name": "compaction",
                                           "results": len(plan)})

            prompt = build_iteration_prompt(
                query, context_text, knowledge_block, iteration,
                self.max_iterations, warnings=warnings,
                memory_block=memory.to_prompt_block(),
            )
            warnings = []
            yield AgentEvent("iteration", {"n": iteration + 1})
            if self.explain_mode:
                yield AgentEvent("phase", {"name": "thinking",
                                           "detail": f"iteration {iteration + 1}"})

            resp = None
            async for ev in self._chat_events(system_prompt(), prompt,
                                              tool_schemas):
                if ev.kind == "_response":
                    resp = ev.data["response"]
                else:
                    yield ev
            if resp.thinking:
                pad.append_thinking(resp.thinking)
                memory.observe(resp.thinking)
                yield AgentEvent("thinking", {"text": resp.thinking})

            if not resp.tool_calls:
                final_text = resp.content
                break

            # ------------------------------------------------- validate calls
            valid_calls: list[ToolCall] = []
            for call in resp.tool_calls:
                if call.name not in self.tools:
                    warnings.append(f"unknown tool {call.name!r}; available: "
                                    f"{', '.join(sorted(self.tools))}")
                    yield AgentEvent("warning", {"text": warnings[-1]})
                    continue
                repeats = pad.record_call_signature(call)
                if repeats > 2:
                    warnings.append(
                        f"tool call {call.name} with identical args repeated "
                        f"{repeats}x — refine the arguments or conclude"
                    )
                    yield AgentEvent("warning", {"text": warnings[-1]})
                    continue
                _, limit_warning = pad.can_call_tool(call.name)
                if limit_warning:
                    warnings.append(limit_warning)
                    yield AgentEvent("warning", {"text": limit_warning})
                valid_calls.append(call)

            if not valid_calls:
                continue

            for call in valid_calls:
                yield AgentEvent("tool_call", {"id": call.id, "name": call.name,
                                               "args": call.args})

            results = await self._execute_calls(valid_calls)

            for result in results:
                compact = None if result.error else summarize_tool_result(
                    result.call.name, result.call.args, result.result
                )
                entry = pad.append_tool_result(
                    result.call, result=result.result, error=result.error,
                    duration_ms=result.duration_ms, compact=compact,
                )
                yield AgentEvent("tool_result", {
                    "id": result.call.id, "name": result.call.name,
                    "result_id": entry.result_id, "error": result.error,
                    "cached": result.cached, "duration_ms": result.duration_ms,
                    "summary": (compact or {}).get("summary"),
                })
                # Memory update + knowledge re-query triggers.
                new_services, new_symptoms = memory.observe(
                    str(result.result)[:4000] if result.result is not None else ""
                )
                if new_services or new_symptoms:
                    extra = await self.knowledge.retrieve(
                        " ".join([query, *new_services, *new_symptoms]),
                        services=new_services or None,
                    )
                    for cm in self.context_managers:
                        try:
                            if new_services and hasattr(cm, "observe_services"):
                                cm.observe_services(new_services)
                            if hasattr(cm, "absorb"):
                                # share the one retrieval above — managers
                                # never re-query on their own here
                                cm.absorb(extra, query=" ".join(
                                    new_services + new_symptoms))
                        except Exception:  # noqa: BLE001 — best-effort
                            pass
                    if not extra.empty:
                        citations.track(extra)
                        knowledge_block = render_knowledge(extra) or knowledge_block
                        yield AgentEvent("knowledge_retrieved",
                                         {"requery": True,
                                          "trigger": new_services + new_symptoms})

        if final_text is None:
            # Iteration budget exhausted: one synthesis call without tools.
            resp = None
            async for ev in self._chat_events(
                    system_prompt(),
                    build_final_answer_prompt(query, pad.build_tiered_context(),
                                              knowledge_block,
                                              memory.to_prompt_block())):
                if ev.kind == "_response":
                    resp = ev.data["response"]
                else:
                    yield ev
            final_text = resp.content

        if hypotheses and hypotheses.nodes:
            final_text += "\n\n" + hypotheses.to_markdown()
        if memory.findings or memory.services:
            summary_bits = []
            if memory.services:
                summary_bits.append("Services: " + ", ".join(memory.services[:8]))
            if memory.findings:
                summary_bits.append(f"{len(memory.findings)} recorded findings")
            final_text += "\n\n_" + "; ".join(summary_bits) + "_"
        final_text += citations.sources_section(final_text)

        pad.append("answer", {"text": final_text})
        memory.save()
        yield AgentEvent("answer", {"text": final_text})
        yield AgentEvent("done", {
            "iterations": iteration + 1 if self.max_iterations else 0,
            "tool_calls": len(pad.list_result_ids()),
            "cache": vars(self.cache.stats),
        })

    # ------------------------------------------------------------- execution

    async def _execute_calls(self, calls: list[ToolCall]) -> list[ToolResult]:
        results: list[Optional[ToolResult]] = [None] * len(calls)
        to_run: list[tuple[int, ToolCall]] = []
        for i, call in enumerate(calls):
            tool = self.tools[call.name]
            if tool.risk == RiskLevel.READ:
                cached = self.cache.get(call.name, call.args)
                if cached is not None:
                    # runbook: noqa[RBK010] — tool label: call.name resolved
                    # through self.tools above, so values are the registered
                    # toolset (fixed at Agent construction).
                    _TOOL_CACHE_HITS.labels(tool=call.name).inc()
                    results[i] = ToolResult(call=call, result=cached, cached=True)
                    continue
            to_run.append((i, call))

        async def execute(call: ToolCall):
            return await self.tools[call.name].execute(call.args)

        if to_run:
            pending_calls = [c for _, c in to_run]
            if self.executor and len(pending_calls) > 1:
                executed = await self.executor.execute_all(
                    pending_calls, execute, self.tools
                )
            else:
                solo = ParallelToolExecutor(max_concurrency=1)
                executed = [await solo._execute_one(c, execute) for c in pending_calls]
            for (i, call), res in zip(to_run, executed):
                results[i] = res
                tool = self.tools[call.name]
                if res.ok and tool.risk == RiskLevel.READ:
                    self.cache.put(call.name, call.args, res.result)
        return [r for r in results if r is not None]
