"""Citation tracking: knowledge sources referenced in answers.

Parity target: reference ``src/agent/citation-context.ts`` (:45) — tracks
retrieved docs and appends a Sources section to the final answer
(``agent.ts:834-845``).
"""

from __future__ import annotations

import re

from runbookai_tpu.agent.types import KnowledgeResult, RetrievedKnowledge


class CitationContext:
    def __init__(self) -> None:
        self.docs: dict[str, KnowledgeResult] = {}

    def track(self, knowledge: RetrievedKnowledge) -> None:
        for item in knowledge.all():
            self.docs.setdefault(item.doc_id, item)

    def cited_ids(self, answer: str) -> list[str]:
        """Doc ids the answer actually references as [doc-id]."""
        referenced = set(re.findall(r"\[([\w./-]+)\]", answer))
        return [doc_id for doc_id in self.docs if doc_id in referenced]

    def sources_section(self, answer: str) -> str:
        """Sources block: cited docs first, then remaining runbooks consulted."""
        if not self.docs:
            return ""
        cited = self.cited_ids(answer)
        lines = ["", "---", "**Sources**"]
        listed: set[str] = set()
        for doc_id in cited:
            item = self.docs[doc_id]
            lines.append(f"- [{doc_id}] {item.title} ({item.knowledge_type})")
            listed.add(doc_id)
        others = [d for d in self.docs.values() if d.doc_id not in listed]
        if others:
            lines.append("**Also consulted**")
            for item in others[:5]:
                lines.append(f"- [{item.doc_id}] {item.title} ({item.knowledge_type})")
        return "\n".join(lines)
