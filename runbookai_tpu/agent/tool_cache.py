"""TTL'd LRU cache of tool results keyed by (tool, args).

Parity target: reference ``src/agent/tool-cache.ts`` (:74 class, :291 factory;
stats hits/misses/evictions). Mutating tools must never be cached — the
registry marks risk levels and the agent only consults the cache for
read-risk tools.
"""

from __future__ import annotations

import json
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Optional


@dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    evictions: int = 0
    expirations: int = 0


class LRUToolCache:
    def __init__(self, max_size: int = 100, ttl_seconds: float = 300.0):
        self.max_size = max_size
        self.ttl = ttl_seconds
        self._store: OrderedDict[str, tuple[float, Any]] = OrderedDict()
        self.stats = CacheStats()

    @staticmethod
    def key(tool: str, args: dict[str, Any]) -> str:
        return f"{tool}:{json.dumps(args, sort_keys=True, default=str)}"

    def get(self, tool: str, args: dict[str, Any]) -> Optional[Any]:
        k = self.key(tool, args)
        item = self._store.get(k)
        if item is None:
            self.stats.misses += 1
            return None
        ts, value = item
        if time.monotonic() - ts > self.ttl:
            del self._store[k]
            self.stats.expirations += 1
            self.stats.misses += 1
            return None
        self._store.move_to_end(k)
        self.stats.hits += 1
        return value

    def put(self, tool: str, args: dict[str, Any], value: Any) -> None:
        k = self.key(tool, args)
        self._store[k] = (time.monotonic(), value)
        self._store.move_to_end(k)
        while len(self._store) > self.max_size:
            self._store.popitem(last=False)
            self.stats.evictions += 1

    def clear(self) -> None:
        self._store.clear()


def create_tool_cache(max_size: int = 100, ttl_seconds: float = 300.0) -> LRUToolCache:
    return LRUToolCache(max_size, ttl_seconds)
