"""Core agent contracts: tools, tool calls, events, retrieved knowledge.

Parity target: reference ``src/agent/types.ts`` (AgentEvent union :6-140,
Tool/ToolCall :174-201, scratchpad entry types :203-263, RetrievedKnowledge
:281). Re-expressed as Python dataclasses; tool ``execute`` is async because the
TPU build overlaps tool I/O with device decode steps (asyncio host program).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Awaitable, Callable, Optional


class RiskLevel(str, Enum):
    """Operation risk classes (reference ``src/agent/safety.ts:38-82``)."""

    READ = "read"
    LOW = "low"
    HIGH = "high"
    CRITICAL = "critical"


@dataclass
class Tool:
    """A callable tool the agent may invoke.

    Mirrors the reference tool interface ``{name, description, parameters,
    execute(args)}`` (``src/agent/types.ts:174-190``) plus the category and
    risk metadata the registry/safety layers need.
    """

    name: str
    description: str
    parameters: dict[str, Any]  # JSON schema for the arguments object
    execute: Callable[[dict[str, Any]], Awaitable[Any]]
    category: str = "general"
    risk: RiskLevel = RiskLevel.READ
    # Graceful per-session call limit (warn, never block — reference
    # scratchpad.ts:173 design principle).
    call_limit: Optional[int] = None

    def schema(self) -> dict[str, Any]:
        """The provider-facing tool schema (name/description/parameters)."""
        return {
            "name": self.name,
            "description": self.description,
            "parameters": self.parameters,
        }


@dataclass
class ToolCall:
    """A model-requested tool invocation (``src/agent/types.ts:192-201``)."""

    id: str
    name: str
    args: dict[str, Any]

    @staticmethod
    def new(name: str, args: dict[str, Any]) -> "ToolCall":
        return ToolCall(id=f"call_{uuid.uuid4().hex[:12]}", name=name, args=args)


@dataclass
class ToolResult:
    """Result of executing one tool call."""

    call: ToolCall
    result: Any = None
    error: Optional[str] = None
    duration_ms: float = 0.0
    cached: bool = False

    @property
    def ok(self) -> bool:
        return self.error is None


@dataclass
class LLMMessage:
    """One chat message. ``role`` in {system,user,assistant,tool}."""

    role: str
    content: str
    tool_calls: list[ToolCall] = field(default_factory=list)
    tool_call_id: Optional[str] = None  # set when role == "tool"
    name: Optional[str] = None


@dataclass
class LLMResponse:
    """What ``LLMClient.chat`` returns (reference ``src/agent/agent.ts:167-181``)."""

    content: str
    tool_calls: list[ToolCall] = field(default_factory=list)
    thinking: Optional[str] = None
    usage: dict[str, int] = field(default_factory=dict)  # prompt/completion tokens


@dataclass
class AgentEvent:
    """Event streamed from the agent loops to UIs.

    The reference models this as a ~20-variant discriminated union
    (``src/agent/types.ts:6-140``). We use a single dataclass with a ``kind``
    discriminator and a payload dict — renderers switch on ``kind``.

    Kinds used by the free-form loop: ``start``, ``knowledge_retrieved``,
    ``iteration``, ``thinking``, ``tool_call``, ``tool_result``, ``warning``,
    ``phase``, ``answer``, ``error``, ``done``.
    Kinds used by the structured path: ``phase_change``, ``hypothesis_created``,
    ``hypothesis_updated``, ``evidence``, ``conclusion``, ``remediation_step``.
    """

    kind: str
    data: dict[str, Any] = field(default_factory=dict)
    ts: float = field(default_factory=time.time)


@dataclass
class KnowledgeResult:
    """One retrieved knowledge chunk surfaced to the agent."""

    doc_id: str
    title: str
    knowledge_type: str
    content: str
    score: float = 0.0
    services: list[str] = field(default_factory=list)
    source: str = ""


@dataclass
class RetrievedKnowledge:
    """Grouped retrieval results (reference ``src/agent/types.ts:281``)."""

    runbooks: list[KnowledgeResult] = field(default_factory=list)
    postmortems: list[KnowledgeResult] = field(default_factory=list)
    known_issues: list[KnowledgeResult] = field(default_factory=list)
    architecture: list[KnowledgeResult] = field(default_factory=list)

    def all(self) -> list[KnowledgeResult]:
        return [*self.runbooks, *self.postmortems, *self.known_issues, *self.architecture]

    @property
    def empty(self) -> bool:
        return not (self.runbooks or self.postmortems or self.known_issues or self.architecture)
