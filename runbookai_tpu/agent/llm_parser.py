"""Structured-output layer: schemas, tolerant parsers, prompt templates.

Parity target: reference ``src/agent/llm-parser.ts`` — zod schemas (:21-210)
become pydantic models (Triage / HypothesisGeneration / EvidenceEvaluation /
Conclusion / RemediationPlan / LogAnalysis); tolerant JSON extraction (:215;
shared with the chat template); prompt templates with ``{placeholders}``
(:396-563) and ``fill_prompt`` (:564).

With guided JSON decoding upstream the parse almost always succeeds on the
first strategy; the tolerant path stays as the fallback (SURVEY.md §7 step 3).
"""

from __future__ import annotations

from typing import Any, Literal, Optional

from pydantic import BaseModel, Field, ValidationError

from runbookai_tpu.model.chat_template import extract_json

Confidence = Literal["high", "medium", "low"]


class TriageResult(BaseModel):
    severity: Literal["critical", "high", "medium", "low"] = "medium"
    summary: str = ""
    affected_services: list[str] = Field(default_factory=list)
    symptoms: list[str] = Field(default_factory=list)
    signals: list[str] = Field(default_factory=list)  # notable evidence seen


class GeneratedHypothesis(BaseModel):
    statement: str
    priority: float = 0.5
    rationale: str = ""


class HypothesisGeneration(BaseModel):
    # min_length=1 reaches the guided-decoding grammar: the prompt demands
    # 3-5 hypotheses, so an empty array is never a valid generation.
    hypotheses: list[GeneratedHypothesis] = Field(default_factory=list,
                                                 min_length=1)


class EvidenceEvaluation(BaseModel):
    action: Literal["continue", "branch", "prune", "confirm"] = "continue"
    confidence: float = 0.0
    reasoning: str = ""
    supports: bool = True
    strength: Literal["strong", "weak", "neutral"] = "weak"
    sub_hypotheses: list[GeneratedHypothesis] = Field(default_factory=list)


class Conclusion(BaseModel):
    root_cause: str = ""
    confidence: Confidence = "low"
    affected_services: list[str] = Field(default_factory=list)
    contributing_factors: list[str] = Field(default_factory=list)
    summary: str = ""


class PlannedRemediationStep(BaseModel):
    description: str
    action: str = ""  # tool or skill id
    params: dict[str, Any] = Field(default_factory=dict)
    risk: Literal["read", "low", "high", "critical"] = "low"
    requires_approval: bool = True


class RemediationPlan(BaseModel):
    steps: list[PlannedRemediationStep] = Field(default_factory=list)
    rollback: str = ""
    notes: str = ""


class LogAnalysis(BaseModel):
    error_categories: list[str] = Field(default_factory=list)
    services_mentioned: list[str] = Field(default_factory=list)
    notable_lines: list[str] = Field(default_factory=list)
    suggested_hypotheses: list[GeneratedHypothesis] = Field(default_factory=list)


# --------------------------------------------------------------------------- #
# tolerant parsing                                                            #
# --------------------------------------------------------------------------- #


def _coerce(payload: Any, model: type[BaseModel]) -> Optional[BaseModel]:
    if not isinstance(payload, dict):
        return None
    try:
        return model.model_validate(payload)
    except ValidationError:
        # Second chance: drop unknown-shaped fields, keep what validates.
        cleaned = {}
        for name, field_info in model.model_fields.items():
            if name in payload:
                cleaned[name] = payload[name]
        try:
            return model.model_validate(cleaned)
        except ValidationError:
            try:
                return model()  # defaults — caller checks emptiness
            except ValidationError:
                return None


def parse_structured(text: str, model: type[BaseModel]) -> Optional[BaseModel]:
    payload = extract_json(text)
    # Tolerate a bare list where a wrapper object is expected
    # (e.g. the model emits [..] instead of {"hypotheses": [..]}).
    if isinstance(payload, list):
        list_fields = [
            n for n, f in model.model_fields.items()
            if "list" in str(f.annotation)
        ]
        if len(list_fields) >= 1:
            payload = {list_fields[0]: payload}
    return _coerce(payload, model)


def parse_triage(text: str) -> TriageResult:
    return parse_structured(text, TriageResult) or TriageResult()


def parse_hypotheses(text: str) -> HypothesisGeneration:
    return parse_structured(text, HypothesisGeneration) or HypothesisGeneration()


def parse_evaluation(text: str) -> EvidenceEvaluation:
    return parse_structured(text, EvidenceEvaluation) or EvidenceEvaluation()


def parse_conclusion(text: str) -> Conclusion:
    return parse_structured(text, Conclusion) or Conclusion()


def parse_remediation(text: str) -> RemediationPlan:
    return parse_structured(text, RemediationPlan) or RemediationPlan()


def parse_log_analysis(text: str) -> LogAnalysis:
    return parse_structured(text, LogAnalysis) or LogAnalysis()


# --------------------------------------------------------------------------- #
# prompt templates (llm-parser.ts:396-563)                                    #
# --------------------------------------------------------------------------- #

PROMPTS: dict[str, str] = {
    "triage": """\
You are triaging a production incident.

Incident context:
{context}

Respond with ONLY a JSON object:
{{"severity": "critical|high|medium|low", "summary": "<one sentence>",
  "affected_services": ["..."], "symptoms": ["..."], "signals": ["..."]}}""",
    "generate_hypotheses": """\
You are investigating this incident:
{summary}

Symptoms: {symptoms}
Affected services: {services}
Evidence so far:
{evidence}

Generate 3-5 testable root-cause hypotheses, most likely first. Respond with
ONLY a JSON object:
{{"hypotheses": [{{"statement": "...", "priority": 0.0-1.0, "rationale": "..."}}]}}""",
    "evaluate_evidence": """\
Hypothesis under test: {hypothesis}

New evidence from queries:
{evidence}

Decide the next action:
- "confirm" if the evidence establishes this as the root cause,
- "prune" if the evidence contradicts it,
- "branch" if it should split into more specific sub-hypotheses,
- "continue" if more evidence is needed.

Respond with ONLY a JSON object:
{{"action": "continue|branch|prune|confirm", "confidence": 0.0-1.0,
  "supports": true|false, "strength": "strong|weak|neutral",
  "reasoning": "...",
  "sub_hypotheses": [{{"statement": "...", "priority": 0.0-1.0}}]}}""",
    "generate_conclusion": """\
Investigation summary:
{summary}

Hypothesis tree:
{tree}

Evidence:
{evidence}

State the conclusion. Respond with ONLY a JSON object:
{{"root_cause": "...", "confidence": "high|medium|low",
  "affected_services": ["..."], "contributing_factors": ["..."],
  "summary": "<2-3 sentences for the incident channel>"}}""",
    "generate_remediation": """\
Root cause: {root_cause}
Affected services: {services}

Relevant runbooks:
{runbooks}

Code-fix candidates:
{fixes}

Plan the remediation. Respond with ONLY a JSON object:
{{"steps": [{{"description": "...", "action": "<tool or skill id>",
   "params": {{}}, "risk": "read|low|high|critical", "requires_approval": true}}],
  "rollback": "...", "notes": "..."}}""",
    "analyze_logs": """\
Analyze these log lines for error patterns:

{logs}

Respond with ONLY a JSON object:
{{"error_categories": ["..."], "services_mentioned": ["..."],
  "notable_lines": ["..."],
  "suggested_hypotheses": [{{"statement": "...", "priority": 0.0-1.0}}]}}""",
}


def fill_prompt(name: str, **values: Any) -> str:
    """Fill a template; missing keys become empty strings (llm-parser.ts:564)."""
    template = PROMPTS[name]

    class _Default(dict):
        def __missing__(self, key):
            return ""

    return template.format_map(_Default(**{k: str(v) for k, v in values.items()}))
