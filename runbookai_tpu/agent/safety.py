"""Safety: risk classification, mutation limits, cooldowns, approval flow.

Parity targets: reference ``src/agent/safety.ts`` (``AWS_RISK_CLASSIFICATION``
:38-82, SafetyManager :89 — mutation limits per session, cooldowns) and
``src/agent/approval.ts`` (``classifyRisk`` :75, auto-approve policy :216,
cooldown :310, audit JSONL ``.runbook/audit/approvals.jsonl`` :39-50).

The approval prompt itself is pluggable (CLI stdin, Slack buttons, auto) via
an async callback; critical operations require the literal confirmation
string, mirroring the reference's type-"yes" gate.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Awaitable, Callable, Optional

from runbookai_tpu.agent.types import RiskLevel

# Operation → risk classes (reference safety.ts:38-82, re-expressed).
OPERATION_RISK: dict[str, RiskLevel] = {
    # reads
    "describe": RiskLevel.READ, "list": RiskLevel.READ, "get": RiskLevel.READ,
    "query": RiskLevel.READ, "search": RiskLevel.READ, "top": RiskLevel.READ,
    # low-risk mutations
    "add_note": RiskLevel.LOW, "acknowledge": RiskLevel.LOW, "post_update": RiskLevel.LOW,
    "tag": RiskLevel.LOW,
    # high-risk mutations
    "scale": RiskLevel.HIGH, "restart": RiskLevel.HIGH, "update_service": RiskLevel.HIGH,
    "rollback": RiskLevel.HIGH, "update_function_configuration": RiskLevel.HIGH,
    "reboot": RiskLevel.HIGH, "start": RiskLevel.HIGH, "close_incident": RiskLevel.HIGH,
    # critical
    "stop": RiskLevel.CRITICAL, "delete": RiskLevel.CRITICAL,
    "terminate": RiskLevel.CRITICAL, "apply": RiskLevel.CRITICAL,
    "exec": RiskLevel.CRITICAL,
}

_RISK_ORDER = [RiskLevel.READ, RiskLevel.LOW, RiskLevel.HIGH, RiskLevel.CRITICAL]


def classify_risk(operation: str, default: RiskLevel = RiskLevel.HIGH) -> RiskLevel:
    """Classify an operation name; unknown mutations default to HIGH
    (fail-safe, reference approval.ts:75)."""
    op = operation.lower()
    if op in OPERATION_RISK:
        return OPERATION_RISK[op]
    for key, risk in OPERATION_RISK.items():
        if op.startswith(key) or key in op:
            return risk
    return default


@dataclass
class ApprovalRequest:
    operation: str
    risk: RiskLevel
    description: str
    params: dict[str, Any] = field(default_factory=dict)
    rollback_hint: Optional[str] = None


@dataclass
class ApprovalDecision:
    approved: bool
    approver: str = "auto"
    reason: str = ""


ApprovalCallback = Callable[[ApprovalRequest], Awaitable[ApprovalDecision]]


async def auto_deny(req: ApprovalRequest) -> ApprovalDecision:
    return ApprovalDecision(approved=False, approver="auto",
                            reason="no approval channel configured")


async def auto_approve(req: ApprovalRequest) -> ApprovalDecision:
    return ApprovalDecision(approved=True, approver="auto", reason="auto-approve policy")


class SafetyManager:
    def __init__(
        self,
        require_approval: tuple[str, ...] = ("high", "critical"),
        auto_approve_low_risk: bool = True,
        max_mutations_per_session: int = 5,
        cooldown_seconds: float = 60.0,
        audit_dir: str | Path = ".runbook/audit",
        approval_callback: Optional[ApprovalCallback] = None,
        persist_audit: bool = True,
    ):
        self.require_approval = {RiskLevel(r) for r in require_approval}
        self.auto_approve_low_risk = auto_approve_low_risk
        self.max_mutations = max_mutations_per_session
        self.cooldown_seconds = cooldown_seconds
        self.audit_path = Path(audit_dir) / "approvals.jsonl"
        self.approval_callback = approval_callback or auto_deny
        self.persist_audit = persist_audit
        self.mutation_count = 0
        self._last_critical_ts: Optional[float] = None

    # ----------------------------------------------------------------- audit

    def _audit(self, event: str, data: dict[str, Any]) -> None:
        if not self.persist_audit:
            return
        self.audit_path.parent.mkdir(parents=True, exist_ok=True)
        with self.audit_path.open("a") as f:
            f.write(json.dumps({"event": event, "ts": time.time(), **data}) + "\n")

    # ------------------------------------------------------------------ gate

    def check_mutation_allowed(self, risk: RiskLevel) -> tuple[bool, Optional[str]]:
        """Session limits + cooldown; returns (allowed, reason_if_denied)."""
        if risk == RiskLevel.READ:
            return True, None
        if self.mutation_count >= self.max_mutations:
            return False, (
                f"mutation limit reached ({self.max_mutations} per session)"
            )
        if risk == RiskLevel.CRITICAL and self._last_critical_ts is not None:
            elapsed = time.monotonic() - self._last_critical_ts
            if elapsed < self.cooldown_seconds:
                return False, (
                    f"cooldown: {self.cooldown_seconds - elapsed:.0f}s until the "
                    "next critical operation is allowed"
                )
        return True, None

    async def gate(self, request: ApprovalRequest) -> ApprovalDecision:
        """Full gate: limits → policy → approval callback → audit."""
        allowed, reason = self.check_mutation_allowed(request.risk)
        if not allowed:
            decision = ApprovalDecision(approved=False, approver="policy", reason=reason or "")
            self._audit("denied", {"operation": request.operation,
                                   "risk": request.risk.value, "reason": reason})
            return decision

        if request.risk == RiskLevel.READ:
            return ApprovalDecision(approved=True, approver="policy", reason="read-only")
        if request.risk == RiskLevel.LOW and self.auto_approve_low_risk and \
                RiskLevel.LOW not in self.require_approval:
            self._record_mutation(request.risk)
            self._audit("auto_approved", {"operation": request.operation,
                                          "risk": request.risk.value})
            return ApprovalDecision(approved=True, approver="policy",
                                    reason="low risk auto-approved")

        decision = await self.approval_callback(request)
        self._audit(
            "approved" if decision.approved else "rejected",
            {"operation": request.operation, "risk": request.risk.value,
             "approver": decision.approver, "reason": decision.reason,
             "params": request.params},
        )
        if decision.approved:
            self._record_mutation(request.risk)
        return decision

    def _record_mutation(self, risk: RiskLevel) -> None:
        self.mutation_count += 1
        if risk == RiskLevel.CRITICAL:
            self._last_critical_ts = time.monotonic()


def make_cli_approval(input_fn: Callable[[str], str] = input) -> ApprovalCallback:
    """CLI approval: critical requires typing 'yes' (reference parity)."""

    async def prompt(req: ApprovalRequest) -> ApprovalDecision:
        header = (
            f"\nAPPROVAL REQUIRED [{req.risk.value.upper()}]: {req.operation}\n"
            f"  {req.description}\n  params: {json.dumps(req.params, default=str)}\n"
        )
        if req.rollback_hint:
            header += f"  rollback: {req.rollback_hint}\n"
        if req.risk == RiskLevel.CRITICAL:
            answer = input_fn(header + "Type 'yes' to approve: ").strip()
            ok = answer == "yes"
        else:
            answer = input_fn(header + "Approve? [y/N]: ").strip().lower()
            ok = answer in ("y", "yes")
        return ApprovalDecision(approved=ok, approver="cli",
                                reason="operator input")

    return prompt
