"""Safety: risk classification, mutation limits, cooldowns, approval flow.

Parity targets: reference ``src/agent/safety.ts`` (``AWS_RISK_CLASSIFICATION``
:38-82, SafetyManager :89 — mutation limits per session, cooldowns) and
``src/agent/approval.ts`` (``classifyRisk`` :75, auto-approve policy :216,
cooldown :310, audit JSONL ``.runbook/audit/approvals.jsonl`` :39-50).

The approval prompt itself is pluggable (CLI stdin, Slack buttons, auto) via
an async callback; critical operations require the literal confirmation
string, mirroring the reference's type-"yes" gate.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Awaitable, Callable, Optional

from runbookai_tpu.agent.types import RiskLevel

# Operation → risk classes (reference safety.ts:38-82, re-expressed).
OPERATION_RISK: dict[str, RiskLevel] = {
    # reads
    "describe": RiskLevel.READ, "list": RiskLevel.READ, "get": RiskLevel.READ,
    "query": RiskLevel.READ, "search": RiskLevel.READ, "top": RiskLevel.READ,
    # low-risk mutations
    "add_note": RiskLevel.LOW, "acknowledge": RiskLevel.LOW, "post_update": RiskLevel.LOW,
    "tag": RiskLevel.LOW,
    # high-risk mutations
    "scale": RiskLevel.HIGH, "restart": RiskLevel.HIGH, "update_service": RiskLevel.HIGH,
    "rollback": RiskLevel.HIGH, "update_function_configuration": RiskLevel.HIGH,
    "reboot": RiskLevel.HIGH, "start": RiskLevel.HIGH, "close_incident": RiskLevel.HIGH,
    # critical
    "stop": RiskLevel.CRITICAL, "delete": RiskLevel.CRITICAL,
    "terminate": RiskLevel.CRITICAL, "apply": RiskLevel.CRITICAL,
    "exec": RiskLevel.CRITICAL,
}

_RISK_ORDER = [RiskLevel.READ, RiskLevel.LOW, RiskLevel.HIGH, RiskLevel.CRITICAL]


def classify_risk(operation: str, default: RiskLevel = RiskLevel.HIGH) -> RiskLevel:
    """Classify an operation name; unknown mutations default to HIGH
    (fail-safe, reference approval.ts:75)."""
    op = operation.lower()
    if op in OPERATION_RISK:
        return OPERATION_RISK[op]
    for key, risk in OPERATION_RISK.items():
        if op.startswith(key) or key in op:
            return risk
    return default


@dataclass
class ApprovalRequest:
    operation: str
    risk: RiskLevel
    description: str
    params: dict[str, Any] = field(default_factory=dict)
    rollback_hint: Optional[str] = None


@dataclass
class ApprovalDecision:
    approved: bool
    approver: str = "auto"
    reason: str = ""


ApprovalCallback = Callable[[ApprovalRequest], Awaitable[ApprovalDecision]]


async def auto_deny(req: ApprovalRequest) -> ApprovalDecision:
    return ApprovalDecision(approved=False, approver="auto",
                            reason="no approval channel configured")


async def auto_approve(req: ApprovalRequest) -> ApprovalDecision:
    return ApprovalDecision(approved=True, approver="auto", reason="auto-approve policy")


class SafetyManager:
    def __init__(
        self,
        require_approval: tuple[str, ...] = ("high", "critical"),
        auto_approve_low_risk: bool = True,
        max_mutations_per_session: int = 5,
        cooldown_seconds: float = 60.0,
        audit_dir: str | Path = ".runbook/audit",
        approval_callback: Optional[ApprovalCallback] = None,
        persist_audit: bool = True,
    ):
        self.require_approval = {RiskLevel(r) for r in require_approval}
        self.auto_approve_low_risk = auto_approve_low_risk
        self.max_mutations = max_mutations_per_session
        self.cooldown_seconds = cooldown_seconds
        self.audit_path = Path(audit_dir) / "approvals.jsonl"
        self.approval_callback = approval_callback or auto_deny
        self.persist_audit = persist_audit
        self.mutation_count = 0
        self._last_critical_ts: Optional[float] = None

    # ----------------------------------------------------------------- audit

    def _audit(self, event: str, data: dict[str, Any]) -> None:
        if not self.persist_audit:
            return
        self.audit_path.parent.mkdir(parents=True, exist_ok=True)
        with self.audit_path.open("a") as f:
            f.write(json.dumps({"event": event, "ts": time.time(), **data}) + "\n")

    # ------------------------------------------------------------------ gate

    def check_mutation_allowed(self, risk: RiskLevel) -> tuple[bool, Optional[str]]:
        """Session limits + cooldown; returns (allowed, reason_if_denied)."""
        if risk == RiskLevel.READ:
            return True, None
        if self.mutation_count >= self.max_mutations:
            return False, (
                f"mutation limit reached ({self.max_mutations} per session)"
            )
        if risk == RiskLevel.CRITICAL and self._last_critical_ts is not None:
            elapsed = time.monotonic() - self._last_critical_ts
            if elapsed < self.cooldown_seconds:
                return False, (
                    f"cooldown: {self.cooldown_seconds - elapsed:.0f}s until the "
                    "next critical operation is allowed"
                )
        return True, None

    async def gate(self, request: ApprovalRequest) -> ApprovalDecision:
        """Full gate: limits → policy → approval callback → audit."""
        allowed, reason = self.check_mutation_allowed(request.risk)
        if not allowed:
            decision = ApprovalDecision(approved=False, approver="policy", reason=reason or "")
            self._audit("denied", {"operation": request.operation,
                                   "risk": request.risk.value, "reason": reason})
            return decision

        if request.risk == RiskLevel.READ:
            return ApprovalDecision(approved=True, approver="policy", reason="read-only")
        if request.risk == RiskLevel.LOW and self.auto_approve_low_risk and \
                RiskLevel.LOW not in self.require_approval:
            self._record_mutation(request.risk)
            self._audit("auto_approved", {"operation": request.operation,
                                          "risk": request.risk.value})
            return ApprovalDecision(approved=True, approver="policy",
                                    reason="low risk auto-approved")

        decision = await self.approval_callback(request)
        self._audit(
            "approved" if decision.approved else "rejected",
            {"operation": request.operation, "risk": request.risk.value,
             "approver": decision.approver, "reason": decision.reason,
             "params": request.params},
        )
        if decision.approved:
            self._record_mutation(request.risk)
        return decision

    def _record_mutation(self, risk: RiskLevel) -> None:
        self.mutation_count += 1
        if risk == RiskLevel.CRITICAL:
            self._last_critical_ts = time.monotonic()


def make_raced_approval(
    store,
    input_fn: Optional[Callable[[str], str]] = None,
    notify: Optional[Callable[[str, "ApprovalRequest"], Awaitable[None]]] = None,
    timeout_s: float = 300.0,
    poll_interval_s: float = 0.5,
) -> ApprovalCallback:
    """CLI prompt RACING Slack-button responses, with a timeout.

    Reference ``approval.ts:347-547`` (``requestApprovalWithOptions``): a
    pending-approval file is created in the webhook server's
    :class:`~runbookai_tpu.server.webhook.ApprovalFileStore`; the operator
    can answer either on the CLI (stdin, run in a worker thread) or by
    clicking an approve/reject button in Slack (the webhook writes the
    response file this callback polls). First decision wins; no decision
    within ``timeout_s`` denies (fail-safe).

    ``notify`` posts the Slack message carrying the buttons (best-effort —
    an unconfigured Slack just leaves the CLI as the only racer).
    ``input_fn=None`` disables the CLI racer (headless gateway mode).
    """
    import asyncio
    import uuid as _uuid

    async def raced(req: ApprovalRequest) -> ApprovalDecision:
        approval_id = f"ap-{_uuid.uuid4().hex[:10]}"
        store.create_pending(approval_id, {
            "operation": req.operation, "risk": req.risk.value,
            "description": req.description, "params": req.params,
        })
        if notify is not None:
            try:
                await notify(approval_id, req)
            except Exception:  # noqa: BLE001 — Slack is an optional racer
                pass

        cli_task = None
        if input_fn is not None:
            prompt = make_cli_approval(input_fn)
            cli_task = asyncio.ensure_future(prompt(req))
        deadline = time.monotonic() + timeout_s
        try:
            while time.monotonic() < deadline:
                resp = store.poll_response(approval_id)
                if resp is not None:
                    return ApprovalDecision(
                        approved=bool(resp.get("approved")),
                        approver=f"slack:{resp.get('user', '')}",
                        reason="slack button")
                if cli_task is not None and cli_task.done():
                    return cli_task.result()
                await asyncio.sleep(poll_interval_s)
            return ApprovalDecision(
                approved=False, approver="timeout",
                reason=f"no decision within {timeout_s:.0f}s")
        finally:
            if cli_task is not None and not cli_task.done():
                cli_task.cancel()
            # The request is decided (either way): retire the pending file
            # so /health stops listing it and a late Slack click can't
            # "approve" an already-resolved request.
            store.discard_pending(approval_id)

    return raced


def make_cli_approval(input_fn: Callable[[str], str] = input) -> ApprovalCallback:
    """CLI approval: critical requires typing 'yes' (reference parity).

    The blocking read runs in a dedicated DAEMON thread so (a) the event
    loop stays live — :func:`make_raced_approval` polls Slack buttons while
    the operator's prompt sits unanswered — and (b) an abandoned prompt
    (the race was decided elsewhere) cannot hang interpreter exit the way
    a ``to_thread`` executor worker blocked in ``input()`` would."""
    import asyncio
    import threading

    def _read(text: str, loop, fut) -> None:
        try:
            answer = input_fn(text)
        except (EOFError, KeyboardInterrupt):
            answer = ""

        def deliver() -> None:
            if not fut.cancelled():
                fut.set_result(answer)

        loop.call_soon_threadsafe(deliver)

    async def prompt(req: ApprovalRequest) -> ApprovalDecision:
        header = (
            f"\nAPPROVAL REQUIRED [{req.risk.value.upper()}]: {req.operation}\n"
            f"  {req.description}\n  params: {json.dumps(req.params, default=str)}\n"
        )
        if req.rollback_hint:
            header += f"  rollback: {req.rollback_hint}\n"
        critical = req.risk == RiskLevel.CRITICAL
        text = header + ("Type 'yes' to approve: " if critical
                         else "Approve? [y/N]: ")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        threading.Thread(target=_read, args=(text, loop, fut),
                         daemon=True).start()
        answer = await fut
        ok = (answer.strip() == "yes" if critical
              else answer.strip().lower() in ("y", "yes"))
        return ApprovalDecision(approved=ok, approver="cli",
                                reason="operator input")

    return prompt
