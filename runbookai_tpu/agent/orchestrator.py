"""Structured investigation orchestrator driving the FSM.

Parity target: reference ``src/agent/investigation-orchestrator.ts`` —
``investigate`` (:633), ``runTriage`` (:723) with ``gatherTriageContext``
(:751: incident fetch then a fallback chain search_knowledge →
cloudwatch_alarms → datadog → aws_query stopping at the first meaningful
signal :364-415), ``generateHypotheses`` (:877), ``runInvestigationCycle``
(:901) with per-hypothesis causal queries, broadness refinement and tool
fallback ``adaptQueryToEnvironment`` (:441-462), ``evaluateEvidence`` (:1005)
→ ``applyEvaluation`` branch/prune/confirm/continue, ``runConclusion``
(:1044), ``runRemediation`` (:1097) with runbook + code-fix retrieval, and
``executeRemediation`` (:1148) through approval callbacks.

The LLM seam is the simple ``complete(prompt) -> str`` interface
(investigation-orchestrator.ts:59-61); with the jax-tpu client this uses
guided JSON decoding.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional

from runbookai_tpu.agent import llm_parser as lp
from runbookai_tpu.agent.causal_query import (
    generate_queries_for_hypothesis,
    is_query_too_broad,
    suggest_query_refinements,
    summarize_query_results,
)
from runbookai_tpu.agent.log_analyzer import analyze_logs
from runbookai_tpu.agent.state_machine import (
    EvaluationAction,
    EvidenceRecord,
    InvestigationStateMachine,
    Phase,
    RemediationStep,
)
from runbookai_tpu.agent.types import AgentEvent

# Tool substitution chains when a query's tool is unavailable
# (investigation-orchestrator.ts:441-462).
TOOL_FALLBACKS: dict[str, list[str]] = {
    "datadog": ["cloudwatch_alarms", "cloudwatch_logs", "prometheus", "aws_query"],
    "prometheus": ["datadog", "cloudwatch_alarms", "aws_query"],
    "cloudwatch_alarms": ["datadog", "prometheus", "aws_query"],
    "cloudwatch_logs": ["datadog", "kubernetes_query"],
    "kubernetes_query": ["aws_query"],
    "aws_query": ["kubernetes_query"],
}


@dataclass
class OrchestratorResult:
    summary: dict[str, Any]
    root_cause: str
    confidence: str
    affected_services: list[str]
    conclusion_summary: str = ""
    remediation: list[dict[str, Any]] = field(default_factory=list)
    events: list[AgentEvent] = field(default_factory=list)


_LEVELS = ("low", "medium", "high")


def _min_level(a: str, b: str) -> str:
    """Conservative blend of two confidence levels (unknown → low)."""
    ia = _LEVELS.index(a) if a in _LEVELS else 0
    ib = _LEVELS.index(b) if b in _LEVELS else 0
    return _LEVELS[min(ia, ib)]


class ToolExecutor:
    """Thin seam: name + params -> result (the orchestrator's tool interface)."""

    def __init__(self, tools: dict[str, Any]):
        self.tools = tools

    def available(self) -> set[str]:
        return set(self.tools)

    async def execute(self, name: str, params: dict[str, Any]) -> Any:
        tool = self.tools.get(name)
        if tool is None:
            raise KeyError(f"tool {name!r} unavailable")
        return await tool.execute(params)


class InvestigationOrchestrator:
    def __init__(
        self,
        llm,  # needs .complete(prompt) -> str
        executor: ToolExecutor,
        machine: Optional[InvestigationStateMachine] = None,
        knowledge=None,  # optional retriever facade
        approval_callback: Optional[Callable[[RemediationStep], Awaitable[bool]]] = None,
        log_group_hint: Optional[str] = None,
        event_sink: Optional[Callable[[AgentEvent], None]] = None,
        queries_per_cycle: int = 3,
        execute_remediation: bool = False,
    ):
        self.llm = llm
        self.executor = executor
        self.machine = machine or InvestigationStateMachine()
        self.knowledge = knowledge
        self.approval_callback = approval_callback
        self.log_group_hint = log_group_hint
        self.event_sink = event_sink
        self.queries_per_cycle = queries_per_cycle
        self.execute_remediation_steps = execute_remediation
        self.events: list[AgentEvent] = []

    def _emit(self, kind: str, **data: Any) -> None:
        ev = AgentEvent(kind, data)
        self.events.append(ev)
        if self.event_sink:
            self.event_sink(ev)

    async def _complete(self, prompt: str, schema: Optional[str] = None) -> str:
        """LLM completion, requesting the named grammar when the client
        supports schema-constrained guided decoding (jax-tpu does; the seam
        stays ``complete(prompt) -> str`` for mocks/adapters).

        Schema support is probed from ``inspect.signature`` once per client
        (ADVICE r2: catching TypeError from the call masked genuine
        TypeErrors raised inside synchronous adapters' argument handling)."""
        # Token streaming (reference streams into the live Ink tree): when
        # a sink is listening and the client can stream, emit token deltas
        # as each phase document decodes — the CLI paints them under the
        # live hypothesis tree. The joined text is byte-identical to the
        # buffered path.
        # Streaming must not silently drop the schema constraint: if the
        # client's complete_stream can't take schema= but this call needs
        # one, prefer the buffered schema-guided complete() below —
        # unconstrained phase documents are worse than unstreamed ones
        # (ADVICE r4).
        if (self.event_sink is not None
                and hasattr(self.llm, "complete_stream")
                and (schema is None
                     or self._supports_schema(self.llm.complete_stream))):
            parts: list[str] = []
            kwargs = {"schema": schema} if schema is not None else {}
            async for piece in self.llm.complete_stream(prompt, **kwargs):
                parts.append(piece)
                # Transient: straight to the sink, NOT self.events — a
                # long investigation would otherwise store every delta.
                self.event_sink(AgentEvent("token", {"delta": piece}))
            return "".join(parts)
        if schema is not None and self._supports_schema():
            return await self.llm.complete(prompt, schema=schema)
        return await self.llm.complete(prompt)

    def _supports_schema(self, method=None) -> bool:
        """Does ``method`` (default: ``llm.complete``) accept ``schema=``?
        Probed per METHOD — an adapter may implement complete(prompt,
        **kw) but complete_stream(prompt) without it."""
        method = method if method is not None else self.llm.complete
        cache: dict = getattr(self, "_schema_ok", None) or {}
        self._schema_ok = cache
        key = getattr(method, "__qualname__", repr(method))
        if key not in cache:
            import inspect

            try:
                params = inspect.signature(method).parameters
                cache[key] = "schema" in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values())
            except (TypeError, ValueError):  # builtins/partials w/o signature
                cache[key] = False
        return cache[key]

    # ------------------------------------------------------------------ main

    async def investigate(self, incident_id: str = "",
                          description: str = "") -> OrchestratorResult:
        m = self.machine
        if incident_id:
            m.incident_id = incident_id
        m.start()
        self._emit("phase_change", phase=Phase.TRIAGE.value)

        triage = await self.run_triage(incident_id, description)
        m.symptoms = triage.symptoms
        m.affected_services = triage.affected_services

        m.transition(Phase.HYPOTHESIZE)
        self._emit("phase_change", phase=Phase.HYPOTHESIZE.value)
        await self.generate_hypotheses(triage)

        if not m.hypotheses:
            m.record_error("no hypotheses generated")
            m.transition(Phase.CONCLUDE)
        else:
            m.transition(Phase.INVESTIGATE)
            self._emit("phase_change", phase=Phase.INVESTIGATE.value)

        # HOT LOOP (investigation-orchestrator.ts:651).
        while m.can_continue():
            m.iterations += 1
            confirmed = await self.run_investigation_cycle()
            if confirmed:
                break
            if m.open_count() == 0:
                break
            if m.phase == Phase.EVALUATE:
                m.transition(Phase.INVESTIGATE)

        if m.phase not in (Phase.CONCLUDE, Phase.COMPLETE, Phase.FAILED):
            m.transition(Phase.CONCLUDE)
        self._emit("phase_change", phase=Phase.CONCLUDE.value)
        conclusion = await self.run_conclusion(description)

        m.transition(Phase.REMEDIATE)
        self._emit("phase_change", phase=Phase.REMEDIATE.value)
        remediation = await self.run_remediation(conclusion)
        if self.execute_remediation_steps and remediation.steps:
            await self.execute_remediation()

        m.transition(Phase.COMPLETE)
        self._emit("phase_change", phase=Phase.COMPLETE.value)

        return OrchestratorResult(
            summary=m.get_summary(),
            root_cause=m.root_cause or "",
            confidence=m.conclusion_confidence or "low",
            affected_services=m.affected_services,
            conclusion_summary=conclusion.summary,
            remediation=[
                {"description": s.description, "action": s.action,
                 "risk": s.risk, "status": s.status, "result": s.result}
                for s in m.remediation_plan
            ],
            events=self.events,
        )

    # ---------------------------------------------------------------- triage

    async def gather_triage_context(self, incident_id: str,
                                    description: str) -> str:
        """Incident fetch then fallback-chain until a meaningful signal."""
        blocks: list[str] = []
        if description:
            blocks.append(f"Description: {description}")
        incident = None
        for tool in ("pagerduty_get_incident", "opsgenie_get_alert"):
            if incident_id and tool in self.executor.available():
                try:
                    incident = await self.executor.execute(
                        tool, {"incident_id": incident_id})
                    if isinstance(incident, dict) and not incident.get("error"):
                        blocks.append(f"Incident: {json.dumps(incident)[:1500]}")
                        break
                except Exception as exc:  # noqa: BLE001 — move to next source
                    self.machine.record_error(f"{tool}: {exc}")

        # Deterministic cross-modality triage first (signal_triage tool):
        # dates signals against the incident start, discounts stale/
        # recovered stories, ranks candidates by symptom topology — the
        # phase document starts from analyzed evidence, not raw noise.
        if "signal_triage" in self.executor.available():
            try:
                tri = await self.executor.execute(
                    "signal_triage", {"incident_id": incident_id})
                if isinstance(tri, dict) and tri.get("report"):
                    blocks.append("Signal triage (deterministic "
                                  "cross-modality analysis):\n"
                                  + str(tri["report"])[:2000])
            except Exception as exc:  # noqa: BLE001 — analysis is optional
                self.machine.record_error(f"signal_triage: {exc}")

        # Fallback chain (orchestrator :815-869) — stop at first real signal.
        chain = [
            ("search_knowledge", {"query": description or incident_id or "incident"}),
            ("cloudwatch_alarms", {"state": "ALARM"}),
            ("datadog", {"action": "monitors"}),
            ("prometheus", {"action": "alerts"}),
            ("aws_query", {"service": "ecs"}),
        ]
        for tool, params in chain:
            if tool not in self.executor.available():
                continue
            try:
                result = await self.executor.execute(tool, params)
            except Exception as exc:  # noqa: BLE001
                self.machine.record_error(f"{tool}: {exc}")
                continue
            text = json.dumps(result, default=str)
            if self._meaningful(result):
                blocks.append(f"{tool}: {text[:1500]}")
                break
            blocks.append(f"{tool}: (no significant signal)")
        return "\n".join(blocks) if blocks else "(no context available)"

    @staticmethod
    def _meaningful(result: Any) -> bool:
        if not result:
            return False
        if isinstance(result, dict):
            if result.get("error"):
                return False
            for v in result.values():
                if isinstance(v, list) and v:
                    return True
                if isinstance(v, dict) and v:
                    return True
            return False
        return bool(result)

    async def run_triage(self, incident_id: str, description: str) -> lp.TriageResult:
        context = await self.gather_triage_context(incident_id, description)
        raw = await self._complete(lp.fill_prompt("triage", context=context),
                                   schema="triage")
        triage = lp.parse_triage(raw)
        if not triage.summary:
            triage.summary = description or f"incident {incident_id}"
        self._emit("triage", severity=triage.severity, summary=triage.summary,
                   services=triage.affected_services)
        self._triage_context = context
        return triage

    # ------------------------------------------------------------ hypotheses

    async def generate_hypotheses(self, triage: lp.TriageResult) -> None:
        raw = await self._complete(lp.fill_prompt(
            "generate_hypotheses",
            summary=triage.summary,
            symptoms=", ".join(triage.symptoms),
            services=", ".join(triage.affected_services),
            evidence="\n".join(triage.signals),
        ), schema="hypotheses")
        generated = lp.parse_hypotheses(raw)
        for g in generated.hypotheses[:5]:
            if g.statement:
                h = self.machine.add_hypothesis(g.statement, priority=g.priority)
                if h:
                    self._emit("hypothesis_created", id=h.id, statement=h.statement,
                               priority=h.priority)

    # ----------------------------------------------------------------- cycle

    def adapt_query_to_environment(self, tool: str) -> Optional[str]:
        available = self.executor.available()
        if tool in available:
            return tool
        for fallback in TOOL_FALLBACKS.get(tool, []):
            if fallback in available:
                return fallback
        return None

    async def execute_queries_for_hypothesis(self, hypothesis) -> list[tuple]:
        queries = generate_queries_for_hypothesis(
            hypothesis.statement,
            log_group=self.log_group_hint,
            available_tools=self.executor.available(),
            max_queries=self.queries_per_cycle,
        )
        results = []
        for query in queries:
            if is_query_too_broad(query):
                query = suggest_query_refinements(
                    query, services=self.machine.affected_services)
            tool = self.adapt_query_to_environment(query.tool)
            if tool is None:
                results.append((query, None, f"no tool available for {query.tool}"))
                continue
            params = query.params if tool == query.tool else self._fallback_params(tool)
            try:
                result = await self.executor.execute(tool, params)
                results.append((query, result, None))
                self._emit("evidence", hypothesis=hypothesis.id, tool=tool,
                           params=params)
            except Exception as exc:  # noqa: BLE001
                results.append((query, None, str(exc)))
                self.machine.record_error(f"{tool}: {exc}")
        return results

    @staticmethod
    def _fallback_params(tool: str) -> dict[str, Any]:
        return {
            "cloudwatch_alarms": {"state": "ALARM"},
            "cloudwatch_logs": {"log_group": "", "filter_pattern": "error"},
            "datadog": {"action": "metrics", "query": "latency"},
            "prometheus": {"action": "alerts"},
            "aws_query": {"service": "ecs"},
            "kubernetes_query": {"action": "pods"},
        }.get(tool, {})

    async def run_investigation_cycle(self) -> bool:
        """One hypothesis cycle; returns True when a hypothesis is confirmed."""
        m = self.machine
        hypothesis = m.get_next_hypothesis()
        if hypothesis is None:
            return False
        hypothesis.status = "investigating"
        results = await self.execute_queries_for_hypothesis(hypothesis)
        evidence_text = summarize_query_results(results)

        if m.can_transition(Phase.EVALUATE):
            m.transition(Phase.EVALUATE)
        raw = await self._complete(lp.fill_prompt(
            "evaluate_evidence", hypothesis=hypothesis.statement,
            evidence=evidence_text,
        ), schema="evaluation")
        evaluation = lp.parse_evaluation(raw)

        for query, result, error in results:
            if error is None:
                m.add_evidence(EvidenceRecord(
                    hypothesis_id=hypothesis.id, query=query.expected_outcome,
                    tool=query.tool, result_summary=str(result)[:400],
                    supports=evaluation.supports, strength=evaluation.strength,
                ))

        # Multi-factor confidence (reference confidence.ts:22-46, wired into
        # evaluation per investigation-orchestrator.ts:1005): blend the LLM's
        # self-reported level with a score computed from the evidence record —
        # the conservative of the two wins, so a confident-sounding evaluation
        # over thin evidence cannot inflate the tree.
        computed = self._computed_confidence(hypothesis.id, hypothesis.depth)
        blended = min(float(evaluation.confidence), computed)
        created = m.apply_evaluation(
            hypothesis.id,
            EvaluationAction(evaluation.action),
            confidence=blended,
            sub_hypotheses=[s.model_dump() for s in evaluation.sub_hypotheses],
            reason=evaluation.reasoning,
        )
        for child in created:
            self._emit("hypothesis_created", id=child.id, statement=child.statement,
                       parent=hypothesis.id)
        self._emit("hypothesis_updated", id=hypothesis.id,
                   action=evaluation.action, confidence=blended,
                   llm_confidence=evaluation.confidence,
                   computed_confidence=computed)

        if evaluation.action == "confirm":
            m.transition(Phase.CONCLUDE)
            return True
        return False

    def _computed_confidence(self, hypothesis_id: str, depth: int) -> float:
        """Evidence-derived confidence for one hypothesis, scaled to [0, 1]
        (the machine's numeric confidence space; confidence.ts scores 0-100)."""
        from runbookai_tpu.agent.confidence import (
            ConfidenceFactors,
            confidence_score,
        )

        records = [e for e in self.machine.evidence
                   if e.hypothesis_id == hypothesis_id]
        support = [e for e in records if e.supports]
        contra = [e for e in records if not e.supports]
        score = confidence_score(ConfidenceFactors(
            evidence_chain_depth=depth + 1,
            corroborating_signals=len(support),
            contradicting_signals=len(contra),
            direct_evidence=any(e.strength == "strong" for e in support),
        ))
        return max(0.0, min(1.0, score / 100.0))

    # ------------------------------------------------------------ conclusion

    async def run_conclusion(self, description: str = "") -> lp.Conclusion:
        m = self.machine
        evidence_text = "\n".join(
            f"- [{e.tool}] {e.result_summary[:200]}" for e in m.evidence[-15:]
        )
        raw = await self._complete(lp.fill_prompt(
            "generate_conclusion",
            summary=description or m.incident_id,
            tree=m.hypothesis_tree_markdown(),
            evidence=evidence_text,
        ), schema="conclusion")
        conclusion = lp.parse_conclusion(raw)
        confirmed = m.confirmed_hypothesis()
        if not conclusion.root_cause and confirmed is not None:
            conclusion.root_cause = confirmed.statement
            conclusion.confidence = "medium"
        if confirmed is not None:
            # Conclusion confidence is also capped by the evidence-derived
            # score of the confirmed hypothesis (confidence.ts wiring).
            from runbookai_tpu.agent.confidence import level_from_value

            computed = self._computed_confidence(confirmed.id, confirmed.depth)
            conclusion.confidence = _min_level(
                conclusion.confidence, level_from_value(computed * 100.0))
        m.root_cause = conclusion.root_cause
        m.conclusion_confidence = conclusion.confidence
        for svc in conclusion.affected_services:
            if svc not in m.affected_services:
                m.affected_services.append(svc)
        self._emit("conclusion", root_cause=m.root_cause,
                   confidence=m.conclusion_confidence,
                   services=m.affected_services)
        return conclusion

    # ----------------------------------------------------------- remediation

    async def fetch_relevant_runbooks(self) -> str:
        if self.knowledge is None:
            return "(no knowledge base)"
        try:
            grouped = self.knowledge.search_grouped(
                self.machine.root_cause or "remediation",
                service=self.machine.affected_services[0]
                if self.machine.affected_services else None,
            )
            docs = grouped.runbooks[:2]
            return "\n".join(f"[{d.doc_id}] {d.title}: {d.content[:600]}"
                             for d in docs) or "(none found)"
        except Exception as exc:  # noqa: BLE001
            self.machine.record_error(f"runbook fetch: {exc}")
            return "(runbook fetch failed)"

    async def fetch_code_fix_candidates(self) -> str:
        for tool in ("github_query", "gitlab_query"):
            if tool in self.executor.available():
                try:
                    result = await self.executor.execute(tool, {
                        "action": "fix_candidates",
                        "service": self.machine.affected_services[0]
                        if self.machine.affected_services else "",
                    })
                    return json.dumps(result, default=str)[:1200]
                except Exception as exc:  # noqa: BLE001
                    self.machine.record_error(f"{tool}: {exc}")
        return "(no code providers configured)"

    async def run_remediation(self, conclusion: lp.Conclusion) -> lp.RemediationPlan:
        runbooks = await self.fetch_relevant_runbooks()
        fixes = await self.fetch_code_fix_candidates()
        raw = await self._complete(lp.fill_prompt(
            "generate_remediation",
            root_cause=self.machine.root_cause or "",
            services=", ".join(self.machine.affected_services),
            runbooks=runbooks, fixes=fixes,
        ), schema="remediation")
        plan = lp.parse_remediation(raw)
        for step in plan.steps:
            self.machine.remediation_plan.append(RemediationStep(
                description=step.description, action=step.action,
                params=step.params, risk=step.risk,
                requires_approval=step.requires_approval,
            ))
            self._emit("remediation_step", description=step.description,
                       risk=step.risk)
        return plan

    async def execute_remediation(self) -> None:
        """Execute plan steps through approval + the skill/tool layer."""
        for step in self.machine.remediation_plan:
            if step.requires_approval and self.approval_callback is not None:
                approved = await self.approval_callback(step)
                if not approved:
                    step.status = "rejected"
                    continue
            elif step.requires_approval:
                step.status = "pending"  # no approval channel: leave pending
                continue
            step.status = "approved"
            if not step.action:
                step.status = "executed"
                step.result = "manual step (no action bound)"
                continue
            try:
                tool = self.adapt_query_to_environment(step.action) or step.action
                result = await self.executor.execute(tool, step.params)
                step.status = "executed"
                step.result = str(result)[:400]
            except Exception as exc:  # noqa: BLE001
                step.status = "failed"
                step.result = str(exc)
                self.machine.record_error(f"remediation {step.action}: {exc}")

    # ------------------------------------------------------------------ logs

    async def analyze_log_lines(self, lines: list[str], use_llm: bool = True) -> lp.LogAnalysis:
        """Regex analysis merged with LLM analysis (orchestrator :1224-1255)."""
        regex = analyze_logs(lines)
        merged = lp.LogAnalysis(
            error_categories=list(regex.pattern_counts),
            services_mentioned=regex.services,
            notable_lines=regex.notable_lines,
            suggested_hypotheses=[
                lp.GeneratedHypothesis(statement=h["statement"], priority=h["priority"])
                for h in regex.hypotheses
            ],
        )
        if use_llm and lines:
            raw = await self._complete(lp.fill_prompt(
                "analyze_logs", logs="\n".join(lines[:80])),
                schema="log_analysis")
            llm_result = lp.parse_log_analysis(raw)
            for cat in llm_result.error_categories:
                if cat not in merged.error_categories:
                    merged.error_categories.append(cat)
            for h in llm_result.suggested_hypotheses:
                if h.statement and all(h.statement != x.statement
                                       for x in merged.suggested_hypotheses):
                    merged.suggested_hypotheses.append(h)
        return merged
