"""Concurrent tool-call execution with a concurrency limit.

Parity target: reference ``src/agent/parallel-executor.ts`` (:47 class, :238
``analyzeToolDependencies``, :281 factory) — Promise.all batches become
``asyncio.gather`` under a semaphore. Dependency analysis keeps calls that
write (mutations) serialized after reads, and calls targeting the same tool
with identical args deduplicated.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Awaitable, Callable, Optional

from runbookai_tpu.agent.types import RiskLevel, Tool, ToolCall, ToolResult
from runbookai_tpu.utils.metrics import TOOL_LATENCY_BUCKETS, get_registry

# Per-tool serving metrics (same registry the engine/server report through;
# tool names are a bounded set, so they are safe as a label).
_TOOL_LATENCY = get_registry().histogram(
    "runbook_agent_tool_latency_seconds", "Tool execution latency",
    labels=("tool",), buckets=TOOL_LATENCY_BUCKETS)
_TOOL_CALLS = get_registry().counter(
    "runbook_agent_tool_calls_total", "Tool executions (cache misses)",
    labels=("tool",))
_TOOL_ERRORS = get_registry().counter(
    "runbook_agent_tool_errors_total",
    "Tool executions that errored or timed out", labels=("tool",))


def analyze_tool_dependencies(
    calls: list[ToolCall], tools: dict[str, Tool]
) -> list[list[ToolCall]]:
    """Group calls into sequential stages of parallelizable batches: reads
    batch together; each mutation runs alone in submission order."""
    stages: list[list[ToolCall]] = []
    current_reads: list[ToolCall] = []
    for call in calls:
        tool = tools.get(call.name)
        is_mutation = tool is not None and tool.risk != RiskLevel.READ
        if is_mutation:
            if current_reads:
                stages.append(current_reads)
                current_reads = []
            stages.append([call])
        else:
            current_reads.append(call)
    if current_reads:
        stages.append(current_reads)
    return stages


class ParallelToolExecutor:
    def __init__(self, max_concurrency: int = 5,
                 timeout_seconds: Optional[float] = 120.0,
                 mutation_timeout_seconds: Optional[float] = None):
        self.max_concurrency = max_concurrency
        self.timeout = timeout_seconds
        # Mutating tools run the human approval flow INSIDE execute() —
        # the read-tool watchdog must not cancel an operator mid-decision
        # (None = no timeout; the approval race has its own).
        self.mutation_timeout = mutation_timeout_seconds

    async def _execute_one(
        self, call: ToolCall, execute: Callable[[ToolCall], Awaitable[Any]],
        is_mutation: bool = False,
    ) -> ToolResult:
        start = time.perf_counter()
        timeout = self.mutation_timeout if is_mutation else self.timeout
        _TOOL_CALLS.labels(tool=call.name).inc()  # runbook: noqa[RBK010] — tool label: registered toolset, fixed at executor construction
        try:
            if timeout:
                result = await asyncio.wait_for(execute(call), timeout=timeout)
            else:
                result = await execute(call)
            return ToolResult(call=call, result=result,
                              duration_ms=(time.perf_counter() - start) * 1000)
        except asyncio.TimeoutError:
            _TOOL_ERRORS.labels(tool=call.name).inc()  # runbook: noqa[RBK010] — tool label: registered toolset, fixed at executor construction
            return ToolResult(call=call, error=f"tool {call.name} timed out",
                              duration_ms=(time.perf_counter() - start) * 1000)
        except Exception as exc:  # noqa: BLE001 — tool errors surface as results
            _TOOL_ERRORS.labels(tool=call.name).inc()  # runbook: noqa[RBK010] — tool label: registered toolset, fixed at executor construction
            return ToolResult(call=call, error=f"{type(exc).__name__}: {exc}",
                              duration_ms=(time.perf_counter() - start) * 1000)
        finally:
            _TOOL_LATENCY.labels(tool=call.name).observe(  # runbook: noqa[RBK010] — tool label: registered toolset, fixed at executor construction
                time.perf_counter() - start)

    async def execute_all(
        self,
        calls: list[ToolCall],
        execute: Callable[[ToolCall], Awaitable[Any]],
        tools: Optional[dict[str, Tool]] = None,
    ) -> list[ToolResult]:
        """Execute calls honoring dependency stages; results in input order."""
        sem = asyncio.Semaphore(self.max_concurrency)
        tool_map = tools or {}

        async def bounded(call: ToolCall) -> ToolResult:
            async with sem:
                tool = tool_map.get(call.name)
                mut = tool is not None and tool.risk != RiskLevel.READ
                return await self._execute_one(call, execute, is_mutation=mut)

        stages = analyze_tool_dependencies(calls, tool_map)
        by_id: dict[str, ToolResult] = {}
        for stage in stages:
            results = await asyncio.gather(*(bounded(c) for c in stage))
            for r in results:
                by_id[r.call.id] = r
        return [by_id[c.id] for c in calls]


def create_parallel_executor(max_concurrency: int = 5) -> ParallelToolExecutor:
    return ParallelToolExecutor(max_concurrency=max_concurrency)
