"""Infra context manager: optional pre-discovery of inventory/health/alarms.

Parity target: reference ``src/agent/infra-context.ts`` (:119 class, :597
factory) — before the loop starts, snapshot AWS inventory, firing alarms, and
cluster health into a system-prompt block so early iterations skip discovery
queries.
"""

from __future__ import annotations

import json
from typing import Any, Optional


class InfraContextManager:
    def __init__(self, executor, max_chars: int = 3000):
        # executor: ToolExecutor-like (execute(name, params), available())
        self.executor = executor
        self.max_chars = max_chars
        self._block: str = ""

    async def discover(self) -> str:
        sections: list[str] = []

        async def sample(tool: str, params: dict[str, Any], label: str) -> None:
            if tool not in self.executor.available():
                return
            try:
                result = await self.executor.execute(tool, params)
            except Exception:  # noqa: BLE001 — discovery is best-effort
                return
            text = json.dumps(result, default=str)
            if len(text) > 5:
                sections.append(f"## {label}\n{text[:900]}")

        await sample("cloudwatch_alarms", {"state": "ALARM"}, "Firing alarms")
        await sample("aws_query", {"service": "ecs"}, "ECS services")
        await sample("kubernetes_query", {"action": "status"}, "Cluster status")
        await sample("kubernetes_query", {"action": "deployments"}, "Deployments")

        if sections:
            self._block = ("# Pre-discovered infrastructure state\n"
                           + "\n".join(sections))[: self.max_chars]
        return self._block

    def system_prompt_block(self) -> str:
        return self._block


async def create_infra_context(executor, enabled: bool = True) -> Optional[InfraContextManager]:
    """Factory (reference infra-context.ts:597): discover up-front or skip."""
    if not enabled:
        return None
    manager = InfraContextManager(executor)
    await manager.discover()
    return manager
