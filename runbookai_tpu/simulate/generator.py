"""Seeded incident-scenario generator (see package docstring).

A scenario is built in three steps:

1. **Topology** — sample 3-5 services from a name pool into a call chain
   (edge service → mid tier → stateful backend), so blast radius and
   "which service do the symptoms point at" differ per seed.
2. **Fault** — sample a fault template and a root-cause service. Each
   template emits the full signal chain the real incident would leave:
   alarms, fault-specific log lines, k8s state, a metric step-change,
   a PagerDuty incident, and (for deploy-caused faults) the culprit PR.
3. **Propagation** — upstream services get secondary symptoms (latency
   alarms, timeout logs) so the agent must walk the chain instead of
   pattern-matching the first alarm.

Ground truth rides in :class:`Scenario.truth` and converts straight into
an :class:`~runbookai_tpu.evalsuite.scoring.EvalCase` (fixtures override +
expected root cause + keywords), so `runbook eval --simulate N` scores
investigations against incidents that exist in no checked-in fixture.

Reference parity: scripts/simulate/setup-incidents.sh (real-infra mode);
this generator is the credential-free equivalent covering ten fault
families instead of one.
"""

from __future__ import annotations

import json
import random
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

# ----------------------------------------------------------------- pools

_EDGE = ["checkout-api", "storefront-web", "mobile-gateway", "partner-api",
         "admin-portal"]
_MID = ["cart-service", "pricing-service", "auth-service", "search-api",
        "billing-worker", "notification-service", "inventory-sync"]
_BACKEND = ["orders-db", "ledger-db", "session-cache", "catalog-db",
            "events-queue", "blob-store"]

_REGIONS = ["us-east-1", "us-west-2", "eu-central-1"]


_BASE_EPOCH = 1_767_225_600  # 2026-01-01T00:00:00Z

# Seed-derived clock base, set per generate_scenario call: same seed →
# byte-identical scenarios (files regenerate reproducibly; the
# determinism test cannot flake across a wall-clock second boundary).
# Module-global + lock (not a threaded-through parameter) keeps the
# eleven fault templates' signatures flat; generation is cheap enough
# that serializing concurrent callers costs nothing.
_ts_base = [_BASE_EPOCH]
_gen_lock = threading.Lock()


def _ts(minutes_ago: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ",
                         time.gmtime(_ts_base[0] - minutes_ago * 60))


@dataclass
class Scenario:
    scenario_id: str
    query: str
    fixtures: dict[str, Any]
    truth: dict[str, Any] = field(default_factory=dict)
    # Served model group this investigation should run against (multi-
    # model fleets): `runbook eval --simulate` / `simulate eval --models`
    # assign groups round-robin so the generated load exercises
    # model-field routing; None = the default model (single-model runs
    # are unchanged).
    model: str | None = None

    def to_json(self) -> str:
        doc = {"scenario_id": self.scenario_id,
               "query": self.query, "truth": self.truth,
               "fixtures": self.fixtures}
        if self.model is not None:
            doc["model"] = self.model
        return json.dumps(doc, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "Scenario":
        d = json.loads(text)
        return cls(scenario_id=d["scenario_id"], query=d["query"],
                   fixtures=d["fixtures"], truth=d.get("truth", {}),
                   model=d.get("model"))


# ------------------------------------------------------------ fault kit
#
# Each fault template returns the ROOT service's telemetry:
#   alarms, logs, k8s pod states, metric shape, pd description, keywords
# and whether a deploy/PR is the culprit.

def _f_db_pool(svc, dep, rng):
    size = rng.choice([10, 15, 20])
    return {
        "alarm_metric": ("DatabaseConnections", 90, 99),
        "logs": [
            ("ERROR", f"connection pool exhausted: size {size} "
                      f"(reduced in last deploy), 214 waiting"),
            ("ERROR", "FATAL: remaining connection slots are reserved"),
        ],
        "pods": "Running",
        "deploy_culprit": True,
        "diff_hint": f"max_pool_size: 50 -> {size}",
        "pd": f"{svc} database connection pool exhausted",
        "keywords": ["connection pool", "deploy"],
        "root_cause": f"{svc} deploy shrank the DB connection pool to "
                      f"{size}, exhausting connections under load",
    }


def _f_oom(svc, dep, rng):
    mb = rng.choice([512, 1024, 2048])
    return {
        "alarm_metric": ("MemoryUtilization", 90, 99),
        "logs": [
            ("ERROR", f"java.lang.OutOfMemoryError: Java heap space "
                      f"(limit {mb}M)"),
            ("WARN", "GC overhead limit: 97% time in GC, 2% heap "
                     "recovered"),
        ],
        "pods": "OOMKilled",
        "deploy_culprit": False,
        "pd": f"{svc} pods OOMKilled repeatedly",
        "keywords": ["oom", "memory"],
        "root_cause": f"{svc} memory leak — heap exhaustion "
                      f"({mb}M limit) causing OOMKilled restarts",
    }


def _f_bad_deploy(svc, dep, rng):
    ver = f"{rng.randint(2, 9)}.{rng.randint(0, 30)}.{rng.randint(0, 9)}"
    return {
        "alarm_metric": ("HTTPCode_Target_5XX_Count", 25, rng.randint(300, 900)),
        "logs": [
            ("ERROR", f"NullPointerException at FeatureFlagResolver.get "
                      f"(introduced in {svc}:{ver})"),
            ("ERROR", "500 Internal Server Error on 38% of requests"),
        ],
        "pods": "Running",
        "deploy_culprit": True,
        "diff_hint": "feature-flag resolver refactor",
        "pd": f"{svc} 5xx spike after deploy {ver}",
        "keywords": ["deploy", "5xx"],
        "root_cause": f"bad deploy {svc}:{ver} — NPE in feature-flag "
                      f"resolver returning 500s",
    }


def _f_cert_expiry(svc, dep, rng):
    return {
        "alarm_metric": ("TLSNegotiationErrorCount", 10, rng.randint(200, 600)),
        "logs": [
            ("ERROR", "SSLHandshakeException: certificate expired "
                      f"(notAfter={_ts(110)})"),
            ("ERROR", f"outbound call to {dep or 'upstream'} failed: "
                      "x509: certificate has expired"),
        ],
        "pods": "Running",
        "deploy_culprit": False,
        "pd": f"{svc} TLS certificate expired",
        "keywords": ["certificate", "expired"],
        "root_cause": f"{svc} TLS certificate expired; all downstream "
                      "calls failing handshake",
    }


def _f_disk_full(svc, dep, rng):
    return {
        "alarm_metric": ("FreeStorageSpace", 5.0, 0.3),
        "logs": [
            ("ERROR", "No space left on device: cannot write WAL segment"),
            ("WARN", "disk usage 99.7% on /var/lib/data"),
        ],
        "pods": "Running",
        "deploy_culprit": False,
        "pd": f"{svc} storage exhausted",
        "keywords": ["disk", "space"],
        "root_cause": f"{svc} disk full (WAL/log growth); writes failing "
                      "with ENOSPC",
    }


def _f_cache_stampede(svc, dep, rng):
    return {
        "alarm_metric": ("CacheMisses", 1000, rng.randint(40000, 90000)),
        "logs": [
            ("WARN", "cache hit rate dropped 98% -> 3% after key "
                     "namespace flush"),
            ("ERROR", f"backend {dep or 'db'} latency 40x baseline under "
                      "stampede load"),
        ],
        "pods": "Running",
        "deploy_culprit": False,
        "pd": f"{svc} cache stampede overloading backend",
        "keywords": ["cache", "stampede"],
        "root_cause": f"{svc} cache flush caused a stampede; "
                      f"{dep or 'the backend'} overloaded by miss traffic",
    }


def _f_throttling(svc, dep, rng):
    return {
        "alarm_metric": ("ThrottledRequests", 50, rng.randint(2000, 8000)),
        "logs": [
            ("ERROR", "ThrottlingException: Rate exceeded (quota 1000 rps)"),
            ("WARN", "retry storm: 6.4x request amplification from "
                     "aggressive retries"),
        ],
        "pods": "Running",
        "deploy_culprit": False,
        "pd": f"{svc} hitting provider rate limits",
        "keywords": ["throttl", "quota"],
        "root_cause": f"{svc} exceeding API quota; retry storm amplifying "
                      "throttled traffic",
    }


def _f_crashloop_config(svc, dep, rng):
    key = rng.choice(["DATABASE_URL", "REDIS_ENDPOINT", "OAUTH_ISSUER"])
    return {
        "alarm_metric": ("HealthyHostCount", 2, 0),
        "logs": [
            ("FATAL", f"config error: required key {key} is unset"),
            ("ERROR", "container exited with code 1 during startup"),
        ],
        "pods": "CrashLoopBackOff",
        "deploy_culprit": True,
        "diff_hint": f"config map refactor dropped {key}",
        "pd": f"{svc} pods crashlooping after config change",
        "keywords": ["config", "crashloop"],
        "root_cause": f"config change dropped {key}; {svc} crashloops at "
                      "startup",
    }


def _f_network_partition(svc, dep, rng):
    az = rng.choice(["a", "b", "c"])
    return {
        "alarm_metric": ("TargetConnectionErrorCount", 20, rng.randint(400, 2000)),
        "logs": [
            ("ERROR", f"connect timeout to {dep or 'peer'}:5432 "
                      f"(az-{az} unreachable)"),
            ("WARN", f"50% of cross-az traffic failing in az-{az}"),
        ],
        "pods": "Running",
        "deploy_culprit": False,
        "pd": f"{svc} network errors to {dep or 'backend'} in az-{az}",
        "keywords": ["network", "timeout"],
        "root_cause": f"network partition in az-{az} between {svc} and "
                      f"{dep or 'its backend'}",
    }


def _f_slow_downstream(svc, dep, rng):
    return {
        "alarm_metric": ("TargetResponseTime", 1.5, round(rng.uniform(4, 9), 2)),
        "logs": [
            ("WARN", f"call to {dep or 'downstream'} took 8214ms "
                     "(budget 800ms)"),
            ("ERROR", "request queue saturated: 412 in-flight, shedding "
                      "load"),
        ],
        "pods": "Running",
        "deploy_culprit": False,
        "pd": f"{svc} latency SLO breach",
        "keywords": ["latency", "downstream"],
        "root_cause": f"{dep or 'a downstream dependency'} slowdown "
                      f"saturating {svc}'s request queue",
    }


def _f_dns_failure(svc, dep, rng):
    return {
        "alarm_metric": ("DNSResolutionErrors", 5, rng.randint(100, 900)),
        "logs": [
            ("ERROR", f"getaddrinfo ENOTFOUND {dep or 'internal'}"
                      ".prod.svc.cluster.local"),
            ("WARN", "ndots/resolv.conf misconfiguration after node image "
                     "rollout"),
        ],
        "pods": "Running",
        "deploy_culprit": False,
        "pd": f"{svc} DNS resolution failures",
        "keywords": ["dns", "resolution"],
        "root_cause": f"DNS resolution broken for {svc} after node image "
                      "rollout (resolv.conf misconfiguration)",
    }


FAULT_TYPES: dict[str, Any] = {
    "db_pool_exhaustion": _f_db_pool,
    "memory_leak_oom": _f_oom,
    "bad_deploy_5xx": _f_bad_deploy,
    "cert_expiry": _f_cert_expiry,
    "disk_full": _f_disk_full,
    "cache_stampede": _f_cache_stampede,
    "throttling_quota": _f_throttling,
    "crashloop_bad_config": _f_crashloop_config,
    "network_partition": _f_network_partition,
    "slow_downstream": _f_slow_downstream,
    "dns_failure": _f_dns_failure,
}


# ------------------------------------------------------------- generator

# Adversarial variants (VERDICT r4 next-round #4): the base templates and
# causal_query's patterns were written by the same hand, so a keyword-
# overlap "investigation" can score well without reasoning. These modes
# are built to break that strategy:
#   misleading_symptom — a louder, WRONG-family signal chain on a visible
#     non-culprit service, STALE relative to incident start (the tell a
#     parrot ignores); parroting the loudest log names the decoy and
#     scores 0 on keywords/services.
#   two_fault — an independent second fault on an off-chain service; the
#     paged incident (and scoring) is the primary's, so "found A fault"
#     is not "found THE fault".
#   signal_dropout — a whole telemetry modality is missing, with a meta
#     signal explaining why (broken log shipper / alarm delivery);
#     the answer must be inferred from the remaining modalities.
ADVERSARIAL_MODES = ("misleading_symptom", "two_fault", "signal_dropout")


def generate_scenario(seed: int, fault_type: str | None = None,
                      adversarial: str | None = None) -> Scenario:
    """One seeded scenario: novel topology + fault + full signal chain.

    ``adversarial`` picks a hardening transform from
    :data:`ADVERSARIAL_MODES` (or ``"mix"`` to rotate by seed)."""
    with _gen_lock:
        s = _generate_locked(seed, fault_type)
        if adversarial:
            mode = adversarial
            if mode == "mix":
                mode = ADVERSARIAL_MODES[seed % len(ADVERSARIAL_MODES)]
            if mode not in ADVERSARIAL_MODES:
                raise ValueError(f"unknown adversarial mode {mode!r}; "
                                 f"valid: {ADVERSARIAL_MODES + ('mix',)}")
            rng = random.Random(seed ^ 0xADE5A1)
            s = _ADVERSARIAL[mode](s, rng)
        return s


def _apply_misleading_symptom(s: Scenario, rng: random.Random) -> Scenario:
    """Red-herring signal chain on a non-culprit service.

    The decoy's fault family differs from the real one and its signals
    are LOUDER (bigger alarm value, FATAL logs) but stale: alarm state
    changed hours before the incident, log timestamps predate it, and a
    recovery event closes the story. An agent that checks timestamps
    walks past it; a keyword parrot reports the decoy and scores 0."""
    root = s.truth["root_cause_service"]
    chain = s.truth["chain"]
    decoy = chain[0] if chain[0] != root else (
        chain[1] if len(chain) > 2 and chain[1] != root else chain[-1])
    if decoy == root:  # degenerate 2-chain with root at the edge
        decoy = chain[-1]
    decoy_fault = rng.choice(sorted(set(FAULT_TYPES)
                                    - {s.truth["fault_type"]}))
    f = FAULT_TYPES[decoy_fault](decoy, None, rng)
    metric, threshold, value = f["alarm_metric"]
    stale = 190 + rng.randint(0, 90)  # minutes before the real incident
    s.fixtures["cloudwatch_alarms"].insert(0, {
        "alarmName": f"{decoy}-{metric}", "state": "ALARM",
        "metric": metric, "threshold": threshold,
        # Louder than the real alarm — the parrot's first pick.
        "currentValue": value if not isinstance(value, (int, float))
        else value * 3,
        "stateChangedAt": _ts(stale), "service": decoy})
    s.fixtures["cloudwatch_logs"][f"/ecs/{decoy}"] = [
        {"ts": _ts(stale + 2 + i), "level": "FATAL" if i == 0 else lvl,
         "message": msg}
        for i, (lvl, msg) in enumerate(f["logs"])]
    # The decoy story CLOSES before the incident starts: self-recovery
    # event visible in datadog — the tell that it is history, not cause.
    s.fixtures["datadog"]["events"].append(
        {"ts": _ts(stale - 12), "title": f"{decoy} recovered",
         "tags": [f"service:{decoy}", "auto-recovery"],
         "text": f"{f['pd']} — self-recovered; no action taken"})
    s.truth["adversarial"] = "misleading_symptom"
    s.truth["decoy_service"] = decoy
    s.truth["decoy_fault_type"] = decoy_fault
    s.truth["decoy_keywords"] = f["keywords"]
    return s


def _apply_two_fault(s: Scenario, rng: random.Random) -> Scenario:
    """Independent concurrent fault on an off-chain service.

    Both faults are live RIGHT NOW; only the primary is what the page is
    about (the query and PD incident are unchanged), so naming the
    secondary is finding A fault, not THE fault. Scoring stays anchored
    to the primary's root cause; the secondary rides in truth for
    per-split reporting."""
    chain = s.truth["chain"]
    candidates = sorted((set(_MID) | set(_BACKEND)) - set(chain))
    second_svc = rng.choice(candidates)
    second_fault = rng.choice(sorted(set(FAULT_TYPES)
                                     - {s.truth["fault_type"]}))
    f = FAULT_TYPES[second_fault](second_svc, None, rng)
    metric, threshold, value = f["alarm_metric"]
    start = rng.randint(10, 45)
    s.fixtures["cloudwatch_alarms"].append({
        "alarmName": f"{second_svc}-{metric}", "state": "ALARM",
        "metric": metric, "threshold": threshold, "currentValue": value,
        "stateChangedAt": _ts(start), "service": second_svc})
    s.fixtures["cloudwatch_logs"][f"/ecs/{second_svc}"] = [
        {"ts": _ts(start + 1 + i), "level": lvl, "message": msg}
        for i, (lvl, msg) in enumerate(f["logs"])]
    s.fixtures["kubernetes"]["pods"].append(
        {"name": f"{second_svc}-{rng.randrange(16**6):06x}-0",
         "namespace": "prod", "status": f["pods"],
         "restarts": rng.randint(3, 11) if f["pods"] != "Running" else 0,
         "age": f"{start + 30}m"})
    s.fixtures["aws"]["ecs"].append(
        {"service": second_svc, "status": "ACTIVE",
         "runningCount": 2 if f["pods"] != "Running" else 3,
         "desiredCount": 3, "pendingCount": 0})
    s.truth["adversarial"] = "two_fault"
    s.truth["secondary"] = {"fault_type": second_fault,
                            "service": second_svc,
                            "root_cause": f["root_cause"]}
    return s


def _apply_signal_dropout(s: Scenario, rng: random.Random) -> Scenario:
    """Drop a whole telemetry modality, with a meta signal saying why.

    Logs/alarms/metrics vanish the way they do in real incidents (broken
    shipper, alarm delivery outage) — the investigation must cross to the
    surviving modalities instead of failing on the empty one."""
    root = s.truth["root_cause_service"]
    dropped = rng.choice(("logs", "alarms", "metrics"))
    if dropped == "logs":
        s.fixtures["cloudwatch_logs"].pop(f"/ecs/{root}", None)
        s.fixtures["kubernetes"]["events"].append(
            {"ts": _ts(30), "type": "Warning", "reason": "DaemonSetDegraded",
             "object": "daemonset/fluent-bit",
             "message": f"log shipper degraded on nodes running {root}; "
                        f"/ecs/{root} not receiving entries"})
    elif dropped == "alarms":
        s.fixtures["cloudwatch_alarms"] = []
        s.fixtures["datadog"]["events"].append(
            {"ts": _ts(35), "title": "CloudWatch alarm delivery degraded",
             "tags": ["provider:aws", "alarms"],
             "text": "alarm actions delayed/dropped; rely on raw metrics "
                     "and prometheus alerts"})
    else:
        s.fixtures["datadog"]["metrics"] = {}
        s.fixtures["datadog"]["events"].append(
            {"ts": _ts(35), "title": "datadog agent fleet degraded",
             "tags": ["provider:datadog"],
             "text": "metric intake gap; dashboards empty for ~1h"})
    s.truth["adversarial"] = "signal_dropout"
    s.truth["dropped"] = dropped
    return s


_ADVERSARIAL = {
    "misleading_symptom": _apply_misleading_symptom,
    "two_fault": _apply_two_fault,
    "signal_dropout": _apply_signal_dropout,
}


def _generate_locked(seed: int, fault_type: str | None) -> Scenario:
    rng = random.Random(seed)
    _ts_base[0] = _BASE_EPOCH + rng.randrange(0, 300 * 24 * 3600)
    edge = rng.choice(_EDGE)
    mids = rng.sample(_MID, rng.randint(1, 2))
    backend = rng.choice(_BACKEND)
    chain = [edge, *mids, backend]
    region = rng.choice(_REGIONS)

    fault_name = fault_type or rng.choice(sorted(FAULT_TYPES))
    # Root cause sits mid-chain or at the backend; symptoms propagate up.
    root_idx = rng.randint(1, len(chain) - 1)
    root = chain[root_idx]
    dep = chain[root_idx + 1] if root_idx + 1 < len(chain) else None
    f = FAULT_TYPES[fault_name](root, dep, rng)

    start = rng.randint(18, 70)  # minutes ago
    metric, threshold, value = f["alarm_metric"]

    alarms = [{"alarmName": f"{root}-{metric}", "state": "ALARM",
               "metric": metric, "threshold": threshold,
               "currentValue": value, "stateChangedAt": _ts(start - 2),
               "service": root}]
    logs = {f"/ecs/{root}": [
        {"ts": _ts(start - 3 - i), "level": lvl, "message": msg}
        for i, (lvl, msg) in enumerate(f["logs"])
    ]}
    pods = [{"name": f"{root}-{rng.randrange(16**6):06x}-{j}",
             "namespace": "prod",
             "status": f["pods"] if j == 0 else "Running",
             # Only the faulted pod of a non-Running fault restarts; a
             # healthy-pod fault must not plant a crashloop red herring.
             "restarts": (rng.randint(4, 19)
                          if f["pods"] != "Running" and j == 0 else 0),
             "age": f"{start + 20}m"} for j in range(2)]
    events = [{"ts": _ts(start - 1), "type": "Warning",
               "reason": "Unhealthy" if f["pods"] == "Running" else "BackOff",
               "object": f"pod/{pods[0]['name']}",
               "message": f["logs"][0][1][:90]}]

    # Upstream propagation: every service above the root sees latency.
    for up in chain[:root_idx]:
        alarms.append({"alarmName": f"{up}-TargetResponseTime",
                       "state": "ALARM", "metric": "TargetResponseTime",
                       "threshold": 1.5,
                       "currentValue": round(rng.uniform(3, 8), 2),
                       "stateChangedAt": _ts(start - 4), "service": up})
        logs[f"/ecs/{up}"] = [
            {"ts": _ts(start - 5), "level": "WARN",
             "message": f"upstream call to {chain[chain.index(up) + 1]} "
                        f"timing out ({rng.randint(2, 9)}s)"}]

    healthy = rng.choice(sorted(set(_MID) - set(chain)))
    ecs = [{"service": s, "status": "ACTIVE",
            "runningCount": 2 if s == root and f["pods"] != "Running" else 3,
            "desiredCount": 3, "pendingCount": 0} for s in chain]
    ecs.append({"service": healthy, "status": "ACTIVE", "runningCount": 2,
                "desiredCount": 2, "pendingCount": 0})

    base = rng.randint(200, 400)
    spike = base * rng.randint(8, 20)
    datadog = {
        "metrics": {f"{edge}.request.latency.p99": {
            "unit": "ms",
            "points": [[_ts(start + 30), base], [_ts(start + 15), base + 20],
                       [_ts(start - 10), spike], [_ts(start - 2), spike],
                       [_ts(5), spike - rng.randint(0, 200)]]}},
        "events": [], "monitors": [
            {"name": f"{edge} p99 latency", "status": "Alert",
             "query": f"avg(last_5m):p99:{edge}.latency > 1500"}],
    }
    github = {}
    if f.get("deploy_culprit"):
        pr = rng.randint(1000, 9999)
        datadog["events"].append(
            {"ts": _ts(start + 3), "title": f"Deployed {root}",
             "tags": [f"service:{root}", "env:prod", "deploy"],
             "text": f"change: {f.get('diff_hint', 'config change')} "
                     f"(PR #{pr})"})
        github[root] = [{"number": pr, "title": f.get("diff_hint", "change"),
                         "mergedAt": _ts(start + 8), "author": "dev-x",
                         "files": ["config/app.yaml"],
                         "diff_hint": f.get("diff_hint", "")}]

    incident_id = f"SIM-{seed}"
    fixtures = {
        "aws": {"ecs": ecs, "rds": [], "lambda": [], "ec2": []},
        "cloudwatch_alarms": alarms,
        "cloudwatch_logs": logs,
        "kubernetes": {
            "pods": pods,
            "deployments": [{"name": s, "namespace": "prod",
                             "replicas": "3/3"} for s in chain],
            "events": events,
            "nodes": [{"name": "node-1", "status": "Ready",
                       "cpu": "58%", "memory": "66%"}],
        },
        "datadog": datadog,
        "prometheus": {"alerts": [
            {"name": metric, "state": "firing",
             "labels": {"service": root, "severity": "page"},
             "activeAt": _ts(start - 2)}], "queries": {}},
        "pagerduty": [{"id": incident_id, "title": f["pd"],
                       "status": "triggered", "urgency": "high",
                       "createdAt": _ts(start), "service": edge,
                       "description": f"{f['pd']} in {region}; users "
                                      f"report failures on {edge}",
                       "notes": []}],
        "github": github,
    }
    truth = {
        "fault_type": fault_name,
        "root_cause_service": root,
        "root_cause": f["root_cause"],
        "keywords": f["keywords"],
        "chain": chain,
        "region": region,
        "incident_id": incident_id,
    }
    query = (f"Investigate {incident_id}: {f['pd']} — users report "
             f"failures on {edge} in {region}")
    return Scenario(scenario_id=incident_id, query=query,
                    fixtures=fixtures, truth=truth)


def generate_scenarios(n: int, seed: int = 0,
                       fault_type: str | None = None,
                       adversarial: str | None = None,
                       models: list[str] | None = None) -> list[Scenario]:
    """``models`` assigns each scenario a served model group round-robin
    (deterministic in i, so the same seed+models always produces the
    same assignment) — multi-model fleet runs then exercise the
    model-field routing path on every case."""
    out = [generate_scenario(seed + i, fault_type, adversarial=adversarial)
           for i in range(n)]
    if models:
        for i, s in enumerate(out):
            s.model = models[i % len(models)]
    return out


def to_eval_case(s: Scenario):
    """Scenario → EvalCase (fixtures override + scored ground truth)."""
    from runbookai_tpu.evalsuite.scoring import EvalCase

    return EvalCase(
        case_id=s.scenario_id,
        description=s.query,
        expected_root_cause=s.truth["root_cause"],
        root_cause_keywords=list(s.truth["keywords"]),
        expected_services=[s.truth["root_cause_service"]],
        incident_id=s.scenario_id,
        fixtures=s.fixtures,
        model=s.model,
    )


def write_scenarios(scenarios: list[Scenario], out_dir: str | Path) -> list[Path]:
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    paths = []
    for s in scenarios:
        p = out / f"{s.scenario_id}.json"
        p.write_text(s.to_json())
        paths.append(p)
    return paths
