"""Incident + traffic simulators.

Incident half (``generator.py``): generated fault scenarios for the
fixture providers. Reference parity:
``scripts/simulate/setup-incidents.sh`` provisions real broken
infrastructure (a failing Lambda + forced CloudWatch alarm, optional
live PagerDuty incident) so investigations run against something the
agent has never seen (``docs/SIMULATE_INCIDENTS.md``). This repo's
equivalent is credential-free and TPU-CI-friendly: a seeded generator
perturbs the simulated-provider fixtures (``tools/simulated.py``) into
NOVEL failure states — random topology, random root cause,
fault-specific telemetry — so every e2e investigation faces an incident
that exists in no checked-in fixture, with machine-checkable ground
truth for the eval suite.

Traffic half (``traffic.py``): the seeded serving-workload scenario mix
(short chat, agentic chains, batch floods, shared-prefix sessions,
spiky tenants) the chaos soak gate drives through the full composed
stack — ``bench.py --soak-scenarios`` (docs/robustness.md).
"""

from runbookai_tpu.simulate.generator import (
    ADVERSARIAL_MODES,
    FAULT_TYPES,
    Scenario,
    generate_scenario,
    generate_scenarios,
    to_eval_case,
)
from runbookai_tpu.simulate.traffic import (
    SCENARIO_CLASSES,
    TrafficChain,
    TrafficMix,
    TrafficTurn,
    generate_traffic,
)

__all__ = [
    "ADVERSARIAL_MODES",
    "FAULT_TYPES",
    "SCENARIO_CLASSES",
    "Scenario",
    "TrafficChain",
    "TrafficMix",
    "TrafficTurn",
    "generate_scenario",
    "generate_scenarios",
    "generate_traffic",
    "to_eval_case",
]
