"""Incident simulator: generated fault scenarios for the fixture providers.

Reference parity: ``scripts/simulate/setup-incidents.sh`` provisions real
broken infrastructure (a failing Lambda + forced CloudWatch alarm, optional
live PagerDuty incident) so investigations run against something the agent
has never seen (``docs/SIMULATE_INCIDENTS.md``). This repo's equivalent is
credential-free and TPU-CI-friendly: a seeded generator perturbs the
simulated-provider fixtures (``tools/simulated.py``) into NOVEL failure
states — random topology, random root cause, fault-specific telemetry —
so every e2e investigation faces an incident that exists in no checked-in
fixture, with machine-checkable ground truth for the eval suite.
"""

from runbookai_tpu.simulate.generator import (
    ADVERSARIAL_MODES,
    FAULT_TYPES,
    Scenario,
    generate_scenario,
    generate_scenarios,
    to_eval_case,
)

__all__ = [
    "ADVERSARIAL_MODES",
    "FAULT_TYPES",
    "Scenario",
    "generate_scenario",
    "generate_scenarios",
    "to_eval_case",
]
