"""Seeded serving-traffic scenario mix (ROADMAP item 5's generator half).

``simulate/generator.py`` fabricates *incidents* for the agent to
investigate; this module fabricates the *serving workload* a
million-session deployment actually sees — the mix the chaos soak
(``bench.py --soak-scenarios``) drives through the full composed stack:

``short_chat``
    Single-turn interactive requests, short prompts, streamed — the
    TTFT-sensitive class whose p95 the invariant gate holds through
    every fault.
``agentic_chain``
    Multi-turn tool-call-shaped chains: each turn's prompt carries the
    previous turns' outputs (so a chain is a causal sequence, not N
    independent requests) — the workload agents generate.
``batch_flood``
    A burst of batch-priority single-turn requests landing together —
    the scheduler-fairness pressure case (PR 9's flood protocol).
``shared_prefix_session``
    Multi-turn sessions sharing one long page-aligned system prefix —
    the prefix-cache / kv-share / affinity workload.
``spiky_tenant``
    A tight cluster of interactive requests from one tenant — the
    admission-fairness pressure case.

Everything derives from ``random.Random(seed)``: the same
``(seed, duration_s, …)`` produces a byte-identical :meth:`TrafficMix.
to_json` (pinned by ``tests/test_chaos.py``), prompts included — so a
chaos run and its chaos-free baseline serve the exact same token
streams, and per-chain digests are comparable across runs.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field

SCENARIO_CLASSES = ("short_chat", "agentic_chain", "batch_flood",
                    "shared_prefix_session", "spiky_tenant")

# Tenant names per class (closed set — fairness accounting and metric
# labels in the soak arm stay bounded).
_INTERACTIVE_TENANTS = ("acme", "beta", "gamma")
_BATCH_TENANT = "batchcorp"
_SPIKY_TENANT = "spiky"


@dataclass(frozen=True)
class TrafficTurn:
    """One request within a chain. ``prompt_ids`` is the turn's own
    prompt; an ``agentic_chain`` driver appends the chain's accumulated
    context in front at serve time (``TrafficChain.carry_context``)."""

    prompt_ids: tuple
    max_new_tokens: int
    gap_s: float  # pause before this turn, after the previous finished
    stream: bool

    def to_dict(self) -> dict:
        return {"prompt_ids": list(self.prompt_ids),
                "max_new_tokens": self.max_new_tokens,
                "gap_s": self.gap_s, "stream": self.stream}


@dataclass(frozen=True)
class TrafficChain:
    """One causal request sequence (a chat, a session, an agent run)."""

    chain_id: str
    cls: str
    tenant: str
    at_s: float             # arrival offset from run start
    priority: str           # "interactive" | "batch"
    temperature: float
    seed: int               # sampling seed (deterministic even at T>0)
    carry_context: bool
    turns: tuple = ()
    model: str | None = None

    def to_dict(self) -> dict:
        return {"chain_id": self.chain_id, "cls": self.cls,
                "tenant": self.tenant, "at_s": self.at_s,
                "priority": self.priority,
                "temperature": self.temperature, "seed": self.seed,
                "carry_context": self.carry_context,
                "model": self.model,
                "turns": [t.to_dict() for t in self.turns]}


@dataclass
class TrafficMix:
    seed: int
    duration_s: float
    chains: list = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "duration_s": self.duration_s,
             "chains": [c.to_dict() for c in self.chains]},
            indent=2, sort_keys=True)

    def by_class(self) -> dict:
        counts: dict[str, int] = {}
        for c in self.chains:
            counts[c.cls] = counts.get(c.cls, 0) + 1
        return dict(sorted(counts.items()))


def _prompt(rng: random.Random, n: int) -> tuple:
    """Byte-vocabulary prompt ids (the bench harness serves the byte
    tokenizer; real deployments swap prompts, not the mix shape)."""
    return tuple(rng.randrange(0, 256) for _ in range(n))


def generate_traffic(seed: int, duration_s: float, *,
                     classes: tuple = SCENARIO_CLASSES,
                     chains_per_minute: float = 120.0,
                     prompt_scale: float = 1.0,
                     max_new_scale: float = 1.0,
                     models: list | None = None) -> TrafficMix:
    """Deterministic scenario mix for a ``duration_s`` window.

    Arrivals land in the first 80% of the window (tails must finish
    inside the measured run). Every requested class appears at least
    once; beyond that the mix is sampled with interactive-heavy weights.
    ``prompt_scale`` / ``max_new_scale`` shrink the token volumes for
    CPU smokes. ``models`` assigns chains to served model groups
    round-robin (deterministic in chain index), like
    ``generate_scenarios``."""
    unknown = set(classes) - set(SCENARIO_CLASSES)
    if unknown:
        raise ValueError(f"unknown scenario classes {sorted(unknown)}; "
                         f"valid: {SCENARIO_CLASSES}")
    if not classes:
        raise ValueError("at least one scenario class is required")
    rng = random.Random(seed)
    n = max(len(classes),
            int(duration_s * chains_per_minute / 60.0))
    # Interactive-heavy sampling weights; every class floor-guaranteed.
    weights = {"short_chat": 5, "agentic_chain": 2, "batch_flood": 1,
               "shared_prefix_session": 2, "spiky_tenant": 1}
    picks = list(classes)
    pool = [c for c in classes for _ in range(weights[c])]
    while len(picks) < n:
        picks.append(pool[rng.randrange(len(pool))])
    # One shared session prefix per mix (page-aligned at the bench's
    # page_size=16): every shared_prefix_session chain reuses it.
    shared_prefix = _prompt(rng, max(16, int(64 * prompt_scale) // 16 * 16))

    def plen(lo: int, hi: int) -> int:
        return max(4, int(rng.randint(lo, hi) * prompt_scale))

    def new_toks(lo: int, hi: int) -> int:
        return max(2, int(rng.randint(lo, hi) * max_new_scale))

    chains: list[TrafficChain] = []
    idx = 0

    def add(cls: str, at_s: float, tenant: str, priority: str,
            turns: list, *, temperature: float = 0.0,
            carry: bool = False) -> None:
        nonlocal idx
        chains.append(TrafficChain(
            chain_id=f"c{idx:04d}-{cls}", cls=cls, tenant=tenant,
            at_s=round(at_s, 3), priority=priority,
            temperature=temperature, seed=seed * 10_000 + idx,
            carry_context=carry, turns=tuple(turns),
            model=(models[idx % len(models)] if models else None)))
        idx += 1

    horizon = duration_s * 0.8
    for cls in picks:
        at = rng.random() * horizon
        if cls == "short_chat":
            tenant = _INTERACTIVE_TENANTS[
                rng.randrange(len(_INTERACTIVE_TENANTS))]
            # A third of chats sample at temperature with a pinned seed:
            # the digest-determinism gate must cover seeded sampling,
            # not just greedy.
            temp = 0.8 if rng.random() < 0.33 else 0.0
            add(cls, at, tenant, "interactive",
                [TrafficTurn(_prompt(rng, plen(16, 48)),
                             new_toks(8, 16), 0.0, stream=True)],
                temperature=temp)
        elif cls == "agentic_chain":
            tenant = _INTERACTIVE_TENANTS[
                rng.randrange(len(_INTERACTIVE_TENANTS))]
            turns = [TrafficTurn(_prompt(rng, plen(24, 64)),
                                 new_toks(8, 24),
                                 0.0 if t == 0
                                 else round(rng.uniform(0.01, 0.05), 3),
                                 stream=False)
                     for t in range(rng.randint(3, 5))]
            add(cls, at, tenant, "interactive", turns, carry=True)
        elif cls == "batch_flood":
            # A burst of independent single-turn batch chains at one
            # arrival instant.
            for _ in range(rng.randint(3, 6)):
                add(cls, at, _BATCH_TENANT, "batch",
                    [TrafficTurn(_prompt(rng, plen(32, 96)),
                                 new_toks(16, 32), 0.0, stream=False)])
        elif cls == "shared_prefix_session":
            tenant = _INTERACTIVE_TENANTS[
                rng.randrange(len(_INTERACTIVE_TENANTS))]
            turns = [TrafficTurn(
                shared_prefix + _prompt(rng, plen(8, 24)),
                new_toks(6, 12),
                0.0 if t == 0 else round(rng.uniform(0.01, 0.04), 3),
                stream=True)
                for t in range(rng.randint(2, 4))]
            add(cls, at, tenant, "interactive", turns)
        else:  # spiky_tenant
            for k in range(rng.randint(3, 6)):
                add(cls, at + k * 0.01, _SPIKY_TENANT, "interactive",
                    [TrafficTurn(_prompt(rng, plen(12, 32)),
                                 new_toks(4, 10), 0.0, stream=False)])
    chains.sort(key=lambda c: (c.at_s, c.chain_id))
    return TrafficMix(seed=seed, duration_s=duration_s, chains=chains)
