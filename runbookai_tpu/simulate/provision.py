"""Real-infrastructure incident mode: the ``--with-aws`` seam.

The reference's simulator can provision ACTUAL broken AWS resources and
open a live PagerDuty incident (``scripts/simulate/setup-incidents.sh:1-624``,
``docs/SIMULATE_INCIDENTS.md``). This repo's simulator is fixtures-first
by design (credential-free, deterministic ground truth); this module is
the documented landing point for the real-infra mode (VERDICT r4
next-round #8): it maps every generated fault family onto a concrete
break/observe/teardown recipe over boto3, prints it as a dry-run plan
offline, and refuses gracefully — with the exact reason — when no AWS
credentials are available or a step still needs operator input.

    runbook simulate provision scenario.json            # plan (offline ok)
    runbook simulate provision scenario.json --apply    # needs credentials

Safety model (stated precisely, not aspirationally):

- The CLI prints the FULL plan — teardown steps first — before anything
  executes, so an interrupted apply is always reversible by hand.
- Resources the recipe CREATES carry the ``runbook-simulate=<id>`` tag.
  Steps that MUTATE pre-existing resources by name cannot be tag-scoped;
  ``render()`` marks each of them ``[mutates existing]`` so the operator
  can audit the blast radius before ``--apply``.
- Steps with site-specific inputs (certificate bodies, instance ids,
  original security groups) carry ``needs``; apply REFUSES while any
  remain unresolved rather than crashing boto3 mid-recipe.
- ``apply_plan`` executes step-by-step and reports exactly how many steps
  landed on failure, pointing back at the teardown plan.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class ProvisionStep:
    service: str
    action: str
    params: dict[str, Any]
    purpose: str
    creates: bool = False  # True: makes a tagged resource; False: mutates
    needs: tuple[str, ...] = ()  # operator inputs required before apply

    def describe(self) -> str:
        marks = []
        if not self.creates:
            marks.append("[mutates existing]")
        if self.needs:
            marks.append(f"[needs: {', '.join(self.needs)}]")
        suffix = (" " + " ".join(marks)) if marks else ""
        return (f"{self.service}:{self.action} {self.params} — "
                f"{self.purpose}{suffix}")


@dataclass
class ProvisionPlan:
    scenario_id: str
    fault_type: str
    break_steps: list[ProvisionStep] = field(default_factory=list)
    teardown_steps: list[ProvisionStep] = field(default_factory=list)

    def unresolved(self) -> list[str]:
        return [f"{s.service}:{s.action} needs {', '.join(s.needs)}"
                for s in self.break_steps if s.needs]

    def render(self) -> str:
        lines = [f"provision plan for {self.scenario_id} "
                 f"({self.fault_type}) — created resources tagged "
                 f"runbook-simulate={self.scenario_id}"]
        lines.append("  teardown (run these to undo, in order):")
        for s in self.teardown_steps:
            lines.append(f"    {s.describe()}")
        lines.append("  break:")
        for s in self.break_steps:
            lines.append(f"    {s.describe()}")
        return "\n".join(lines)


def _tag(scenario_id: str) -> list[dict]:
    return [{"Key": "runbook-simulate", "Value": scenario_id}]


def provision_plan(scenario) -> ProvisionPlan:
    """Map a generated scenario onto real-AWS break/teardown steps.

    Each fault family gets the smallest real mutation that reproduces its
    signal chain (mirroring setup-incidents.sh's scenarios: broken
    security group, broken task revision, expired-cert import, throttled
    table, clamped connection pool)."""
    root = scenario.truth["root_cause_service"]
    sid = scenario.scenario_id
    fault = scenario.truth["fault_type"]
    p = ProvisionPlan(scenario_id=sid, fault_type=fault)

    def step(lst, _svc, _action, _purpose, _creates=False, _needs=(),
             **params):
        lst.append(ProvisionStep(_svc, _action, params, _purpose,
                                 creates=_creates, needs=tuple(_needs)))

    if fault in ("db_pool_exhaustion", "slow_downstream", "cache_stampede"):
        step(p.break_steps, "rds", "modify_db_parameter_group",
             "clamp max_connections so the pool exhausts under load",
             _needs=("DBParameterGroupName of the live instance",),
             Parameters=[{"ParameterName": "max_connections",
                          "ParameterValue": "8",
                          "ApplyMethod": "immediate"}])
        step(p.teardown_steps, "rds", "reset_db_parameter_group",
             "restore engine-default max_connections",
             _needs=("DBParameterGroupName of the live instance",),
             ResetAllParameters=True)
    elif fault in ("memory_leak_oom", "crashloop_bad_config",
                   "bad_deploy_5xx"):
        step(p.break_steps, "ecs", "register_task_definition",
             "register a broken revision (bad env/limits)", _creates=True,
             family=f"{root}-sim", memory="128",
             containerDefinitions=[{"name": root, "memory": 128,
                                    "environment": [
                                        {"name": "SIM_FAULT",
                                         "value": fault}]}],
             tags=[{"key": "runbook-simulate", "value": sid}])
        step(p.break_steps, "ecs", "update_service",
             "point the service at the broken revision",
             _needs=("cluster name",),
             service=root, taskDefinition=f"{root}-sim")
        step(p.teardown_steps, "ecs", "update_service",
             "roll back to the previous task definition",
             _needs=("cluster name", "previous taskDefinition revision"),
             service=root)
        step(p.teardown_steps, "ecs", "deregister_task_definition",
             "remove the broken revision",
             _needs=("broken revision ARN from the apply output",))
    elif fault == "cert_expiry":
        step(p.break_steps, "acm", "import_certificate",
             "import an already-expired certificate onto the listener",
             _creates=True,
             _needs=("Certificate/PrivateKey PEM of an expired cert",
                     "listener ARN to swap"),
             Tags=_tag(sid))
        step(p.teardown_steps, "elbv2", "modify_listener",
             "restore the valid certificate on the listener",
             _needs=("listener ARN", "original certificate ARN"))
        step(p.teardown_steps, "acm", "delete_certificate",
             "remove the expired certificate",
             _needs=("imported certificate ARN from the apply output",))
    elif fault == "disk_full":
        step(p.break_steps, "ssm", "send_command",
             "fallocate a file filling the data volume to >95%",
             _needs=("InstanceIds of the service hosts",),
             DocumentName="AWS-RunShellScript",
             Parameters={"commands": [
                 f"fallocate -l 95% /var/data/runbook-sim-{sid}.fill"]})
        step(p.teardown_steps, "ssm", "send_command",
             "remove the fill file",
             _needs=("InstanceIds of the service hosts",),
             DocumentName="AWS-RunShellScript",
             Parameters={"commands": [
                 f"rm -f /var/data/runbook-sim-{sid}.fill"]})
    elif fault == "throttling_quota":
        step(p.break_steps, "dynamodb", "update_table",
             "drop provisioned throughput to 1 RCU/WCU",
             TableName=f"{root}-table",
             ProvisionedThroughput={"ReadCapacityUnits": 1,
                                    "WriteCapacityUnits": 1})
        step(p.break_steps, "dynamodb", "tag_resource",
             "tag the throttled table for audit",
             _needs=("table ARN",), Tags=_tag(sid))
        step(p.teardown_steps, "dynamodb", "update_table",
             "restore provisioned throughput",
             _needs=("original RCU/WCU from the apply output",),
             TableName=f"{root}-table")
    elif fault in ("network_partition", "dns_failure"):
        step(p.break_steps, "ec2", "create_security_group",
             "empty security group (denies everything) for the partition",
             _creates=True, _needs=("VpcId",),
             GroupName=f"runbook-sim-{sid}",
             Description="simulated partition",
             TagSpecifications=[{"ResourceType": "security-group",
                                 "Tags": _tag(sid)}])
        step(p.break_steps, "ec2", "modify_instance_attribute",
             "swap the service's instances onto the deny-all group",
             _needs=("InstanceId per host", "deny-all group id from step 1"))
        step(p.teardown_steps, "ec2", "modify_instance_attribute",
             "restore the original security groups",
             _needs=("InstanceId per host", "original group ids"))
        step(p.teardown_steps, "ec2", "delete_security_group",
             "delete the deny-all group",
             _needs=("deny-all group id from the apply output",))
    else:  # future families land here explicitly, not silently
        raise ValueError(f"no real-infra recipe for fault {fault!r}")
    return p


def aws_credentials_available() -> Optional[str]:
    """Return the credential source name, or None when boto3 has nothing
    to sign with (the graceful-refusal path)."""
    try:
        import botocore.session

        creds = botocore.session.Session().get_credentials()
        return getattr(creds, "method", "static") if creds else None
    except Exception:  # noqa: BLE001 — no botocore == no credentials
        return None


def apply_plan(plan: ProvisionPlan,
               resolutions: Optional[dict[str, dict[str, Any]]] = None
               ) -> str:
    """Execute the break steps. Callers print ``plan.render()`` FIRST.

    ``resolutions`` maps ``"service:action"`` to extra boto3 params that
    resolve a step's ``needs`` (cluster names, instance ids, PEM bodies).
    Refuses — before touching anything — while credentials are missing or
    any step's needs are unresolved; on a mid-apply failure, reports how
    many steps landed so the printed teardown plan can be applied by hand.
    """
    source = aws_credentials_available()
    if source is None:
        return ("refused: no AWS credentials available (configure a "
                "profile or role; the plan above is what --apply would "
                "execute)")
    resolutions = resolutions or {}
    unresolved = [u for s in plan.break_steps if s.needs
                  and f"{s.service}:{s.action}" not in resolutions
                  for u in [f"{s.service}:{s.action} needs "
                            f"{', '.join(s.needs)}"]]
    if unresolved:
        return ("refused: steps need operator input (pass --resolve "
                "service:action key=value):\n  " + "\n  ".join(unresolved))
    import boto3

    executed = 0
    try:
        for s in plan.break_steps:
            params = dict(s.params)
            params.update(resolutions.get(f"{s.service}:{s.action}", {}))
            getattr(boto3.client(s.service), s.action)(**params)
            executed += 1
    except Exception as exc:  # noqa: BLE001 — partial apply must report
        return (f"FAILED on break step {executed + 1}/"
                f"{len(plan.break_steps)} ({exc}); {executed} steps were "
                f"applied — run the teardown plan printed above to "
                f"restore")
    return (f"applied {executed} break steps via {source}; run the "
            f"teardown plan printed above to restore when done")


def provision(scenario, apply: bool = False) -> tuple[ProvisionPlan, str]:
    """Plan (always) + apply gate; kept for library callers. The CLI
    prints ``plan.render()`` before invoking :func:`apply_plan` so the
    teardown recipe is on screen before any mutation."""
    plan = provision_plan(scenario)
    if not apply:
        return plan, "dry-run (pass --apply with AWS credentials to execute)"
    return plan, apply_plan(plan)
