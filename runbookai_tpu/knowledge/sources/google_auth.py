"""Google OAuth2 token management for the Drive knowledge source.

Parity target: reference ``src/knowledge/sources/google-auth.ts`` —
authorization-URL construction (:38), code→token exchange (:179), refresh
(:224), and token persistence used by ``runbook knowledge auth google``.
The local-callback-server browser flow (:107) is collapsed to a paste-the-code
flow here (terminal-first; no browser automation in this environment); the
exchange/refresh HTTP goes through the injectable ``fetch`` contract.
"""

from __future__ import annotations

import json
import os
import time
import urllib.parse
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Optional

Fetch = Callable[[str, dict[str, str], bytes], tuple[int, bytes]]

AUTH_ENDPOINT = "https://accounts.google.com/o/oauth2/v2/auth"
TOKEN_ENDPOINT = "https://oauth2.googleapis.com/token"
SCOPE = "https://www.googleapis.com/auth/drive.readonly"
OOB_REDIRECT = "urn:ietf:wg:oauth:2.0:oob"


def default_post(url: str, headers: dict[str, str], body: bytes) -> tuple[int, bytes]:
    import urllib.request

    req = urllib.request.Request(url, data=body, headers=headers, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:  # pragma: no cover
            return resp.status, resp.read()
    except urllib.error.HTTPError as err:  # pragma: no cover - network path
        return err.code, err.read()


@dataclass
class GoogleTokens:
    access_token: str = ""
    refresh_token: str = ""
    expires_at: float = 0.0
    token_type: str = "Bearer"
    extra: dict = field(default_factory=dict)

    @property
    def expired(self) -> bool:
        return bool(self.access_token) and time.time() >= self.expires_at - 60

    def to_dict(self) -> dict:
        return {"access_token": self.access_token,
                "refresh_token": self.refresh_token,
                "expires_at": self.expires_at,
                "token_type": self.token_type}

    @classmethod
    def from_dict(cls, data: dict) -> "GoogleTokens":
        return cls(access_token=data.get("access_token", ""),
                   refresh_token=data.get("refresh_token", ""),
                   expires_at=float(data.get("expires_at", 0)),
                   token_type=data.get("token_type", "Bearer"))


def authorization_url(client_id: str, redirect_uri: str = OOB_REDIRECT) -> str:
    params = {
        "client_id": client_id,
        "redirect_uri": redirect_uri,
        "response_type": "code",
        "scope": SCOPE,
        "access_type": "offline",
        "prompt": "consent",
    }
    return f"{AUTH_ENDPOINT}?{urllib.parse.urlencode(params)}"


def _token_request(params: dict[str, str], post: Fetch) -> GoogleTokens:
    body = urllib.parse.urlencode(params).encode()
    status, resp = post(TOKEN_ENDPOINT,
                        {"Content-Type": "application/x-www-form-urlencoded"},
                        body)
    if status != 200:
        raise RuntimeError(f"google token endpoint: HTTP {status}: "
                           f"{resp.decode(errors='replace')[:200]}")
    data = json.loads(resp.decode())
    return GoogleTokens(
        access_token=data.get("access_token", ""),
        refresh_token=data.get("refresh_token", params.get("refresh_token", "")),
        expires_at=time.time() + float(data.get("expires_in", 3600)),
        token_type=data.get("token_type", "Bearer"),
        extra=data,
    )


def exchange_code(client_id: str, client_secret: str, code: str,
                  redirect_uri: str = OOB_REDIRECT,
                  post: Fetch = default_post) -> GoogleTokens:
    return _token_request({
        "client_id": client_id, "client_secret": client_secret,
        "code": code, "grant_type": "authorization_code",
        "redirect_uri": redirect_uri,
    }, post)


def refresh_tokens(client_id: str, client_secret: str, refresh_token: str,
                   post: Fetch = default_post) -> GoogleTokens:
    return _token_request({
        "client_id": client_id, "client_secret": client_secret,
        "refresh_token": refresh_token, "grant_type": "refresh_token",
    }, post)


class TokenStore:
    """Persist tokens under ``.runbook/google-tokens.json`` (0600)."""

    def __init__(self, path: str | Path = ".runbook/google-tokens.json"):
        self.path = Path(path)

    def load(self) -> Optional[GoogleTokens]:
        if not self.path.exists():
            return None
        try:
            return GoogleTokens.from_dict(json.loads(self.path.read_text()))
        except (json.JSONDecodeError, OSError):
            return None

    def save(self, tokens: GoogleTokens) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        # Create with the final 0600 mode — never world-readable, even briefly.
        fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(json.dumps(tokens.to_dict(), indent=2))
        self.path.chmod(0o600)  # repair pre-existing files too


def valid_access_token(store: TokenStore, client_id: str, client_secret: str,
                       post: Fetch = default_post) -> Optional[str]:
    """Stored token, refreshed if expired; None if auth never completed."""
    tokens = store.load()
    if tokens is None or not tokens.access_token:
        return None
    if tokens.expired and tokens.refresh_token:
        tokens = refresh_tokens(client_id, client_secret,
                                tokens.refresh_token, post=post)
        store.save(tokens)
    return tokens.access_token
