"""Minimal HTML → markdown conversion for remote knowledge sources.

Parity target: the HTML→markdown step of the reference Confluence loader
(``src/knowledge/sources/confluence.ts`` ``convertConfluenceToMarkdown``),
which flattens Confluence "storage format" (XHTML) into headed markdown that
the section chunker (`chunker.py`) can split. Implemented on the stdlib
``html.parser`` — no external deps.
"""

from __future__ import annotations

from html.parser import HTMLParser

_HEADINGS = {f"h{i}": "#" * i for i in range(1, 7)}
_SKIP = {"script", "style", "head"}


class _Converter(HTMLParser):
    def __init__(self) -> None:
        super().__init__(convert_charrefs=True)
        self.out: list[str] = []
        self._skip_depth = 0
        self._list_stack: list[str] = []  # "ul" | "ol"
        self._ol_counters: list[int] = []
        self._in_pre = False
        self._cell_buf: list[str] | None = None
        self._row: list[str] = []
        self._table_rows: list[list[str]] = []
        self._in_table = False
        self._href: str | None = None
        self._link_text: list[str] = []

    # -- helpers ---------------------------------------------------------
    def _emit(self, text: str) -> None:
        # An open link captures text first — even inside a table cell — so
        # </a> can rebuild [text](href) into whatever encloses the link.
        if self._href is not None:
            self._link_text.append(text)
        elif self._cell_buf is not None:
            self._cell_buf.append(text)
        else:
            self.out.append(text)

    def _newline(self, n: int = 1) -> None:
        if self._cell_buf is not None:
            return
        self.out.append("\n" * n)

    def _buf(self) -> list[str]:
        if self._href is not None:
            return self._link_text
        if self._cell_buf is not None:
            return self._cell_buf
        return self.out

    def _close_inline(self, marker: str) -> None:
        """Close ** / * / ` flush against the wrapped text, not its space."""
        buf = self._buf()
        if buf and buf[-1].endswith(" "):
            buf[-1] = buf[-1][:-1]
            buf.append(marker + " ")
        else:
            buf.append(marker)

    # -- parser hooks ----------------------------------------------------
    def handle_starttag(self, tag, attrs):
        if tag in _SKIP:
            self._skip_depth += 1
            return
        attrs = dict(attrs)
        if tag in _HEADINGS:
            self._newline(2)
            self._emit(_HEADINGS[tag] + " ")
        elif tag == "p":
            self._newline(2)
        elif tag == "br":
            self._newline()
        elif tag in ("ul", "ol"):
            self._list_stack.append(tag)
            if tag == "ol":
                self._ol_counters.append(0)
            self._newline()
        elif tag == "li":
            self._newline()
            indent = "  " * (len(self._list_stack) - 1)
            if self._list_stack and self._list_stack[-1] == "ol":
                self._ol_counters[-1] += 1
                self._emit(f"{indent}{self._ol_counters[-1]}. ")
            else:
                self._emit(f"{indent}- ")
        elif tag == "pre":
            self._in_pre = True
            self._newline(2)
            self._emit("```\n")
        elif tag == "code" and not self._in_pre:
            self._emit("`")
        elif tag in ("strong", "b"):
            self._emit("**")
        elif tag in ("em", "i"):
            self._emit("*")
        elif tag == "a":
            self._href = attrs.get("href", "")
            self._link_text = []
        elif tag == "table":
            self._in_table = True
            self._table_rows = []
        elif tag == "tr":
            self._row = []
        elif tag in ("td", "th"):
            self._cell_buf = []
        elif tag == "hr":
            self._newline(2)
            self._emit("---")
            self._newline()

    def handle_endtag(self, tag):
        if tag in _SKIP:
            self._skip_depth = max(0, self._skip_depth - 1)
            return
        if tag in _HEADINGS or tag == "p":
            self._newline()
        elif tag in ("ul", "ol"):
            if self._list_stack:
                popped = self._list_stack.pop()
                if popped == "ol" and self._ol_counters:
                    self._ol_counters.pop()
            self._newline()
        elif tag == "pre":
            self._in_pre = False
            self._emit("\n```")
            self._newline(2)
        elif tag == "code" and not self._in_pre:
            self._close_inline("`")
        elif tag in ("strong", "b"):
            self._close_inline("**")
        elif tag in ("em", "i"):
            self._close_inline("*")
        elif tag == "a":
            text = "".join(self._link_text).strip()
            href = self._href or ""
            self._href = None
            self._link_text = []
            target = self._cell_buf if self._cell_buf is not None else self.out
            if text and href and not href.startswith("#"):
                target.append(f"[{text}]({href})")
            else:
                target.append(text)
        elif tag in ("td", "th"):
            self._row.append(" ".join("".join(self._cell_buf or []).split()))
            self._cell_buf = None
        elif tag == "tr":
            if self._row:
                self._table_rows.append(self._row)
            self._row = []
        elif tag == "table":
            self._in_table = False
            self._emit_table()

    def handle_data(self, data):
        if self._skip_depth:
            return
        if self._in_pre:
            self._emit(data)
        else:
            text = " ".join(data.split())
            if text:
                buf = self._buf()
                prev = buf[-1] if buf else ""
                # Whitespace between elements is collapsed, not dropped:
                # "</a> more" keeps its separating space ("[x](u) more").
                if data[:1].isspace() and prev and not prev[-1].isspace():
                    text = " " + text
                self._emit(text + " " if not self._in_table or self._cell_buf is not None else text)

    def _emit_table(self) -> None:
        if not self._table_rows:
            return
        self._newline(2)
        header, *rows = self._table_rows
        width = max(len(header), *(len(r) for r in rows)) if rows else len(header)
        header += [""] * (width - len(header))
        self.out.append("| " + " | ".join(header) + " |\n")
        self.out.append("|" + "---|" * width + "\n")
        for row in rows:
            row = row + [""] * (width - len(row))
            self.out.append("| " + " | ".join(row) + " |\n")
        self._newline()


def html_to_markdown(html: str) -> str:
    parser = _Converter()
    parser.feed(html)
    parser.close()
    text = "".join(parser.out)
    # Collapse runs of blank lines and trailing space.
    lines = [ln.rstrip() for ln in text.split("\n")]
    out: list[str] = []
    for ln in lines:
        if ln == "" and out and out[-1] == "":
            continue
        out.append(ln)
    return "\n".join(out).strip()
