"""Google Drive knowledge source.

Parity target: reference ``src/knowledge/sources/google-drive.ts`` —
``loadFromGoogleDrive`` (:45): folder listing with pagination + recursive
subfolder traversal (:101-180), supported-type filtering (:187), Google Docs
exported as text, Sheets exported as CSV and rendered to markdown tables,
plain markdown/text downloaded raw (:202-224), incremental sync via
``modifiedTime``. OAuth token plumbing lives in ``google_auth.py``
(reference ``google-auth.ts``).

Networking goes through the same injectable ``fetch`` contract as the
Confluence source so tests are hermetic and zero-egress builds can gate it.
"""

from __future__ import annotations

import csv
import io
import json
import time
import urllib.parse
from typing import Any, Callable, Optional

from runbookai_tpu.knowledge.chunker import chunk_markdown, document_from_markdown
from runbookai_tpu.knowledge.sources.confluence import _parse_iso, default_fetch
from runbookai_tpu.knowledge.types import KnowledgeDocument

Fetch = Callable[[str, dict[str, str]], tuple[int, bytes]]

DRIVE_API = "https://www.googleapis.com/drive/v3"
FOLDER_MIME = "application/vnd.google-apps.folder"
DOC_MIME = "application/vnd.google-apps.document"
SHEET_MIME = "application/vnd.google-apps.spreadsheet"
SUPPORTED_MIMES = (DOC_MIME, SHEET_MIME, "text/markdown", "text/plain")

_FILE_FIELDS = ("nextPageToken,files(id,name,mimeType,modifiedTime,"
                "createdTime,description,properties,parents,webViewLink)")


def csv_to_markdown_table(text: str) -> str:
    """Sheets CSV export → markdown table (google-drive.ts Sheets path)."""
    rows = [row for row in csv.reader(io.StringIO(text)) if any(row)]
    if not rows:
        return ""
    width = max(len(r) for r in rows)
    rows = [r + [""] * (width - len(r)) for r in rows]
    header, *body = rows
    out = ["| " + " | ".join(header) + " |", "|" + "---|" * width]
    out += ["| " + " | ".join(r) + " |" for r in body]
    return "\n".join(out)


class GoogleDriveSource:
    """Recursive folder loader over the Drive v3 REST API."""

    def __init__(
        self,
        folder_ids: list[str],
        access_token: str,
        name: str = "google-drive",
        mime_types: Optional[list[str]] = None,
        fetch: Fetch = default_fetch,
    ):
        self.folder_ids = folder_ids
        self.name = name
        self.mime_types = mime_types
        self.fetch = fetch
        self.headers = {"Authorization": f"Bearer {access_token}",
                        "Accept": "application/json"}

    # -- listing ---------------------------------------------------------
    def _get(self, url: str) -> tuple[int, bytes]:
        return self.fetch(url, self.headers)

    def _list_folder(self, folder_id: str) -> list[dict[str, Any]]:
        files: list[dict[str, Any]] = []
        subfolders: list[str] = []
        page_token = ""
        query = f"'{folder_id}' in parents and trashed = false"
        while True:
            params = {"q": query, "fields": _FILE_FIELDS, "pageSize": "100"}
            if page_token:
                params["pageToken"] = page_token
            status, body = self._get(f"{DRIVE_API}/files?"
                                     + urllib.parse.urlencode(params))
            if status != 200:
                raise RuntimeError(f"drive list failed: HTTP {status}")
            data = json.loads(body.decode())
            for file in data.get("files", []):
                mime = file.get("mimeType", "")
                if mime == FOLDER_MIME:
                    subfolders.append(file["id"])
                elif self.mime_types and mime not in self.mime_types:
                    continue
                elif mime in SUPPORTED_MIMES:
                    files.append(file)
            page_token = data.get("nextPageToken", "")
            if not page_token:
                break
        for sub in subfolders:
            files.extend(self._list_folder(sub))
        return files

    # -- content ---------------------------------------------------------
    def _export(self, file_id: str, mime: str) -> str:
        url = (f"{DRIVE_API}/files/{file_id}/export?"
               + urllib.parse.urlencode({"mimeType": mime}))
        status, body = self._get(url)
        if status != 200:
            raise RuntimeError(f"drive export failed: HTTP {status}")
        return body.decode(errors="replace")

    def _download(self, file_id: str) -> str:
        status, body = self._get(f"{DRIVE_API}/files/{file_id}?alt=media")
        if status != 200:
            raise RuntimeError(f"drive download failed: HTTP {status}")
        return body.decode(errors="replace")

    def _to_document(self, file: dict[str, Any]) -> KnowledgeDocument:
        file_id = str(file["id"])
        mime = file.get("mimeType", "")
        title = str(file.get("name") or file_id)
        if mime == DOC_MIME:
            content = self._export(file_id, "text/plain")
        elif mime == SHEET_MIME:
            content = csv_to_markdown_table(self._export(file_id, "text/csv"))
        else:
            content = self._download(file_id)
        properties = file.get("properties") or {}
        doc = document_from_markdown(file_id, content, source=self.name,
                                     default_title=title)
        # Drive file properties override/augment frontmatter metadata.
        if properties.get("type"):
            doc.knowledge_type = str(properties["type"])
        if properties.get("services"):
            doc.services = [s.strip() for s in
                            str(properties["services"]).split(",") if s.strip()]
        doc.updated_at = _parse_iso(file.get("modifiedTime", "")) or time.time()
        doc.chunks = chunk_markdown(doc.doc_id, doc.content)
        return doc

    def load(self, since: Optional[float] = None) -> list[KnowledgeDocument]:
        docs = []
        for folder_id in self.folder_ids:
            for file in self._list_folder(folder_id):
                modified = _parse_iso(file.get("modifiedTime", ""))
                if since is not None and modified and modified <= since:
                    continue
                try:
                    docs.append(self._to_document(file))
                except Exception:
                    continue  # one bad file must not abort the sync
        return docs
