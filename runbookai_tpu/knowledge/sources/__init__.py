"""Knowledge source loaders + dispatcher.

Parity target: reference ``src/knowledge/sources/index.ts`` —
``loadFromSource`` (:19) routes a per-source config union to the right
loader (filesystem | confluence | google-drive). Each loader returns
``KnowledgeDocument``s with chunks; incremental sync is expressed by the
``since`` epoch argument (reference ``lastSyncTime``).
"""

from __future__ import annotations

import os
from typing import Any, Optional

from runbookai_tpu.knowledge.types import KnowledgeDocument


def build_source(src_config: Any, fetch: Any = None) -> Optional[Any]:
    """Config row → source object with a ``load(since)`` method."""
    kind = getattr(src_config, "type", "filesystem")
    if kind == "filesystem" and getattr(src_config, "path", None):
        from runbookai_tpu.knowledge.retriever import FilesystemSource

        return FilesystemSource(src_config.path, name=src_config.name)
    if kind == "confluence" and getattr(src_config, "base_url", None):
        from runbookai_tpu.knowledge.sources.confluence import (
            ConfluenceSource,
            default_fetch,
        )

        return ConfluenceSource(
            base_url=src_config.base_url,
            space_key=src_config.space or "",
            email=os.environ.get("CONFLUENCE_EMAIL", ""),
            api_token=src_config.token or os.environ.get("CONFLUENCE_API_TOKEN", ""),
            labels=list(src_config.labels),
            name=src_config.name,
            fetch=fetch or default_fetch,
        )
    if kind == "google-drive" and getattr(src_config, "folder_id", None):
        from runbookai_tpu.knowledge.sources.confluence import default_fetch
        from runbookai_tpu.knowledge.sources.google_auth import (
            TokenStore,
            valid_access_token,
        )
        from runbookai_tpu.knowledge.sources.google_drive import GoogleDriveSource

        token = src_config.token
        if not token:
            try:
                token = valid_access_token(
                    TokenStore(),
                    os.environ.get("GOOGLE_CLIENT_ID", ""),
                    os.environ.get("GOOGLE_CLIENT_SECRET", ""),
                )
            except RuntimeError:
                token = None  # refresh failed (revoked/offline)
        if not token:
            return None  # auth not completed; sync skips this source
        return GoogleDriveSource(
            folder_ids=[src_config.folder_id],
            access_token=token,
            name=src_config.name,
            fetch=fetch or default_fetch,
        )
    return None


def load_from_source(src_config: Any, since: Optional[float] = None,
                     fetch: Any = None) -> list[KnowledgeDocument]:
    source = build_source(src_config, fetch=fetch)
    return source.load(since) if source is not None else []
