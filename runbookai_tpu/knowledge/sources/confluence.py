"""Confluence knowledge source.

Parity target: reference ``src/knowledge/sources/confluence.ts`` —
``loadFromConfluence`` (:50) walking a space's pages through the REST **v2**
API (``/wiki/api/v2/spaces/{key}/pages``, :96) with a **v1 CQL fallback**
(``/wiki/rest/api/content`` + label CQL, :152-168), label-driven type/service
inference (:285-291), HTML("storage")→markdown conversion, and incremental
sync via ``since`` timestamps (:124-126).

Networking goes through an injectable ``fetch(url, headers) -> (status,
body_bytes)`` callable so tests run hermetically and the zero-egress build
can gate it; the default uses ``urllib``.
"""

from __future__ import annotations

import base64
import json
import re
import time
import urllib.parse
import urllib.request
from datetime import datetime, timezone
from typing import Any, Callable, Optional

from runbookai_tpu.knowledge.chunker import chunk_markdown
from runbookai_tpu.knowledge.sources.html_markdown import html_to_markdown
from runbookai_tpu.knowledge.types import KnowledgeDocument

Fetch = Callable[[str, dict[str, str]], tuple[int, bytes]]

_TYPE_LABELS = {"runbook", "postmortem", "known-issue", "architecture",
                "reference", "procedure", "troubleshooting", "faq"}


def default_fetch(url: str, headers: dict[str, str]) -> tuple[int, bytes]:
    req = urllib.request.Request(url, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as err:  # pragma: no cover - network path
        return err.code, err.read()


def _parse_iso(ts: str) -> float:
    """ISO-8601 → epoch seconds. Confluence Cloud returns
    ``2024-05-01T12:00:00.000Z``; Server/DC returns local offsets like
    ``...+1000``, which ``fromisoformat`` handles. Naive timestamps are UTC."""
    ts = ts.strip()
    if not ts:
        return 0.0
    ts = ts.replace("Z", "+00:00")
    # Python 3.10's fromisoformat only accepts ±HH:MM offsets; normalize the
    # colon-less ±HHMM form Confluence Server/DC emits.
    m = re.search(r"([+-]\d{2})(\d{2})$", ts)
    if m and ":" not in ts[m.start():]:
        ts = ts[: m.start()] + m.group(1) + ":" + m.group(2)
    try:
        dt = datetime.fromisoformat(ts)
    except ValueError:
        return 0.0
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()


def infer_type_from_labels(labels: list[str]) -> str:
    for label in labels:
        normalized = label.lower().replace("_", "-")
        if normalized in _TYPE_LABELS:
            return "known_issue" if normalized == "known-issue" else normalized
    return "reference"


def services_from_labels(labels: list[str]) -> list[str]:
    return [label.split(":", 1)[1] for label in labels
            if label.startswith("service:")]


class ConfluenceSource:
    """Space walker with v2→v1 fallback and label filtering."""

    def __init__(
        self,
        base_url: str,
        space_key: str,
        email: str = "",
        api_token: str = "",
        labels: Optional[list[str]] = None,
        name: str = "confluence",
        fetch: Fetch = default_fetch,
        page_limit: int = 50,
    ):
        self.base_url = base_url.rstrip("/")
        self.space_key = space_key
        self.labels = labels or []
        self.name = name
        self.fetch = fetch
        self.page_limit = page_limit
        credentials = base64.b64encode(f"{email}:{api_token}".encode()).decode()
        self.headers = {"Authorization": f"Basic {credentials}",
                        "Accept": "application/json"}

    # -- API pagination --------------------------------------------------
    def _get_json(self, url: str) -> tuple[int, Any]:
        status, body = self.fetch(url, self.headers)
        try:
            return status, json.loads(body.decode() or "null")
        except json.JSONDecodeError:
            return status, None

    def _pages_v2(self, since: Optional[float]) -> Optional[list[dict[str, Any]]]:
        pages: list[dict[str, Any]] = []
        url = (f"{self.base_url}/wiki/api/v2/spaces/{self.space_key}/pages"
               f"?body-format=storage&limit={self.page_limit}")
        while url:
            status, data = self._get_json(url)
            if status == 404 or data is None:
                return None  # fall back to v1
            if status != 200:
                raise RuntimeError(f"confluence v2 fetch failed: HTTP {status}")
            for page in data.get("results", []):
                modified = _parse_iso(
                    (page.get("version") or {}).get("createdAt", ""))
                if since is not None and modified and modified <= since:
                    continue
                # v2 listings carry no label metadata; fetch per page (the
                # v1 fallback gets them via expand=metadata.labels instead).
                page.setdefault("labels", {"results": self._labels_v2(
                    str(page.get("id", "")))})
                pages.append(page)
            nxt = (data.get("_links") or {}).get("next")
            url = urllib.parse.urljoin(self.base_url, nxt) if nxt else ""
        return pages

    def _labels_v2(self, page_id: str) -> list[dict[str, Any]]:
        if not page_id:
            return []
        status, data = self._get_json(
            f"{self.base_url}/wiki/api/v2/pages/{page_id}/labels?limit=100")
        if status != 200 or not isinstance(data, dict):
            return []
        return [{"name": l.get("name", "")} for l in data.get("results", [])]

    def _pages_v1(self, since: Optional[float]) -> list[dict[str, Any]]:
        pages: list[dict[str, Any]] = []
        start = 0
        while True:
            params = {
                "spaceKey": self.space_key, "type": "page",
                "expand": "body.storage,version,metadata.labels",
                "start": str(start), "limit": str(self.page_limit),
            }
            if self.labels:
                params["cql"] = " OR ".join(f'label="{l}"' for l in self.labels)
            url = (f"{self.base_url}/wiki/rest/api/content?"
                   + urllib.parse.urlencode(params))
            status, data = self._get_json(url)
            if status != 200 or data is None:
                raise RuntimeError(f"confluence v1 fetch failed: HTTP {status}")
            results = data.get("results", [])
            for page in results:
                modified = _parse_iso(
                    (page.get("version") or {}).get("when", ""))
                if since is not None and modified and modified <= since:
                    continue
                pages.append(page)
            if len(results) < self.page_limit:
                return pages
            start += self.page_limit

    # -- document assembly ------------------------------------------------
    def _labels_of(self, page: dict[str, Any]) -> list[str]:
        meta = ((page.get("metadata") or {}).get("labels") or {})
        results = meta.get("results") or (page.get("labels") or {}).get("results") or []
        return [str(l.get("name", "")) for l in results if l.get("name")]

    def _to_document(self, page: dict[str, Any]) -> Optional[KnowledgeDocument]:
        html = ((page.get("body") or {}).get("storage") or {}).get("value", "")
        labels = self._labels_of(page)
        if self.labels and not (set(labels) & set(self.labels)):
            return None
        markdown = html_to_markdown(html)
        page_id = str(page.get("id", ""))
        ref = f"{self.space_key}/{page_id}"
        doc_id = KnowledgeDocument.make_id(self.name, ref)
        version = page.get("version") or {}
        updated = _parse_iso(version.get("createdAt") or version.get("when") or "")
        doc = KnowledgeDocument(
            doc_id=doc_id,
            title=str(page.get("title") or page_id),
            content=markdown,
            knowledge_type=infer_type_from_labels(labels),
            source=self.name,
            source_ref=ref,
            services=services_from_labels(labels),
            tags=[l for l in labels
                  if not l.startswith("service:")
                  and l.lower().replace("_", "-") not in _TYPE_LABELS],
            updated_at=updated or time.time(),
        )
        doc.chunks = chunk_markdown(doc_id, markdown)
        return doc

    def load(self, since: Optional[float] = None) -> list[KnowledgeDocument]:
        pages = self._pages_v2(since)
        if pages is None:
            pages = self._pages_v1(since)
        docs = []
        for page in pages:
            try:
                doc = self._to_document(page)
            except Exception:
                continue  # one bad page must not abort the sync
            if doc is not None:
                docs.append(doc)
        return docs
