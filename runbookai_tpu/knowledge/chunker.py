"""Markdown-aware chunking with frontmatter metadata.

Parity target: reference ``src/knowledge/sources/filesystem.ts`` (:22) —
gray-matter frontmatter (type, services, symptoms, severity; README.md:431-451)
and markdown section chunking with chunk-type inference (procedure / context /
command / ...).
"""

from __future__ import annotations

import re
from typing import Any

import yaml

from runbookai_tpu.knowledge.types import KnowledgeChunk, KnowledgeDocument

_FRONTMATTER_RE = re.compile(r"\A---\s*\n(.*?)\n---\s*\n", re.DOTALL)
_HEADING_RE = re.compile(r"^(#{1,6})\s+(.*)$", re.MULTILINE)


def parse_frontmatter(text: str) -> tuple[dict[str, Any], str]:
    m = _FRONTMATTER_RE.match(text)
    if not m:
        return {}, text
    try:
        meta = yaml.safe_load(m.group(1)) or {}
    except yaml.YAMLError:
        meta = {}
    return (meta if isinstance(meta, dict) else {}), text[m.end():]


def infer_chunk_type(content: str, section: str) -> str:
    body = content.strip()
    section_low = section.lower()
    numbered = len(re.findall(r"^\s*\d+[.)]\s", body, re.MULTILINE))
    if numbered >= 2 or any(w in section_low for w in ("procedure", "steps", "mitigation", "remediation")):
        return "procedure"
    if body.count("```") >= 2 or re.search(r"^\s*\$\s", body, re.MULTILINE):
        return "command"
    if re.search(r"^\|.*\|", body, re.MULTILINE):
        return "table"
    if len(re.findall(r"^\s*[-*]\s", body, re.MULTILINE)) >= 3:
        return "list"
    if any(w in section_low for w in ("background", "context", "overview", "architecture")):
        return "context"
    return "text"


def chunk_markdown(doc_id: str, text: str, max_chunk_chars: int = 2400) -> list[KnowledgeChunk]:
    """Split on headings; oversized sections split on paragraph boundaries."""
    sections: list[tuple[str, str]] = []
    matches = list(_HEADING_RE.finditer(text))
    if not matches:
        sections.append(("", text))
    else:
        if matches[0].start() > 0:
            sections.append(("", text[: matches[0].start()]))
        for i, m in enumerate(matches):
            end = matches[i + 1].start() if i + 1 < len(matches) else len(text)
            sections.append((m.group(2).strip(), text[m.end():end]))

    chunks: list[KnowledgeChunk] = []
    for section, body in sections:
        body = body.strip()
        if not body and not section:
            continue
        pieces = [body] if len(body) <= max_chunk_chars else _split_paragraphs(body, max_chunk_chars)
        for piece in pieces:
            content = f"{section}\n{piece}".strip() if section else piece
            if not content:
                continue
            chunks.append(KnowledgeChunk(
                chunk_id=f"{doc_id}#{len(chunks)}",
                doc_id=doc_id,
                content=content,
                section=section,
                chunk_type=infer_chunk_type(piece, section),
                position=len(chunks),
            ))
    return chunks


def _split_paragraphs(body: str, max_chars: int) -> list[str]:
    pieces: list[str] = []
    current: list[str] = []
    size = 0
    for para in body.split("\n\n"):
        if size + len(para) > max_chars and current:
            pieces.append("\n\n".join(current))
            current, size = [], 0
        current.append(para)
        size += len(para) + 2
    if current:
        pieces.append("\n\n".join(current))
    return pieces


def document_from_markdown(
    path_or_ref: str, text: str, source: str = "filesystem",
    default_title: str = "",
) -> KnowledgeDocument:
    meta, body = parse_frontmatter(text)
    doc_id = KnowledgeDocument.make_id(source, path_or_ref)
    title = str(meta.get("title") or default_title or _first_heading(body) or path_or_ref)
    services = meta.get("services") or []
    symptoms = meta.get("symptoms") or []
    tags = meta.get("tags") or []
    doc = KnowledgeDocument(
        doc_id=doc_id,
        title=title,
        content=body,
        knowledge_type=str(meta.get("type", "reference")),
        source=source,
        source_ref=path_or_ref,
        services=[str(s) for s in services] if isinstance(services, list) else [str(services)],
        symptoms=[str(s) for s in symptoms] if isinstance(symptoms, list) else [str(symptoms)],
        severity=meta.get("severity"),
        tags=[str(t) for t in tags] if isinstance(tags, list) else [str(tags)],
    )
    doc.chunks = chunk_markdown(doc_id, body)
    return doc


def _first_heading(text: str) -> str:
    m = _HEADING_RE.search(text)
    return m.group(2).strip() if m else ""
