"""Service dependency graph.

Parity target: reference ``src/knowledge/store/graph-store.ts``
(``ServiceGraph`` :76 — addService :85, addDependency :184, upstream/downstream
impact :342/:383, team/type/tag/tier filters :296-322, path finding + cycle
detection + stats :430-600; persisted as ``.runbook/service-graph.json``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional


@dataclass
class ServiceNode:
    name: str
    type: str = "service"
    team: Optional[str] = None
    tier: Optional[int] = None
    tags: list[str] = field(default_factory=list)
    metadata: dict[str, Any] = field(default_factory=dict)


@dataclass
class DependencyEdge:
    source: str  # depends on target
    target: str
    kind: str = "sync"  # sync | async | data
    description: str = ""


class ServiceGraph:
    def __init__(self) -> None:
        self.nodes: dict[str, ServiceNode] = {}
        self.edges: list[DependencyEdge] = []

    # ------------------------------------------------------------------ build

    def add_service(self, name: str, **kw) -> ServiceNode:
        node = self.nodes.get(name)
        if node is None:
            node = ServiceNode(name=name, **kw)
            self.nodes[name] = node
        else:
            for k, v in kw.items():
                if v is not None:
                    setattr(node, k, v)
        return node

    def add_dependency(self, source: str, target: str, kind: str = "sync",
                       description: str = "") -> DependencyEdge:
        self.add_service(source)
        self.add_service(target)
        for e in self.edges:
            if e.source == source and e.target == target:
                return e
        edge = DependencyEdge(source=source, target=target, kind=kind,
                              description=description)
        self.edges.append(edge)
        return edge

    # ---------------------------------------------------------------- queries

    def dependencies_of(self, name: str) -> list[str]:
        return [e.target for e in self.edges if e.source == name]

    def dependents_of(self, name: str) -> list[str]:
        return [e.source for e in self.edges if e.target == name]

    def downstream_impact(self, name: str, max_depth: int = 10) -> list[str]:
        """Services affected if ``name`` degrades (transitive dependents —
        the blast radius)."""
        return self._walk(name, self.dependents_of, max_depth)

    def upstream_impact(self, name: str, max_depth: int = 10) -> list[str]:
        """Services whose failure could explain ``name`` degrading."""
        return self._walk(name, self.dependencies_of, max_depth)

    def _walk(self, start: str, neighbors, max_depth: int) -> list[str]:
        seen: list[str] = []
        frontier = [(start, 0)]
        visited = {start}
        while frontier:
            cur, depth = frontier.pop(0)
            if depth >= max_depth:
                continue
            for nxt in neighbors(cur):
                if nxt not in visited:
                    visited.add(nxt)
                    seen.append(nxt)
                    frontier.append((nxt, depth + 1))
        return seen

    def find_path(self, source: str, target: str) -> Optional[list[str]]:
        frontier = [[source]]
        visited = {source}
        while frontier:
            path = frontier.pop(0)
            if path[-1] == target:
                return path
            for nxt in self.dependencies_of(path[-1]):
                if nxt not in visited:
                    visited.add(nxt)
                    frontier.append(path + [nxt])
        return None

    def find_cycles(self) -> list[list[str]]:
        cycles = []
        state: dict[str, int] = {}
        stack: list[str] = []

        def dfs(node: str) -> None:
            state[node] = 1
            stack.append(node)
            for nxt in self.dependencies_of(node):
                if state.get(nxt, 0) == 0:
                    dfs(nxt)
                elif state.get(nxt) == 1 and nxt in stack:
                    cycles.append(stack[stack.index(nxt):] + [nxt])
            stack.pop()
            state[node] = 2

        for name in self.nodes:
            if state.get(name, 0) == 0:
                dfs(name)
        return cycles

    def filter(self, team: Optional[str] = None, type: Optional[str] = None,
               tag: Optional[str] = None, tier: Optional[int] = None) -> list[ServiceNode]:
        out = []
        for node in self.nodes.values():
            if team and node.team != team:
                continue
            if type and node.type != type:
                continue
            if tag and tag not in node.tags:
                continue
            if tier is not None and node.tier != tier:
                continue
            out.append(node)
        return out

    def stats(self) -> dict[str, Any]:
        indegree: dict[str, int] = {n: 0 for n in self.nodes}
        for e in self.edges:
            indegree[e.target] = indegree.get(e.target, 0) + 1
        most_depended = sorted(indegree.items(), key=lambda kv: kv[1], reverse=True)[:5]
        return {
            "services": len(self.nodes),
            "dependencies": len(self.edges),
            "cycles": len(self.find_cycles()),
            "most_depended_on": most_depended,
        }

    # ------------------------------------------------------------ persistence

    def save(self, path: str | Path = ".runbook/service-graph.json") -> None:
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps({
            "nodes": [vars(n) for n in self.nodes.values()],
            "edges": [vars(e) for e in self.edges],
        }, indent=2))

    @classmethod
    def load(cls, path: str | Path = ".runbook/service-graph.json") -> "ServiceGraph":
        graph = cls()
        p = Path(path)
        if p.is_file():
            data = json.loads(p.read_text())
            for raw in data.get("nodes", []):
                graph.nodes[raw["name"]] = ServiceNode(**raw)
            for raw in data.get("edges", []):
                graph.edges.append(DependencyEdge(**raw))
        return graph

    @classmethod
    def from_services_config(cls, services_cfg) -> "ServiceGraph":
        """Build from ``.runbook/services.yaml`` (config ServicesConfig)."""
        graph = cls()
        for svc in services_cfg.services:
            graph.add_service(svc.name, type=svc.type, team=svc.team,
                              tier=svc.tier, tags=list(svc.tags))
            for dep in svc.depends_on:
                graph.add_dependency(svc.name, dep)
        return graph
