"""Vector store: embeddings in SQLite, similarity search as one device matmul.

Parity target: reference ``src/knowledge/store/vector-store.ts`` (:24; its
``search`` :188-211 is an O(N) JavaScript cosine loop). Here the corpus matrix
is cached on device and a query is a single ``[1, D] @ [D, N]`` matmul + top-k
— the SURVEY.md §3.4 hot-loop replacement.
"""

from __future__ import annotations

import sqlite3
from typing import Optional

import numpy as np

_SCHEMA = """
CREATE TABLE IF NOT EXISTS embeddings (
    chunk_id TEXT PRIMARY KEY,
    doc_id TEXT NOT NULL,
    dim INTEGER NOT NULL,
    vector BLOB NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_emb_doc ON embeddings(doc_id);
"""


class VectorStore:
    def __init__(self, db: sqlite3.Connection):
        self.db = db
        self.db.executescript(_SCHEMA)
        self._matrix: Optional[np.ndarray] = None  # [N, D] float32 normalized
        self._ids: list[str] = []
        self._device_matrix = None

    def store(self, chunk_id: str, doc_id: str, vector: np.ndarray) -> None:
        vec = np.asarray(vector, dtype=np.float32)
        with self.db:
            self.db.execute(
                """INSERT INTO embeddings (chunk_id, doc_id, dim, vector)
                   VALUES (?, ?, ?, ?)
                   ON CONFLICT(chunk_id) DO UPDATE SET
                       doc_id=excluded.doc_id, dim=excluded.dim, vector=excluded.vector""",
                (chunk_id, doc_id, vec.shape[0], vec.tobytes()),
            )
        self._invalidate()

    def store_many(self, rows: list[tuple[str, str, np.ndarray]]) -> None:
        with self.db:
            self.db.executemany(
                """INSERT INTO embeddings (chunk_id, doc_id, dim, vector)
                   VALUES (?, ?, ?, ?)
                   ON CONFLICT(chunk_id) DO UPDATE SET
                       doc_id=excluded.doc_id, dim=excluded.dim, vector=excluded.vector""",
                [(cid, did, np.asarray(v, np.float32).shape[0],
                  np.asarray(v, np.float32).tobytes()) for cid, did, v in rows],
            )
        self._invalidate()

    def delete_doc(self, doc_id: str) -> None:
        with self.db:
            self.db.execute("DELETE FROM embeddings WHERE doc_id = ?", (doc_id,))
        self._invalidate()

    def count(self) -> int:
        return self.db.execute("SELECT COUNT(*) FROM embeddings").fetchone()[0]

    def _invalidate(self) -> None:
        self._matrix = None
        self._device_matrix = None

    def _load_matrix(self) -> None:
        rows = self.db.execute(
            "SELECT chunk_id, dim, vector FROM embeddings ORDER BY chunk_id"
        ).fetchall()
        self._ids = [r[0] for r in rows]
        if not rows:
            self._matrix = np.zeros((0, 1), dtype=np.float32)
            return
        mat = np.stack([
            np.frombuffer(r[2], dtype=np.float32, count=r[1]) for r in rows
        ])
        norms = np.linalg.norm(mat, axis=1, keepdims=True)
        self._matrix = mat / np.maximum(norms, 1e-9)

    def search(self, query_vec: np.ndarray, limit: int = 10) -> list[tuple[str, float]]:
        """Top-k (chunk_id, cosine) — one matmul on device when jax is live."""
        if self._matrix is None:
            self._load_matrix()
        if len(self._ids) == 0:
            return []
        q = np.asarray(query_vec, np.float32)
        q = q / max(float(np.linalg.norm(q)), 1e-9)
        try:
            import jax.numpy as jnp

            if self._device_matrix is None:
                self._device_matrix = jnp.asarray(self._matrix)
            scores = np.asarray(self._device_matrix @ jnp.asarray(q))
        except Exception:  # pragma: no cover — jax unavailable
            scores = self._matrix @ q
        k = min(limit, len(scores))
        top = np.argpartition(-scores, k - 1)[:k]
        top = top[np.argsort(-scores[top])]
        return [(self._ids[i], float(scores[i])) for i in top]
