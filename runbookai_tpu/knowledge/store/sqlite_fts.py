"""SQLite knowledge store with FTS5 full-text search.

Parity target: reference ``src/knowledge/store/sqlite.ts`` (``KnowledgeStore``
:11; schema :19-71 — documents + chunks tables, FTS5 virtual table kept in sync
by triggers; ``search`` :125). The reference uses better-sqlite3 (native C++
bindings); Python's stdlib ``sqlite3`` links the same C library — the FTS5
index and trigger discipline are identical. Embeddings live in a sibling table
(see ``vector.py``) so vector rows share chunk ids with FTS rows.
"""

from __future__ import annotations

import json
import sqlite3
import time
from pathlib import Path
from typing import Any, Optional

from runbookai_tpu.knowledge.types import (
    KnowledgeChunk,
    KnowledgeDocument,
    SearchHit,
)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS documents (
    doc_id TEXT PRIMARY KEY,
    title TEXT NOT NULL,
    content TEXT NOT NULL,
    knowledge_type TEXT NOT NULL DEFAULT 'reference',
    source TEXT NOT NULL DEFAULT 'filesystem',
    source_ref TEXT NOT NULL DEFAULT '',
    services TEXT NOT NULL DEFAULT '[]',
    symptoms TEXT NOT NULL DEFAULT '[]',
    severity TEXT,
    tags TEXT NOT NULL DEFAULT '[]',
    updated_at REAL NOT NULL
);

CREATE TABLE IF NOT EXISTS chunks (
    chunk_id TEXT PRIMARY KEY,
    doc_id TEXT NOT NULL REFERENCES documents(doc_id) ON DELETE CASCADE,
    content TEXT NOT NULL,
    section TEXT NOT NULL DEFAULT '',
    chunk_type TEXT NOT NULL DEFAULT 'text',
    position INTEGER NOT NULL DEFAULT 0
);

CREATE INDEX IF NOT EXISTS idx_chunks_doc ON chunks(doc_id);

CREATE VIRTUAL TABLE IF NOT EXISTS chunks_fts USING fts5(
    content, section,
    content=chunks, content_rowid=rowid
);

CREATE TRIGGER IF NOT EXISTS chunks_ai AFTER INSERT ON chunks BEGIN
    INSERT INTO chunks_fts(rowid, content, section)
    VALUES (new.rowid, new.content, new.section);
END;
CREATE TRIGGER IF NOT EXISTS chunks_ad AFTER DELETE ON chunks BEGIN
    INSERT INTO chunks_fts(chunks_fts, rowid, content, section)
    VALUES ('delete', old.rowid, old.content, old.section);
END;
CREATE TRIGGER IF NOT EXISTS chunks_au AFTER UPDATE ON chunks BEGIN
    INSERT INTO chunks_fts(chunks_fts, rowid, content, section)
    VALUES ('delete', old.rowid, old.content, old.section);
    INSERT INTO chunks_fts(rowid, content, section)
    VALUES (new.rowid, new.content, new.section);
END;

CREATE TABLE IF NOT EXISTS sync_state (
    source TEXT PRIMARY KEY,
    last_sync_time REAL NOT NULL
);
"""


class KnowledgeStore:
    def __init__(self, db_path: str | Path = ":memory:"):
        if db_path != ":memory:":
            Path(db_path).parent.mkdir(parents=True, exist_ok=True)
        self.db = sqlite3.connect(str(db_path))
        self.db.row_factory = sqlite3.Row
        self.db.execute("PRAGMA foreign_keys = ON")
        self.db.executescript(_SCHEMA)

    # ------------------------------------------------------------------ CRUD

    def upsert_document(self, doc: KnowledgeDocument) -> None:
        with self.db:
            self.db.execute("DELETE FROM chunks WHERE doc_id = ?", (doc.doc_id,))
            self.db.execute(
                """INSERT INTO documents (doc_id, title, content, knowledge_type,
                        source, source_ref, services, symptoms, severity, tags, updated_at)
                   VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)
                   ON CONFLICT(doc_id) DO UPDATE SET
                        title=excluded.title, content=excluded.content,
                        knowledge_type=excluded.knowledge_type, source=excluded.source,
                        source_ref=excluded.source_ref, services=excluded.services,
                        symptoms=excluded.symptoms, severity=excluded.severity,
                        tags=excluded.tags, updated_at=excluded.updated_at""",
                (doc.doc_id, doc.title, doc.content, doc.knowledge_type, doc.source,
                 doc.source_ref, json.dumps(doc.services), json.dumps(doc.symptoms),
                 doc.severity, json.dumps(doc.tags), doc.updated_at),
            )
            for chunk in doc.chunks:
                self.db.execute(
                    """INSERT INTO chunks (chunk_id, doc_id, content, section,
                            chunk_type, position) VALUES (?, ?, ?, ?, ?, ?)""",
                    (chunk.chunk_id, chunk.doc_id, chunk.content, chunk.section,
                     chunk.chunk_type, chunk.position),
                )

    def delete_document(self, doc_id: str) -> None:
        with self.db:
            self.db.execute("DELETE FROM chunks WHERE doc_id = ?", (doc_id,))
            self.db.execute("DELETE FROM documents WHERE doc_id = ?", (doc_id,))

    def get_document(self, doc_id: str) -> Optional[KnowledgeDocument]:
        row = self.db.execute("SELECT * FROM documents WHERE doc_id = ?", (doc_id,)).fetchone()
        return self._doc_from_row(row) if row else None

    def _doc_from_row(self, row: sqlite3.Row) -> KnowledgeDocument:
        return KnowledgeDocument(
            doc_id=row["doc_id"], title=row["title"], content=row["content"],
            knowledge_type=row["knowledge_type"], source=row["source"],
            source_ref=row["source_ref"], services=json.loads(row["services"]),
            symptoms=json.loads(row["symptoms"]), severity=row["severity"],
            tags=json.loads(row["tags"]), updated_at=row["updated_at"],
        )

    def all_chunks(self) -> list[KnowledgeChunk]:
        rows = self.db.execute("SELECT * FROM chunks ORDER BY doc_id, position").fetchall()
        return [KnowledgeChunk(
            chunk_id=r["chunk_id"], doc_id=r["doc_id"], content=r["content"],
            section=r["section"], chunk_type=r["chunk_type"], position=r["position"],
        ) for r in rows]

    def stats(self) -> dict[str, Any]:
        docs = self.db.execute("SELECT COUNT(*) c FROM documents").fetchone()["c"]
        chunks = self.db.execute("SELECT COUNT(*) c FROM chunks").fetchone()["c"]
        by_type = {
            r["knowledge_type"]: r["c"]
            for r in self.db.execute(
                "SELECT knowledge_type, COUNT(*) c FROM documents GROUP BY 1")
        }
        return {"documents": docs, "chunks": chunks, "by_type": by_type}

    # ------------------------------------------------------------------ sync

    def get_last_sync_time(self, source: str) -> Optional[float]:
        row = self.db.execute(
            "SELECT last_sync_time FROM sync_state WHERE source = ?", (source,)
        ).fetchone()
        return row["last_sync_time"] if row else None

    def set_last_sync_time(self, source: str, ts: Optional[float] = None) -> None:
        with self.db:
            self.db.execute(
                """INSERT INTO sync_state (source, last_sync_time) VALUES (?, ?)
                   ON CONFLICT(source) DO UPDATE SET last_sync_time=excluded.last_sync_time""",
                (source, ts if ts is not None else time.time()),
            )

    # ---------------------------------------------------------------- search

    @staticmethod
    def _fts_query(query: str) -> str:
        """Sanitize a natural-language query into FTS5 OR-term syntax."""
        terms = [t for t in "".join(
            c if c.isalnum() or c in "-_" else " " for c in query
        ).split() if len(t) > 1]
        return " OR ".join(f'"{t}"' for t in terms[:16]) or '""'

    def search(
        self,
        query: str,
        limit: int = 10,
        knowledge_type: Optional[str] = None,
        service: Optional[str] = None,
    ) -> list[SearchHit]:
        sql = """
            SELECT c.chunk_id, c.doc_id, c.content AS chunk_content, c.section,
                   c.chunk_type, c.position,
                   d.title, d.content, d.knowledge_type, d.source, d.source_ref,
                   d.services, d.symptoms, d.severity, d.tags, d.updated_at,
                   bm25(chunks_fts) AS rank
            FROM chunks_fts f
            JOIN chunks c ON c.rowid = f.rowid
            JOIN documents d ON d.doc_id = c.doc_id
            WHERE chunks_fts MATCH ?
        """
        params: list[Any] = [self._fts_query(query)]
        if knowledge_type:
            sql += " AND d.knowledge_type = ?"
            params.append(knowledge_type)
        if service:
            sql += " AND d.services LIKE ?"
            params.append(f'%"{service}"%')
        sql += " ORDER BY rank LIMIT ?"
        params.append(limit)
        hits = []
        for r in self.db.execute(sql, params).fetchall():
            chunk = KnowledgeChunk(
                chunk_id=r["chunk_id"], doc_id=r["doc_id"], content=r["chunk_content"],
                section=r["section"], chunk_type=r["chunk_type"], position=r["position"],
            )
            doc = self._doc_from_row(r)
            # bm25 rank: lower is better; convert to a positive score.
            hits.append(SearchHit(chunk=chunk, doc=doc, score=-float(r["rank"]), mode="fts"))
        return hits

    def close(self) -> None:
        self.db.close()
