"""Hybrid retrieval: FTS5 + on-device vector search fused by RRF, plus the
retriever facade with incremental sync.

Parity targets: reference ``src/knowledge/retriever/hybrid-search.ts``
(``HybridRetriever`` :22; modes fts/vector/hybrid :54-100; Reciprocal Rank
Fusion :106 with k=60, weights FTS 0.4 / vector 0.6 :17-19; FTS-only fallback
when the embedder is unconfigured :67) and ``retriever/index.ts``
(``KnowledgeRetriever`` :24, ``sync`` :44 with lastSyncTime, ``search`` :85,
grouping into runbooks/postmortems/knownIssues/architecture).
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Optional

from runbookai_tpu.agent.types import KnowledgeResult, RetrievedKnowledge
from runbookai_tpu.knowledge.chunker import document_from_markdown
from runbookai_tpu.knowledge.store.sqlite_fts import KnowledgeStore
from runbookai_tpu.knowledge.store.vector import VectorStore
from runbookai_tpu.knowledge.types import SearchHit


def reciprocal_rank_fusion(
    ranked_lists: list[tuple[float, list[str]]], k: int = 60
) -> dict[str, float]:
    """RRF over (weight, [ids best-first]) lists (hybrid-search.ts:106)."""
    scores: dict[str, float] = {}
    for weight, ids in ranked_lists:
        for rank, item_id in enumerate(ids):
            scores[item_id] = scores.get(item_id, 0.0) + weight / (k + rank + 1)
    return scores


class HybridRetriever:
    def __init__(
        self,
        store: KnowledgeStore,
        vectors: Optional[VectorStore] = None,
        embedder: Optional[Any] = None,
        rrf_k: int = 60,
        fts_weight: float = 0.4,
        vector_weight: float = 0.6,
    ):
        self.store = store
        self.vectors = vectors
        self.embedder = embedder
        self.rrf_k = rrf_k
        self.fts_weight = fts_weight
        self.vector_weight = vector_weight

    def search(
        self,
        query: str,
        limit: int = 8,
        mode: str = "hybrid",
        knowledge_type: Optional[str] = None,
        service: Optional[str] = None,
    ) -> list[SearchHit]:
        has_vectors = (
            self.embedder is not None and self.vectors is not None
            and self.vectors.count() > 0
        )
        if mode == "hybrid" and not has_vectors:
            mode = "fts"  # fallback (hybrid-search.ts:67)

        fts_hits = self.store.search(query, limit=limit * 3,
                                     knowledge_type=knowledge_type, service=service)
        if mode == "fts":
            return fts_hits[:limit]

        qvec = self.embedder.embed_text(query, is_query=True)
        vec_pairs = self.vectors.search(qvec, limit=limit * 3)
        by_chunk: dict[str, SearchHit] = {h.chunk.chunk_id: h for h in fts_hits}
        # Materialize vector-only hits from the store.
        missing = [cid for cid, _ in vec_pairs if cid not in by_chunk]
        if missing:
            for hit in self._hits_for_chunk_ids(missing, knowledge_type, service):
                by_chunk[hit.chunk.chunk_id] = hit
        if mode == "vector":
            ordered = [cid for cid, _ in vec_pairs if cid in by_chunk]
            return [by_chunk[cid] for cid in ordered[:limit]]

        fused = reciprocal_rank_fusion(
            [
                (self.fts_weight, [h.chunk.chunk_id for h in fts_hits]),
                (self.vector_weight, [cid for cid, _ in vec_pairs]),
            ],
            k=self.rrf_k,
        )
        ranked = sorted(fused.items(), key=lambda kv: kv[1], reverse=True)
        out = []
        for cid, score in ranked:
            hit = by_chunk.get(cid)
            if hit is None:
                continue
            out.append(SearchHit(chunk=hit.chunk, doc=hit.doc, score=score, mode="hybrid"))
            if len(out) >= limit:
                break
        return out

    def _hits_for_chunk_ids(self, chunk_ids, knowledge_type, service) -> list[SearchHit]:
        hits = []
        for cid in chunk_ids:
            row = self.store.db.execute(
                "SELECT * FROM chunks WHERE chunk_id = ?", (cid,)
            ).fetchone()
            if row is None:
                continue
            doc = self.store.get_document(row["doc_id"])
            if doc is None:
                continue
            if knowledge_type and doc.knowledge_type != knowledge_type:
                continue
            if service and service not in doc.services:
                continue
            from runbookai_tpu.knowledge.types import KnowledgeChunk

            chunk = KnowledgeChunk(
                chunk_id=row["chunk_id"], doc_id=row["doc_id"], content=row["content"],
                section=row["section"], chunk_type=row["chunk_type"],
                position=row["position"],
            )
            hits.append(SearchHit(chunk=chunk, doc=doc, score=0.0, mode="vector"))
        return hits


class KnowledgeRetriever:
    """Facade: sync sources → store (+embeddings); search → grouped results."""

    def __init__(self, store: KnowledgeStore, hybrid: HybridRetriever,
                 sources: Optional[list[Any]] = None):
        self.store = store
        self.hybrid = hybrid
        self.sources = sources or []

    # ------------------------------------------------------------------ sync

    def sync(self, force: bool = False) -> dict[str, int]:
        """Incremental sync of all sources; returns per-source doc counts."""
        counts: dict[str, int] = {}
        for source in self.sources:
            name = source.name
            last = None if force else self.store.get_last_sync_time(name)
            docs = source.load(since=last)
            for doc in docs:
                self.store.upsert_document(doc)
                if self.hybrid.embedder is not None and self.hybrid.vectors is not None:
                    texts = [c.content for c in doc.chunks]
                    if texts:
                        self.hybrid.vectors.delete_doc(doc.doc_id)
                        embs = self.hybrid.embedder.embed_texts(texts)
                        self.hybrid.vectors.store_many([
                            (c.chunk_id, doc.doc_id, embs[i])
                            for i, c in enumerate(doc.chunks)
                        ])
            self.store.set_last_sync_time(name)
            counts[name] = len(docs)
        return counts

    # ---------------------------------------------------------------- search

    async def retrieve(self, query: str, services: Optional[list[str]] = None) -> RetrievedKnowledge:
        """Async adapter the Agent consumes (grouped, reference types.ts:281)."""
        return self.search_grouped(query, service=services[0] if services else None)

    def search_grouped(self, query: str, limit: int = 8,
                       service: Optional[str] = None) -> RetrievedKnowledge:
        hits = self.hybrid.search(query, limit=limit, service=service)
        grouped = RetrievedKnowledge()
        buckets = {
            "runbook": grouped.runbooks,
            "procedure": grouped.runbooks,
            "troubleshooting": grouped.runbooks,
            "postmortem": grouped.postmortems,
            "known-issue": grouped.known_issues,
            "architecture": grouped.architecture,
        }
        for hit in hits:
            result = KnowledgeResult(
                doc_id=hit.doc.doc_id, title=hit.doc.title,
                knowledge_type=hit.doc.knowledge_type, content=hit.chunk.content,
                score=hit.score, services=hit.doc.services, source=hit.doc.source,
            )
            buckets.get(hit.doc.knowledge_type, grouped.architecture).append(result)
        return grouped

    def stats(self) -> dict[str, Any]:
        s = self.store.stats()
        if self.hybrid.vectors is not None:
            s["embeddings"] = self.hybrid.vectors.count()
        if self.hybrid.embedder is not None:
            s["embedder"] = dict(self.hybrid.embedder.stats)
        return s


class FilesystemSource:
    """Markdown tree loader (reference sources/filesystem.ts:22)."""

    def __init__(self, path: str | Path, name: str = "filesystem"):
        self.path = Path(path)
        self.name = name

    def load(self, since: Optional[float] = None) -> list[Any]:
        docs = []
        if not self.path.exists():
            return docs
        for file in sorted(self.path.rglob("*.md")):
            mtime = file.stat().st_mtime
            if since is not None and mtime <= since:
                continue
            doc = document_from_markdown(
                str(file.relative_to(self.path)), file.read_text(),
                source=self.name, default_title=file.stem,
            )
            doc.updated_at = mtime
            docs.append(doc)
        return docs


def create_retriever(config, embedder: Optional[Any] = None) -> KnowledgeRetriever:
    """Build the full stack from a Config (reference retriever/index.ts:170)."""
    kcfg = config.knowledge
    store = KnowledgeStore(kcfg.db_path)
    vectors = VectorStore(store.db)
    if embedder is None and kcfg.embedder.enabled:
        from runbookai_tpu.knowledge.embedder import Embedder

        embedder = Embedder.from_config(kcfg.embedder)
    hybrid = HybridRetriever(
        store, vectors=vectors, embedder=embedder,
        rrf_k=kcfg.rrf_k, fts_weight=kcfg.fts_weight, vector_weight=kcfg.vector_weight,
    )
    from runbookai_tpu.knowledge.sources import build_source

    sources = [s for s in (build_source(src) for src in kcfg.sources)
               if s is not None]
    return KnowledgeRetriever(store, hybrid, sources=sources)
