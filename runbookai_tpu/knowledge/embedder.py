"""The knowledge embedder: batched JAX bge encode with caching.

Parity target: reference ``src/knowledge/indexer/embedder.ts`` — the exact API
to reimplement (:57-163): ``embed_text`` (single), ``embed_texts`` (batched
with md5 in-memory cache :49), ``cosine_similarity`` (:168), cost estimation
(:261 — becomes token counts; there is no per-token dollar cost on-device).

Batches are padded to fixed (batch, length) buckets so XLA compiles a small
number of programs; encode bursts run between decode steps when co-resident
with the LLM on one slice (SURVEY.md §7 hard part 5).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Optional

import numpy as np

from runbookai_tpu.utils.tokens import load_tokenizer


class Embedder:
    def __init__(
        self,
        model_name: str = "bge-test",
        model_path: Optional[str] = None,
        tokenizer_path: Optional[str] = None,
        max_length: int = 512,
        batch_size: int = 64,
        query_instruction: str = "Represent this sentence for searching relevant passages: ",
        cache_max_entries: int = 4096,
    ):
        import jax.numpy as jnp  # deferred

        from runbookai_tpu.models import bge

        self.cfg, self.params = bge.load_or_init(model_name, model_path)
        self._encode = bge.encode
        self.tokenizer = load_tokenizer(tokenizer_path or model_path)
        self.max_length = min(max_length, self.cfg.max_positions)
        self.batch_size = batch_size
        self.query_instruction = query_instruction
        self.dim = self.cfg.dim
        # LRU-bounded md5→embedding cache: a days-long process indexing
        # rolling docs must not grow this dict forever (same leak class
        # the r5 soak caught in the engine's finished-request registry).
        # ~dim*4 bytes/entry → the default cap holds ~12 MB for bge-base.
        self._cache: OrderedDict[str, np.ndarray] = OrderedDict()
        self._cache_max = max(0, cache_max_entries)
        self._jnp = jnp
        self.stats = {"texts": 0, "tokens": 0, "cache_hits": 0, "batches": 0,
                      "cache_evictions": 0}

    @classmethod
    def from_config(cls, emb_cfg) -> "Embedder":
        """Shared factory for the knowledge retriever and the serving
        endpoint — one place maps EmbedderConfig fields to kwargs."""
        return cls(model_name=emb_cfg.model, model_path=emb_cfg.model_path,
                   max_length=emb_cfg.max_length,
                   batch_size=emb_cfg.batch_size,
                   cache_max_entries=getattr(emb_cfg, "cache_max_entries",
                                             4096))

    @staticmethod
    def _key(text: str) -> str:
        return hashlib.md5(text.encode()).hexdigest()

    def _bucket_len(self, longest: int) -> int:
        """Round up to a power-of-two bucket to bound compilation count."""
        n = 16
        while n < longest and n < self.max_length:
            n *= 2
        return min(n, self.max_length)

    def _tokenize(self, text: str) -> list[int]:
        ids = self.tokenizer.encode(text)[: self.max_length - 2]
        # CLS/BOS ... SEP/EOS framing; byte fallback uses bos/eos ids.
        cls = getattr(self.tokenizer, "bos_id", None) or 0
        sep = getattr(self.tokenizer, "eos_id", None) or 0
        return [cls, *ids, sep]

    def embed_texts(self, texts: list[str], is_query: bool = False) -> np.ndarray:
        """Batched embed with cache; returns [N, dim] float32 (L2-normalized)."""
        jnp = self._jnp
        out = np.zeros((len(texts), self.dim), dtype=np.float32)
        pending: list[tuple[int, list[int]]] = []
        for i, text in enumerate(texts):
            rendered = (self.query_instruction + text) if is_query else text
            key = self._key(("q:" if is_query else "d:") + rendered)
            cached = self._cache.get(key)
            if cached is not None:
                self._cache.move_to_end(key)  # LRU recency
                out[i] = cached
                self.stats["cache_hits"] += 1
            else:
                pending.append((i, self._tokenize(rendered)))

        for start in range(0, len(pending), self.batch_size):
            batch = pending[start : start + self.batch_size]
            longest = max(len(ids) for _, ids in batch)
            pad_to = self._bucket_len(longest)
            pad_id = getattr(self.tokenizer, "pad_id", 0) % self.cfg.vocab_size
            tokens = np.full((len(batch), pad_to), pad_id, dtype=np.int32)
            mask = np.zeros((len(batch), pad_to), dtype=np.int32)
            for row, (_, ids) in enumerate(batch):
                ids = [t % self.cfg.vocab_size for t in ids[:pad_to]]
                tokens[row, : len(ids)] = ids
                mask[row, : len(ids)] = 1
                self.stats["tokens"] += len(ids)
            embs = np.asarray(self._encode(
                self.params, self.cfg, jnp.asarray(tokens), jnp.asarray(mask)
            ))
            for row, (i, _) in enumerate(batch):
                out[i] = embs[row]
            self.stats["batches"] += 1

        # Fill cache after computing, evicting least-recently-used entries
        # past the cap (a duplicate within `texts` refreshes recency only).
        for i, text in enumerate(texts):
            rendered = (self.query_instruction + text) if is_query else text
            key = self._key(("q:" if is_query else "d:") + rendered)
            if key in self._cache:
                self._cache.move_to_end(key)
            elif self._cache_max:
                # Copy, don't view: out[i] aliases the whole [N, dim]
                # batch array — a cached view would pin the full batch in
                # memory (defeating the cap) and share mutable memory
                # with the caller's returned rows.
                self._cache[key] = out[i].copy()
                while len(self._cache) > self._cache_max:
                    self._cache.popitem(last=False)
                    self.stats["cache_evictions"] += 1
        self.stats["texts"] += len(texts)
        return out

    def embed_text(self, text: str, is_query: bool = False) -> np.ndarray:
        return self.embed_texts([text], is_query=is_query)[0]

    def estimate_tokens(self, texts: list[str]) -> int:
        """Reference cost estimation analogue: token counts (no dollar cost
        for an in-tree encoder)."""
        return sum(len(self._tokenize(t)) for t in texts)


def cosine_similarity(a: np.ndarray, b: np.ndarray) -> float:
    denom = float(np.linalg.norm(a) * np.linalg.norm(b)) or 1e-9
    return float(np.dot(a, b) / denom)
