"""Knowledge domain types.

Parity target: reference ``src/knowledge/types.ts`` — ``KnowledgeDocument`` /
``KnowledgeChunk`` (:30-71), 8 knowledge types (:8-16), source types and
per-source configs (:83-120).
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field
from typing import Optional

KNOWLEDGE_TYPES = (
    "runbook", "postmortem", "known-issue", "architecture", "troubleshooting",
    "procedure", "faq", "reference",
)

CHUNK_TYPES = ("procedure", "context", "command", "table", "list", "text")


@dataclass
class KnowledgeChunk:
    chunk_id: str
    doc_id: str
    content: str
    section: str = ""
    chunk_type: str = "text"
    position: int = 0


@dataclass
class KnowledgeDocument:
    doc_id: str
    title: str
    content: str
    knowledge_type: str = "reference"
    source: str = "filesystem"
    source_ref: str = ""  # path / page id / file id
    services: list[str] = field(default_factory=list)
    symptoms: list[str] = field(default_factory=list)
    severity: Optional[str] = None
    tags: list[str] = field(default_factory=list)
    updated_at: float = field(default_factory=time.time)
    chunks: list[KnowledgeChunk] = field(default_factory=list)

    @staticmethod
    def make_id(source: str, source_ref: str) -> str:
        return hashlib.md5(f"{source}:{source_ref}".encode()).hexdigest()[:16]


@dataclass
class SearchHit:
    chunk: KnowledgeChunk
    doc: KnowledgeDocument
    score: float
    mode: str = "fts"  # fts | vector | hybrid
