"""Native (C++) host-runtime components, loaded via ctypes.

The serving engine's host-side hot path — page allocation, prefix-cache
probing, block hashing — runs here when the compiled library is available,
with the pure-Python implementations in :mod:`runbookai_tpu.engine.kv_cache`
as a behavior-identical fallback (the test suite diffs the two backends over
randomized op sequences).

Build model: a single translation unit (``src/runtime.cpp``) compiled on
first use with ``g++ -O2 -shared -fPIC`` into ``_build/libruntime.so`` and
cached by source mtime. No pybind11 (not in the image) — plain C ABI +
ctypes. Set ``RUNBOOKAI_NATIVE=0`` to force the Python fallback.

The reference has no first-party native code (SURVEY.md §2.9); this module is
new construction for the TPU build's runtime layer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

_SRC = Path(__file__).parent / "src" / "runtime.cpp"
_BUILD_DIR = Path(__file__).parent / "_build"
_LIB_PATH = _BUILD_DIR / "libruntime.so"

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _compile() -> bool:
    # Build to a process-private temp path and os.replace() into place so
    # concurrent first-compiles can't interleave writes into the cached .so.
    tmp = _LIB_PATH.with_suffix(f".{os.getpid()}.tmp.so")
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
           str(_SRC), "-o", str(tmp)]
    try:
        _BUILD_DIR.mkdir(exist_ok=True)
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if res.returncode != 0 or not tmp.is_file():
            return False
        os.replace(tmp, _LIB_PATH)
    except (OSError, subprocess.TimeoutExpired):
        # Read-only installs (site-packages, runfiles) fall back to Python.
        return False
    finally:
        tmp.unlink(missing_ok=True)
    return _LIB_PATH.is_file()


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("RUNBOOKAI_NATIVE", "1") == "0":
        return None
    try:
        stale = (not _LIB_PATH.is_file()
                 or (_SRC.is_file()
                     and _LIB_PATH.stat().st_mtime < _SRC.stat().st_mtime))
    except OSError:
        stale = not _LIB_PATH.is_file()
    if stale and (not _SRC.is_file() or not _compile()):
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError:
        return None

    lib.rk_alloc_create.restype = ctypes.c_void_p
    lib.rk_alloc_create.argtypes = [ctypes.c_int64]
    lib.rk_alloc_destroy.argtypes = [ctypes.c_void_p]
    lib.rk_alloc_free_pages.restype = ctypes.c_int64
    lib.rk_alloc_free_pages.argtypes = [ctypes.c_void_p]
    lib.rk_alloc_cached_pages.restype = ctypes.c_int64
    lib.rk_alloc_cached_pages.argtypes = [ctypes.c_void_p]
    lib.rk_alloc_alloc.restype = ctypes.c_int
    lib.rk_alloc_alloc.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                   ctypes.POINTER(ctypes.c_int64)]
    lib.rk_alloc_release.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
    lib.rk_alloc_register.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64]
    lib.rk_alloc_lookup.restype = ctypes.c_int64
    lib.rk_alloc_lookup.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.rk_alloc_acquire.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.rk_alloc_is_retired.restype = ctypes.c_int
    lib.rk_alloc_is_retired.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.rk_hash_blocks.restype = ctypes.c_int64
    lib.rk_hash_blocks.argtypes = [ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
                                   ctypes.c_int64, ctypes.c_int64,
                                   ctypes.POINTER(ctypes.c_uint64)]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


class NativePageAllocator:
    """ctypes wrapper with the same interface as the Python ``PageAllocator``."""

    NULL_PAGE = 0

    def __init__(self, num_pages: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime library unavailable")
        if num_pages < 2:
            raise ValueError("need at least 2 pages (one reserved null page)")
        self._lib = lib
        self.num_pages = num_pages
        self._h = ctypes.c_void_p(lib.rk_alloc_create(num_pages))
        if not self._h:
            raise RuntimeError("rk_alloc_create failed")

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.rk_alloc_destroy(h)
            self._h = None

    @property
    def free_pages(self) -> int:
        return self._lib.rk_alloc_free_pages(self._h)

    @property
    def cached_pages(self) -> int:
        return self._lib.rk_alloc_cached_pages(self._h)

    def alloc(self, n: int) -> list[int]:
        out = (ctypes.c_int64 * max(n, 1))()
        if self._lib.rk_alloc_alloc(self._h, n, out) != 0:
            raise MemoryError(
                f"KV page pool exhausted: want {n}, have {self.free_pages}")
        return list(out[:n])

    def free(self, pages: Sequence[int]) -> None:
        n = len(pages)
        arr = (ctypes.c_int64 * max(n, 1))(*pages)
        self._lib.rk_alloc_release(self._h, arr, n)

    def register(self, page: int, block_hash: int) -> None:
        self._lib.rk_alloc_register(self._h, page, block_hash & 0xFFFFFFFFFFFFFFFF)

    def lookup(self, block_hash: int) -> Optional[int]:
        p = self._lib.rk_alloc_lookup(self._h, block_hash & 0xFFFFFFFFFFFFFFFF)
        return None if p < 0 else p

    def acquire(self, page: int) -> None:
        self._lib.rk_alloc_acquire(self._h, page)

    def is_retired(self, page: int) -> bool:
        return bool(self._lib.rk_alloc_is_retired(self._h, page))


def hash_blocks_native(token_ids: Sequence[int], page_size: int,
                       max_blocks: Optional[int] = None) -> Optional[list[int]]:
    """FNV-1a block hash chain in C++; None when the library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    toks = np.ascontiguousarray(token_ids, dtype=np.int32)
    cap = len(toks) // page_size if page_size else 0
    out = np.empty(max(cap, 1), dtype=np.uint64)
    n = lib.rk_hash_blocks(
        toks.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(toks),
        page_size, -1 if max_blocks is None else max_blocks,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    return [int(h) for h in out[:n]]


def make_page_allocator(num_pages: int):
    """Native allocator when the library loads, else the Python fallback."""
    if available():
        return NativePageAllocator(num_pages)
    from runbookai_tpu.engine.kv_cache import PageAllocator

    return PageAllocator(num_pages)
