// Native host-runtime components for the TPU serving engine.
//
// The continuous-batching hot path does per-step page-table bookkeeping and,
// on every admission, a hash-chain probe over up-to-max_seq_len/page_size
// blocks. This file implements the page allocator + prefix-cache index and
// the FNV-1a block hasher behind a C ABI consumed via ctypes
// (runbookai_tpu/native/__init__.py). Semantics are bit-identical to the
// pure-Python PageAllocator/hash_blocks in engine/kv_cache.py — the test
// suite runs both backends through randomized op sequences and diffs state.
//
// No reference counterpart: the reference (RunbookAI) has no model runtime at
// all (SURVEY.md §2.9) — its only native dependency is better-sqlite3.

#include <cstddef>
#include <cstdint>
#include <list>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t kNullPage = 0;

struct Allocator {
  int64_t num_pages;
  std::vector<int64_t> free_stack;               // pop_back == Python list.pop()
  std::unordered_map<int64_t, int64_t> ref;      // page -> live refcount
  std::list<int64_t> retired_lru;                // front = oldest retired
  std::unordered_map<int64_t, std::list<int64_t>::iterator> retired_pos;
  std::unordered_map<uint64_t, int64_t> hash_to_page;
  std::unordered_map<int64_t, uint64_t> page_to_hash;

  explicit Allocator(int64_t n) : num_pages(n) {
    free_stack.reserve(static_cast<size_t>(n - 1));
    for (int64_t p = n - 1; p >= 1; --p) free_stack.push_back(p);
  }

  int64_t free_pages() const {
    return static_cast<int64_t>(free_stack.size() + retired_lru.size());
  }

  void invalidate(int64_t page) {
    auto it = page_to_hash.find(page);
    if (it == page_to_hash.end()) return;
    auto h = hash_to_page.find(it->second);
    if (h != hash_to_page.end() && h->second == page) hash_to_page.erase(h);
    page_to_hash.erase(it);
  }

  // Returns 0 on success, -1 when the pool is exhausted (nothing mutated).
  int alloc(int64_t n, int64_t* out) {
    if (n > free_pages()) return -1;
    for (int64_t i = 0; i < n; ++i) {
      int64_t p;
      if (!free_stack.empty()) {
        p = free_stack.back();
        free_stack.pop_back();
      } else {
        p = retired_lru.front();
        retired_lru.pop_front();
        retired_pos.erase(p);
        invalidate(p);
      }
      ref[p] = 1;
      out[i] = p;
    }
    return 0;
  }

  void release(const int64_t* pages, int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
      int64_t p = pages[i];
      if (p == kNullPage) continue;
      auto rp = retired_pos.find(p);
      if (rp != retired_pos.end()) {
        // Double-free of a retired page: Python's OrderedDict assignment +
        // move_to_end dedups but refreshes LRU position — mirror that.
        retired_lru.erase(rp->second);
        retired_lru.push_back(p);
        rp->second = std::prev(retired_lru.end());
        continue;
      }
      auto it = ref.find(p);
      int64_t r = (it == ref.end() ? 0 : it->second) - 1;
      if (r > 0) {
        it->second = r;
        continue;
      }
      if (it != ref.end()) ref.erase(it);
      if (page_to_hash.count(p)) {
        retired_lru.push_back(p);
        retired_pos[p] = std::prev(retired_lru.end());
      } else {
        free_stack.push_back(p);
      }
    }
  }

  void register_hash(int64_t page, uint64_t h) {
    if (page == kNullPage || hash_to_page.count(h)) return;  // first writer wins
    invalidate(page);
    page_to_hash[page] = h;
    hash_to_page[h] = page;
  }

  int64_t lookup(uint64_t h) const {
    auto it = hash_to_page.find(h);
    return it == hash_to_page.end() ? -1 : it->second;
  }

  void acquire(int64_t page) {
    auto it = retired_pos.find(page);
    if (it != retired_pos.end()) {
      retired_lru.erase(it->second);
      retired_pos.erase(it);
      ref[page] = 1;
    } else {
      ref[page] += 1;  // value-initialized to 0 when absent
    }
  }
};

}  // namespace

extern "C" {

void* rk_alloc_create(int64_t num_pages) {
  if (num_pages < 2) return nullptr;
  return new Allocator(num_pages);
}

void rk_alloc_destroy(void* a) { delete static_cast<Allocator*>(a); }

int64_t rk_alloc_free_pages(void* a) {
  return static_cast<Allocator*>(a)->free_pages();
}

int64_t rk_alloc_cached_pages(void* a) {
  return static_cast<int64_t>(static_cast<Allocator*>(a)->retired_lru.size());
}

int rk_alloc_alloc(void* a, int64_t n, int64_t* out) {
  return static_cast<Allocator*>(a)->alloc(n, out);
}

void rk_alloc_release(void* a, const int64_t* pages, int64_t n) {
  static_cast<Allocator*>(a)->release(pages, n);
}

void rk_alloc_register(void* a, int64_t page, uint64_t hash) {
  static_cast<Allocator*>(a)->register_hash(page, hash);
}

int64_t rk_alloc_lookup(void* a, uint64_t hash) {
  return static_cast<Allocator*>(a)->lookup(hash);
}

void rk_alloc_acquire(void* a, int64_t page) {
  static_cast<Allocator*>(a)->acquire(page);
}

int rk_alloc_is_retired(void* a, int64_t page) {
  return static_cast<Allocator*>(a)->retired_pos.count(page) ? 1 : 0;
}

// FNV-1a hash chain over full pages of token ids; returns the block count.
// Mirrors hash_blocks() in engine/kv_cache.py exactly.
int64_t rk_hash_blocks(const int32_t* tokens, int64_t n_tokens,
                       int64_t page_size, int64_t max_blocks, uint64_t* out) {
  int64_t n_full = n_tokens / page_size;
  if (max_blocks >= 0 && max_blocks < n_full) n_full = max_blocks;
  uint64_t h = 0xCBF29CE484222325ULL;
  for (int64_t b = 0; b < n_full; ++b) {
    for (int64_t i = b * page_size; i < (b + 1) * page_size; ++i) {
      h ^= static_cast<uint64_t>(static_cast<int64_t>(tokens[i]) + 1);
      h *= 0x100000001B3ULL;
    }
    out[b] = h;
  }
  return n_full;
}

}  // extern "C"
