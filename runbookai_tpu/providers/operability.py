"""Operability-context providers: pluggable external context backends.

Parity target: reference ``src/providers/operability-context/`` — ``types.ts``
(355 LoC: provider-agnostic contract with capabilities :23-32, confidence
scores, provenance, change claims), ``adapters/http.ts`` (413 LoC generic HTTP
adapter), named adapters (sourcegraph / entireio / runbook-context / custom),
``factory.ts``, ``registry.ts``, ``reconcile.ts``. Config-driven selection
(utils/config.ts:66-73 equivalent: ``providers.operability_context``).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Optional

CAPABILITIES = (
    "session_ingest",  # accept tool/session event streams
    "blast_radius",  # service impact estimation
    "similar_incidents",  # retrieval of alike past incidents
    "change_claims",  # recent-change claims about services
    "fact_lookup",  # service facts (owners, endpoints, configs)
)


@dataclass
class Provenance:
    source: str
    retrieved_at: float = field(default_factory=time.time)
    url: Optional[str] = None


@dataclass
class ContextClaim:
    """One claim about the environment, with confidence + provenance."""

    subject: str  # service / resource name
    predicate: str  # e.g. "deployed", "config_changed", "scaled"
    value: Any = None
    confidence: float = 0.5
    provenance: Optional[Provenance] = None
    ts: float = field(default_factory=time.time)


@dataclass
class SimilarIncident:
    incident_id: str
    title: str
    similarity: float
    root_cause: Optional[str] = None


class OperabilityAdapter:
    """Provider-agnostic contract. Adapters override what they support."""

    name = "base"
    capabilities: tuple[str, ...] = ()

    def supports(self, capability: str) -> bool:
        return capability in self.capabilities

    async def ingest_session(self, events: list[dict[str, Any]]) -> dict[str, Any]:
        raise NotImplementedError

    async def blast_radius(self, service: str) -> list[str]:
        raise NotImplementedError

    async def similar_incidents(self, description: str) -> list[SimilarIncident]:
        raise NotImplementedError

    async def change_claims(self, service: str) -> list[ContextClaim]:
        raise NotImplementedError

    async def fact_lookup(self, service: str) -> dict[str, Any]:
        raise NotImplementedError


class HTTPAdapter(OperabilityAdapter):
    """Generic REST adapter (reference adapters/http.ts): capability routes
    are conventional paths under a base URL."""

    name = "http"

    def __init__(self, base_url: str, token: Optional[str] = None,
                 capabilities: Optional[list[str]] = None, name: str = "http"):
        self.base = base_url.rstrip("/")
        self.token = token
        self.name = name
        self.capabilities = tuple(capabilities or CAPABILITIES)

    async def _request(self, method: str, path: str,
                       payload: Optional[dict] = None) -> Any:
        import requests

        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"

        def call():
            resp = requests.request(method, f"{self.base}{path}",
                                    headers=headers, json=payload, timeout=20)
            resp.raise_for_status()
            return resp.json() if resp.content else {}

        return await asyncio.to_thread(call)

    async def ingest_session(self, events):
        return await self._request("POST", "/v1/sessions/ingest",
                                   {"events": events})

    async def blast_radius(self, service):
        data = await self._request("GET", f"/v1/services/{service}/blast-radius")
        return [str(s) for s in data.get("services", [])]

    async def similar_incidents(self, description):
        data = await self._request("POST", "/v1/incidents/similar",
                                   {"description": description})
        return [SimilarIncident(
            incident_id=str(i.get("id", "")), title=str(i.get("title", "")),
            similarity=float(i.get("similarity", 0)),
            root_cause=i.get("root_cause"),
        ) for i in data.get("incidents", [])]

    async def change_claims(self, service):
        data = await self._request("GET", f"/v1/services/{service}/changes")
        return [ContextClaim(
            subject=service, predicate=str(c.get("type", "changed")),
            value=c.get("detail"), confidence=float(c.get("confidence", 0.5)),
            provenance=Provenance(source=self.name, url=c.get("url")),
        ) for c in data.get("changes", [])]

    async def fact_lookup(self, service):
        return await self._request("GET", f"/v1/services/{service}")


class SourcegraphAdapter(HTTPAdapter):
    """Code-search backend: change claims from recent commits/diffs."""

    def __init__(self, base_url: str, token: Optional[str] = None):
        super().__init__(base_url, token, ["change_claims", "fact_lookup"],
                         name="sourcegraph")


class EntireIOAdapter(HTTPAdapter):
    def __init__(self, base_url: str, token: Optional[str] = None):
        super().__init__(base_url, token,
                         ["session_ingest", "similar_incidents", "blast_radius"],
                         name="entireio")


class RunbookContextAdapter(HTTPAdapter):
    def __init__(self, base_url: str, token: Optional[str] = None):
        super().__init__(base_url, token, list(CAPABILITIES),
                         name="runbook-context")


class LocalGraphAdapter(OperabilityAdapter):
    """In-process fallback over the local service graph + knowledge store —
    gives blast_radius / similar_incidents without any external backend."""

    name = "local"
    capabilities = ("blast_radius", "similar_incidents", "fact_lookup")

    def __init__(self, graph=None, retriever=None):
        self.graph = graph
        self.retriever = retriever

    async def blast_radius(self, service):
        if self.graph is None:
            return []
        return self.graph.downstream_impact(service)

    async def similar_incidents(self, description):
        if self.retriever is None:
            return []
        hits = self.retriever.hybrid.search(description, limit=5,
                                            knowledge_type="postmortem")
        return [SimilarIncident(
            incident_id=h.doc.doc_id, title=h.doc.title,
            similarity=min(1.0, h.score), root_cause=None,
        ) for h in hits]

    async def fact_lookup(self, service):
        if self.graph is None or service not in self.graph.nodes:
            return {}
        node = self.graph.nodes[service]
        return {"name": node.name, "team": node.team, "tier": node.tier,
                "tags": node.tags,
                "depends_on": self.graph.dependencies_of(service)}


def create_adapter(config, graph=None, retriever=None) -> Optional[OperabilityAdapter]:
    """Factory (reference factory.ts): config-driven adapter selection."""
    oc = config.providers.operability_context
    if not oc.enabled:
        return None
    if oc.adapter == "sourcegraph" and oc.base_url:
        return SourcegraphAdapter(oc.base_url, oc.token)
    if oc.adapter == "entireio" and oc.base_url:
        return EntireIOAdapter(oc.base_url, oc.token)
    if oc.adapter == "runbook-context" and oc.base_url:
        return RunbookContextAdapter(oc.base_url, oc.token)
    if oc.adapter in ("http", "custom") and oc.base_url:
        return HTTPAdapter(oc.base_url, oc.token,
                           oc.capabilities or None, name=oc.adapter)
    return LocalGraphAdapter(graph=graph, retriever=retriever)


class AdapterRegistry:
    """Multiple adapters with capability routing (reference registry.ts)."""

    def __init__(self) -> None:
        self._adapters: list[OperabilityAdapter] = []

    def register(self, adapter: OperabilityAdapter) -> None:
        self._adapters.append(adapter)

    def for_capability(self, capability: str) -> list[OperabilityAdapter]:
        return [a for a in self._adapters if a.supports(capability)]


def reconcile_claims(claims: list[ContextClaim],
                     min_confidence: float = 0.3) -> list[ContextClaim]:
    """Merge duplicate (subject, predicate) claims (reference reconcile.ts):
    keep the highest-confidence instance, boost confidence when independent
    sources agree, drop below-threshold leftovers."""
    grouped: dict[tuple[str, str], list[ContextClaim]] = {}
    for claim in claims:
        grouped.setdefault((claim.subject, claim.predicate), []).append(claim)
    out: list[ContextClaim] = []
    for group in grouped.values():
        best = max(group, key=lambda c: c.confidence)
        sources = {c.provenance.source for c in group if c.provenance}
        if len(sources) > 1:
            best.confidence = min(1.0, best.confidence + 0.15 * (len(sources) - 1))
        if best.confidence >= min_confidence:
            out.append(best)
    return sorted(out, key=lambda c: c.confidence, reverse=True)
