"""Analyzer core: findings, the shared one-pass AST walker, noqa handling.

Design constraints (package docstring has the why):

- stdlib only — the gate must run without jax installed and in milliseconds;
- ONE ``ast`` walk per file: rules are event subscribers on ``_Walker``,
  which tracks the cross-cutting scope state every rule needs (enclosing
  function + jit-reachability, traced parameter names, lock-scope depth,
  enclosing class) so no rule re-derives it;
- suppression is lexical: a ``# runbook: noqa[RULE]`` comment anywhere on
  the lines a flagged statement spans silences that rule for the statement.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator, Optional, Sequence

# Severities are informational ordering for humans; the gate fails on any
# non-baselined finding regardless of severity.
class Severity:
    ERROR = "error"
    WARNING = "warning"


PARSE_RULE_ID = "RBK000"  # un-parseable file: always an error, never baselined away silently

# Bare `noqa` (suppress-all) only counts when NOT followed by a bracket or
# more word chars: a malformed `noqa[RBK002` (unclosed) or `noqa-ish` must
# suppress NOTHING — silently widening a typo'd one-rule suppression to
# all rules is how gates rot.
_NOQA_RE = re.compile(
    r"#\s*runbook:\s*noqa"
    r"(?:\[(?P<rules>[A-Za-z0-9_,\s]*)\]|(?![\w\[-]))", re.IGNORECASE)

# Attributes of a traced array that are static under jit (shape metadata is
# known at trace time — branching on them does NOT retrace or sync).
_STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "itemsize"})

# Calls whose result is static even when applied to a traced value.
_STATIC_CALLS = frozenset({"len", "isinstance", "type", "hasattr", "getattr"})

# Path components that mark a module as serving hot path for path-scoped
# rules (RBK002 keys on "engine"; RBK006 on the full set).
HOT_PATH_TAGS = frozenset({"engine", "ops", "model", "models", "parallel"})


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    severity: str
    message: str
    # Enclosing symbol ("Class.method", "func", "" at module level) — the
    # line-move-tolerant anchor CI fingerprints key on.
    symbol: str = ""

    @property
    def baseline_key(self) -> str:
        # Line numbers churn on unrelated edits; baselines key on
        # (file, rule) with a count so the gate survives refactors that
        # move (but don't add) grandfathered findings.
        return f"{self.path}:{self.rule}"

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.severity}] {self.message}")

    def to_json(self) -> dict:
        return {"path": self.path, "line": self.line, "col": self.col,
                "rule": self.rule, "severity": self.severity,
                "symbol": self.symbol, "message": self.message}


def finding_fingerprints(findings: Sequence[Finding]) -> list[str]:
    """Stable per-finding ids CI can diff across commits.

    Hash of (rule, path, symbol, ordinal) — ordinal is the finding's rank
    among same-keyed findings in line order, so moving a function around a
    file (or adding unrelated lines above it) keeps the fingerprint, while
    a SECOND violation of the same rule in the same symbol mints a new one.
    Line and column deliberately excluded.
    """
    import hashlib

    ordinals: dict[tuple[str, str, str], int] = {}
    out: list[str] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        key = (f.rule, f.path, f.symbol)
        n = ordinals.get(key, 0)
        ordinals[key] = n + 1
        out.append(hashlib.blake2b(
            f"{f.rule}|{f.path}|{f.symbol}|{n}".encode(),
            digest_size=8).hexdigest())
    # Re-order to match the caller's finding order.
    order = sorted(range(len(findings)),
                   key=lambda i: (findings[i].path, findings[i].line,
                                  findings[i].col, findings[i].rule))
    by_input = [""] * len(findings)
    for rank, idx in enumerate(order):
        by_input[idx] = out[rank]
    return by_input


class Rule:
    """Base class: subscribe to walker events by overriding hooks.

    Hooks yield ``(node, message)`` pairs; the walker anchors the finding at
    the node and applies noqa suppression over the node's line span.
    """

    rule_id: str = "RBK???"
    severity: str = Severity.WARNING
    description: str = ""

    def on_call(self, ctx: "ModuleContext", scope: "Scope",
                node: ast.Call) -> Iterator[tuple[ast.AST, str]]:
        return iter(())

    def on_branch(self, ctx: "ModuleContext", scope: "Scope",
                  node: ast.stmt) -> Iterator[tuple[ast.AST, str]]:
        """``if`` / ``while`` statements."""
        return iter(())

    def on_attr_write(self, ctx: "ModuleContext", scope: "Scope",
                      node: ast.AST, attr: str) -> Iterator[tuple[ast.AST, str]]:
        """Assignment / augmented assignment to ``self.<attr>``."""
        return iter(())

    def finish(self, ctx: "ModuleContext") -> Iterator[tuple[ast.AST, str]]:
        """Called once after the walk — for rules that aggregate."""
        return iter(())


# --------------------------------------------------------------------------- #
# helpers shared by rules                                                     #
# --------------------------------------------------------------------------- #


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def mentions_traced(node: ast.AST, traced: frozenset[str]) -> bool:
    """True when ``node`` references a traced name in a value position.

    Shielded contexts do not count: ``x is None`` / ``x is not None``
    (host-level structure checks), ``x.shape``-family attributes, and
    ``len()/isinstance()``-family calls are all static under jit.
    """
    if not traced:
        return False
    if isinstance(node, ast.Name):
        return node.id in traced
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return False
        return mentions_traced(node.value, traced)
    if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn in _STATIC_CALLS:
            return False
        parts: list[ast.AST] = list(node.args)
        parts.extend(kw.value for kw in node.keywords)
        if isinstance(node.func, ast.Attribute):
            parts.append(node.func)  # method receiver may be traced
        return any(mentions_traced(c, traced) for c in parts)
    if isinstance(node, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return False  # identity checks never force a device sync
        return any(mentions_traced(c, traced)
                   for c in [node.left, *node.comparators])
    return any(mentions_traced(c, traced) for c in ast.iter_child_nodes(node))


# --------------------------------------------------------------------------- #
# per-module context: tags, noqa lines, jit reachability                      #
# --------------------------------------------------------------------------- #


def _path_tags(path: str) -> frozenset[str]:
    parts = Path(path).parts
    return frozenset(p.lower() for p in parts[:-1] if p not in (".", ".."))


def _noqa_lines(source: str) -> dict[int, Optional[frozenset[str]]]:
    """line → suppressed rule ids (None = all rules).

    Scans real COMMENT tokens (via ``tokenize``), not raw lines — a string
    literal *containing* the noqa syntax (an error message quoting it, a
    test fixture) must never suppress findings on its own statement.
    """
    import io
    import tokenize

    out: dict[int, Optional[frozenset[str]]] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out  # un-tokenizable files never reach the walker anyway
    for tok in tokens:
        if tok.type != tokenize.COMMENT or "runbook" not in tok.string.lower():
            continue
        m = _NOQA_RE.search(tok.string)
        if not m:
            continue
        rules = m.group("rules")
        if rules is None or not rules.strip():
            out[tok.start[0]] = None
        else:
            out[tok.start[0]] = frozenset(
                r.strip().upper() for r in rules.split(",") if r.strip())
    return out


@dataclass
class _FuncInfo:
    node: ast.AST
    jit_decorated: bool = False
    static_params: frozenset[str] = frozenset()
    jit_reachable: bool = False  # decorated OR in same-module closure
    # Traced-by-propagation param names for closure-reached functions:
    # a param only counts as traced if some jit-reachable call site passes
    # it an expression that itself mentions a traced value (so shape/config
    # helpers called from jit with static ints stay clean).
    traced_params: set[str] = field(default_factory=set)


def _jit_decorator_info(fn: ast.FunctionDef | ast.AsyncFunctionDef,
                        ) -> Optional[frozenset[str]]:
    """If ``fn`` is jit-decorated, return its static param names, else None.

    Recognized forms: ``@jax.jit``, ``@jit``, ``@pjit``/``@jax.pjit``,
    ``@partial(jax.jit, ...)`` / ``@functools.partial(jax.jit, ...)`` with
    literal ``static_argnames`` / ``static_argnums``.
    """
    jit_names = {"jax.jit", "jit", "pjit", "jax.pjit"}
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target)
        statics: set[str] = set()
        is_jit = name in jit_names
        if (isinstance(dec, ast.Call)
                and name in {"partial", "functools.partial"}
                and dec.args and dotted_name(dec.args[0]) in jit_names):
            is_jit = True
        if not is_jit:
            continue
        if isinstance(dec, ast.Call):
            all_params = [a.arg for a in (*fn.args.posonlyargs, *fn.args.args)]
            for kw in dec.keywords:
                if kw.arg == "static_argnames":
                    for el in ast.walk(kw.value):
                        if isinstance(el, ast.Constant) and isinstance(el.value, str):
                            statics.add(el.value)
                elif kw.arg == "static_argnums":
                    for el in ast.walk(kw.value):
                        if isinstance(el, ast.Constant) and isinstance(el.value, int):
                            if 0 <= el.value < len(all_params):
                                statics.add(all_params[el.value])
        # kwonly args of a jit function are keyword-static by convention in
        # this codebase (page_size=..., attn_impl=...): jax requires them to
        # be static anyway (jit rejects traced kwonly defaults in our usage).
        statics.update(a.arg for a in fn.args.kwonlyargs)
        return frozenset(statics)
    return None


def _param_names(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> list[str]:
    a = fn.args
    names = [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return [n for n in names if n not in ("self", "cls")]


def iter_functions(tree: ast.Module) -> list[tuple[str, Optional[str], ast.AST]]:
    """``(qualname, enclosing_class, node)`` for every function def, in
    source order. Qualnames join enclosing class/function names with dots
    (``Cls.meth``, ``outer.inner``) — the shared spelling the project
    index, jit seeds, and finding fingerprints all key on."""
    out: list[tuple[str, Optional[str], ast.AST]] = []

    def _walk(node: ast.AST, stack: tuple[str, ...],
              cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = ".".join((*stack, child.name))
                out.append((qual, cls, child))
                _walk(child, (*stack, child.name), cls)
            elif isinstance(child, ast.ClassDef):
                _walk(child, (*stack, child.name), child.name)
            else:
                _walk(child, stack, cls)

    _walk(tree, (), None)
    return out


def _jit_table(tree: ast.Module,
               seeds: Optional[dict[str, frozenset[str]]] = None,
               ) -> dict[ast.AST, _FuncInfo]:
    """Every function def → jit info, with same-module closure propagation.

    "jit-reachable" is approximated statically as: directly jit-decorated,
    called by name from a jit-reachable function in the same module, or
    seeded by the PROJECT pass (``seeds``: qualname → traced param names,
    derived from cross-module call edges — the whole-program upgrade that
    closed the documented "same module only" gap).
    """
    infos: dict[ast.AST, _FuncInfo] = {}
    by_name: dict[str, _FuncInfo] = {}
    for qual, _cls, node in iter_functions(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            statics = _jit_decorator_info(node)
            info = _FuncInfo(node=node, jit_decorated=statics is not None,
                             static_params=statics or frozenset(),
                             jit_reachable=statics is not None)
            if info.jit_decorated:
                info.traced_params = set(_param_names(node)) - set(statics)
            seeded = (seeds or {}).get(qual)
            if seeded is not None:
                info.jit_reachable = True
                info.traced_params |= set(seeded) - set(info.static_params)
            infos[node] = info
            # Last definition wins for duplicate names — matches runtime.
            by_name[node.name] = info

    def _callee_params(fn) -> list[str]:
        return [a.arg for a in (*fn.args.posonlyargs, *fn.args.args)
                if a.arg not in ("self", "cls")]

    # Fixed-point closure over bare-name calls from jit-reachable bodies,
    # propagating traced-ness PER PARAMETER from actual call-site args.
    changed = True
    while changed:
        changed = False
        for info in infos.values():
            if not info.jit_reachable:
                continue
            caller_traced = frozenset(info.traced_params)
            for call in ast.walk(info.node):
                if not (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Name)):
                    continue
                callee = by_name.get(call.func.id)
                if callee is None or callee is info:
                    continue
                if not callee.jit_reachable:
                    callee.jit_reachable = True
                    changed = True
                params = _callee_params(callee.node)
                hits: set[str] = set()
                for idx, arg in enumerate(call.args):
                    if idx < len(params) and mentions_traced(arg, caller_traced):
                        hits.add(params[idx])
                for kw in call.keywords:
                    if kw.arg and mentions_traced(kw.value, caller_traced):
                        hits.add(kw.arg)
                hits -= callee.static_params
                if not hits <= callee.traced_params:
                    callee.traced_params |= hits
                    changed = True
    return infos


@dataclass
class ModuleContext:
    path: str
    source: str
    tree: ast.Module
    tags: frozenset[str]
    noqa: dict[int, Optional[frozenset[str]]]
    jit_info: dict[ast.AST, _FuncInfo]
    lines: list[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        if not self.lines:
            self.lines = self.source.splitlines()

    def _line_suppresses(self, line: int, rule_id: str) -> bool:
        if line not in self.noqa:
            return False
        rules = self.noqa[line]
        return rules is None or rule_id in rules

    def suppressed(self, rule_id: str, node: ast.AST) -> bool:
        start = getattr(node, "lineno", None)
        if start is None:
            return False
        end = getattr(node, "end_lineno", start) or start
        # 1) noqa anywhere on the lines the statement spans.
        for line in range(start, end + 1):
            if self._line_suppresses(line, rule_id):
                return True
        # 2) noqa in the contiguous comment block immediately above (long
        #    dispatch lines can't fit a trailing comment + reason string).
        lines = self.lines
        line = start - 1
        while 1 <= line <= len(lines) and lines[line - 1].lstrip().startswith("#"):
            if self._line_suppresses(line, rule_id):
                return True
            line -= 1
        return False


@dataclass
class Scope:
    """Cross-cutting state rules read; maintained by the walker."""
    in_jit: bool = False
    traced_params: frozenset[str] = frozenset()
    lock_depth: int = 0
    class_name: Optional[str] = None
    func_name: Optional[str] = None

    @property
    def in_lock(self) -> bool:
        return self.lock_depth > 0


# "lock" as a word segment: matches `_lock`, `lock`, `step_lock`, `rlock`,
# `lock_a`; must NOT match `block`/`on_block`/`block_pages` (this codebase
# is full of KV *block* state) — substring matching made those ERRORs.
_LOCK_SEG_RE = re.compile(r"(?:^|_)(?:r|w|rw)?locks?(?:_|$|ed\b)")


def _is_lock_ctx(item: ast.withitem) -> bool:
    name = dotted_name(item.context_expr)
    if name is None and isinstance(item.context_expr, ast.Call):
        name = dotted_name(item.context_expr.func)
    if name is None:
        return False
    return any(_LOCK_SEG_RE.search(seg) for seg in name.lower().split("."))


class _Walker(ast.NodeVisitor):
    """Single traversal that fans each node out to every subscribed rule."""

    def __init__(self, ctx: ModuleContext, rules: Sequence[Rule]):
        self.ctx = ctx
        self.rules = rules
        self.scope = Scope()
        self.findings: list[Finding] = []
        self._func_stack: list[_FuncInfo] = []
        self._qual: list[str] = []  # enclosing class/function name stack

    # ----------------------------------------------------------- plumbing

    def _emit(self, rule: Rule, results: Iterable[tuple[ast.AST, str]]) -> None:
        symbol = ".".join(self._qual)
        for node, message in results:
            if self.ctx.suppressed(rule.rule_id, node):
                continue
            self.findings.append(Finding(
                path=self.ctx.path,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                rule=rule.rule_id,
                severity=rule.severity,
                message=message,
                symbol=symbol,
            ))

    def run(self) -> list[Finding]:
        self.visit(self.ctx.tree)
        for rule in self.rules:
            self._emit(rule, rule.finish(self.ctx))
        self.findings.sort(key=lambda f: (f.line, f.col, f.rule))
        return self.findings

    # -------------------------------------------------------------- scope

    def _visit_function(self, node) -> None:
        info = self.ctx.jit_info.get(node)
        prev = self.scope
        if prev.in_jit:
            # Nested def inside a jit body (scan/cond bodies): its params
            # are carries/operands — traced by construction.
            traced = prev.traced_params | frozenset(_param_names(node))
            in_jit = True
        elif info is not None and info.jit_reachable:
            # Decorated roots: params minus statics. Closure-reached
            # helpers: only params that some jit call site actually fed a
            # traced expression (per-param propagation in _jit_table).
            traced = frozenset(info.traced_params)
            in_jit = True
        else:
            traced = frozenset()
            in_jit = False
        # lock_depth resets: a def nested inside a `with lock:` block is
        # only *defined* there — its body runs later, lock not held.
        self.scope = Scope(in_jit=in_jit, traced_params=traced,
                           lock_depth=0,
                           class_name=prev.class_name, func_name=node.name)
        self._func_stack.append(info or _FuncInfo(node=node))
        self._qual.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self._qual.pop()
            self._func_stack.pop()
            self.scope = prev

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev = self.scope
        self.scope = Scope(in_jit=False, traced_params=frozenset(),
                           lock_depth=prev.lock_depth, class_name=node.name,
                           func_name=prev.func_name)
        self._qual.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self._qual.pop()
            self.scope = prev

    def _visit_with(self, node) -> None:
        locked = any(_is_lock_ctx(i) for i in node.items)
        if locked:
            self.scope.lock_depth += 1
        try:
            self.generic_visit(node)
        finally:
            if locked:
                self.scope.lock_depth -= 1

    visit_With = _visit_with
    visit_AsyncWith = _visit_with  # asyncio.Lock stalls coroutines the same

    # ------------------------------------------------------------- events

    def visit_Call(self, node: ast.Call) -> None:
        for rule in self.rules:
            self._emit(rule, rule.on_call(self.ctx, self.scope, node))
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        for rule in self.rules:
            self._emit(rule, rule.on_branch(self.ctx, self.scope, node))
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        for rule in self.rules:
            self._emit(rule, rule.on_branch(self.ctx, self.scope, node))
        self.generic_visit(node)

    def _attr_write(self, node: ast.AST, targets: Iterable[ast.AST]) -> None:
        for target in targets:
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                for rule in self.rules:
                    self._emit(rule, rule.on_attr_write(
                        self.ctx, self.scope, node, target.attr))

    def visit_Assign(self, node: ast.Assign) -> None:
        self._attr_write(node, node.targets)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._attr_write(node, [node.target])
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._attr_write(node, [node.target])
        self.generic_visit(node)


# --------------------------------------------------------------------------- #
# drivers                                                                     #
# --------------------------------------------------------------------------- #

_SKIP_DIRS = frozenset({"__pycache__", ".git", "node_modules", "docs-site",
                        ".venv", "venv"})


def iter_python_files(paths: Iterable[str | Path]) -> list[Path]:
    out: list[Path] = []
    seen: set[Path] = set()  # overlapping inputs must not double-count
    for p in paths:
        p = Path(p)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if any(part in _SKIP_DIRS for part in f.parts):
                    continue
                if f.resolve() not in seen:
                    seen.add(f.resolve())
                    out.append(f)
        elif p.suffix == ".py" and p.resolve() not in seen:
            seen.add(p.resolve())
            out.append(p)
    return out


def _rel_path(path: Path, root: Optional[Path]) -> str:
    root = root or Path.cwd()
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def _module_name_on_disk(path: Path) -> str:
    """Dotted module name derived from the file's PACKAGE ROOT on disk:
    walk parents up while ``__init__.py`` marks them as package dirs.

    Import resolution in the project index must not depend on how the
    display path was anchored — `runbook lint /abs/checkout/runbookai_tpu`
    and an in-repo run link the same `runbookai_tpu.engine.fleet` names,
    so cross-module rules never silently degrade to per-file analysis
    because of the invocation cwd.
    """
    p = path.resolve()
    top = p.parent
    while (top / "__init__.py").is_file() and top.parent != top:
        top = top.parent
    parts = list(p.relative_to(top).parts)
    parts[-1] = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def analyze_source(source: str, path: str,
                   rules: Optional[Sequence[Rule]] = None,
                   jit_seeds: Optional[dict[str, frozenset[str]]] = None,
                   ) -> list[Finding]:
    """Analyze one module's source under a display path (noqa applied).

    ``jit_seeds`` (qualname → traced param names) marks functions
    jit-reachable from OTHER modules — produced by the project pass; the
    in-module closure then continues from the seeded state.
    """
    if rules is None:
        # Fresh instances per call: RBK004 aggregates per-walk state, and a
        # shared module-level set would cross-attribute findings if callers
        # ever analyze concurrently.
        from runbookai_tpu.analysis.rules import default_rules
        rules = default_rules()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(path=path, line=e.lineno or 0, col=e.offset or 0,
                        rule=PARSE_RULE_ID, severity=Severity.ERROR,
                        message=f"un-parseable module: {e.msg}")]
    ctx = ModuleContext(path=path, source=source, tree=tree,
                        tags=_path_tags(path), noqa=_noqa_lines(source),
                        jit_info=_jit_table(tree, seeds=jit_seeds))
    return _Walker(ctx, list(rules)).run()


def analyze_file(path: str | Path, rules: Optional[Sequence[Rule]] = None,
                 root: Optional[Path] = None) -> list[Finding]:
    p = Path(path)
    return analyze_source(p.read_text(encoding="utf-8"),
                          _rel_path(p, root), rules=rules)


def analyze_paths(paths: Iterable[str | Path],
                  rules: Optional[Sequence[Rule]] = None,
                  root: Optional[Path] = None,
                  project: bool = True) -> list[Finding]:
    """Two-phase analysis over a file set.

    Phase 1 (index): every file is parsed once into the whole-program
    symbol table / call graph (``analysis/project.py``) — this yields the
    cross-module rules RBK007–RBK010 and the jit-reachability seeds that
    upgrade RBK001 past the module boundary. Phase 2 runs the per-file
    rules with those seeds applied. ``project=False`` reverts to the
    first-order per-file pass (used by targeted unit tests only — the CLI
    always runs both phases).

    Output is deterministic for a given file SET regardless of input
    order: files are discovered sorted and findings sort on
    (path, line, col, rule).
    """
    files = iter_python_files(paths)
    entries = [(f, _rel_path(f, root), f.read_text(encoding="utf-8"))
               for f in files]
    findings: list[Finding] = []
    seeds_by_path: dict[str, dict[str, frozenset[str]]] = {}
    if project and entries:
        from runbookai_tpu.analysis.project import build_index
        from runbookai_tpu.analysis.xrules import run_cross_rules
        # Module names come from each file's on-disk package root, NOT
        # the display path — imports must resolve however the run was
        # anchored (absolute paths, foreign cwd, --no-baseline).
        index = build_index([(rel, text, _module_name_on_disk(f))
                             for f, rel, text in entries])
        seeds_by_path = index.jit_seeds()
        findings.extend(run_cross_rules(index))
    for _f, rel, text in entries:
        findings.extend(analyze_source(text, rel, rules=rules,
                                       jit_seeds=seeds_by_path.get(rel)))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
