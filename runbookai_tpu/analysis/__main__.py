"""``python -m runbookai_tpu.analysis`` — same surface as ``runbook lint``."""

import sys

from runbookai_tpu.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
