"""Command surface for the analyzer — shared by ``runbook lint``,
``python -m runbookai_tpu.analysis`` and ``scripts/lint.py``.

Kept free of heavy imports (no jax, no engine): the lint gate is the
fastest check in tier-1 and must stay that way.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Optional, Sequence

from runbookai_tpu.analysis.baseline import (
    load_baseline,
    new_findings,
    write_baseline,
)
from runbookai_tpu.analysis.core import (
    Severity,
    _rel_path,
    analyze_paths,
    iter_python_files,
)

DEFAULT_BASELINE = "lint-baseline.json"


def add_lint_arguments(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("paths", nargs="*", default=None,
                        help="files/directories to analyze "
                             "(default: runbookai_tpu/)")
    parser.add_argument("--format", choices=("text", "json"), default="text",
                        dest="fmt", help="finding output format")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline JSON path (default: "
                             f"{DEFAULT_BASELINE} when it exists)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, baselined or not")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from the current tree "
                             "and exit 0")


def run_lint(args: argparse.Namespace,
             stdout=None) -> int:
    out = stdout if stdout is not None else sys.stdout
    paths = args.paths or ["runbookai_tpu"]
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(f"lint: no such path: {', '.join(missing)}", file=out)
        return 2

    baseline_path = args.baseline or DEFAULT_BASELINE
    # Finding paths (= baseline keys) anchor to the baseline file's
    # directory — the repo root in-tree — so `runbook lint` matches the
    # committed baseline no matter which cwd it is invoked from. Pure
    # --no-baseline runs stay cwd-relative.
    root = None
    if not args.no_baseline:
        root = Path(baseline_path).resolve().parent

    findings = analyze_paths(paths, root=root)

    if args.update_baseline:
        # Merge-scoped to the analyzed files: a partial-path update must
        # not drop other files' grandfathered keys (write_baseline doc).
        # Normalized like Finding.path so set membership lines up.
        analyzed = {_rel_path(f, root) for f in iter_python_files(paths)}
        counts = write_baseline(baseline_path, findings,
                                analyzed_paths=analyzed)
        print(f"lint: baseline written to {baseline_path} "
              f"({sum(counts.values())} findings across {len(counts)} keys)",
              file=out)
        return 0

    baseline: dict[str, int] = {}
    if not args.no_baseline and (args.baseline or Path(baseline_path).is_file()):
        baseline = load_baseline(baseline_path)
    new = new_findings(findings, baseline)

    if args.fmt == "json":
        json.dump({
            "findings": [f.to_json() for f in new],
            "total": len(findings),
            "baselined": len(findings) - len(new),
            "new": len(new),
            "errors": sum(f.severity == Severity.ERROR for f in new),
        }, out, indent=2)
        out.write("\n")
    else:
        for f in new:
            print(f.format(), file=out)
        baselined = len(findings) - len(new)
        suffix = f" ({baselined} baselined)" if baselined else ""
        if new:
            print(f"lint: {len(new)} new finding(s){suffix}", file=out)
        else:
            print(f"lint: clean{suffix}", file=out)
    return 1 if new else 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="runbook-lint",
        description="AST static analysis for JAX/TPU serving hazards "
                    "(RBK001-RBK006; see docs/lint.md)")
    add_lint_arguments(parser)
    return run_lint(parser.parse_args(argv))


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
