"""Command surface for the analyzer — shared by ``runbook lint``,
``python -m runbookai_tpu.analysis`` and ``scripts/lint.py``.

Kept free of heavy imports (no jax, no engine): the lint gate is the
fastest check in tier-1 and must stay that way. Every run is two-phase
(whole-program index, then per-file rules with cross-module seeds) and
byte-deterministic for a given file set regardless of discovery order.

Formats:

- ``text`` (default) — one ``path:line:col: RULE [severity] message`` line;
- ``json`` — findings carry ``severity``, ``symbol`` and a stable
  ``fingerprint`` (rule+path+symbol hash, line-move tolerant) so CI can
  diff finding SETS across commits without line-number churn;
- ``sarif`` — minimal SARIF 2.1.0 for CI annotation UIs.

``--changed`` keeps pre-commit fast without giving up the whole-program
view: the full index is still built (cross-module rules need it), but
reported findings are filtered to files modified per ``git status``.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path
from typing import Optional, Sequence

from runbookai_tpu.analysis.baseline import (
    load_baseline,
    new_findings,
    write_baseline,
)
from runbookai_tpu.analysis.core import (
    PARSE_RULE_ID,
    Finding,
    Severity,
    _rel_path,
    analyze_paths,
    finding_fingerprints,
    iter_python_files,
)

DEFAULT_BASELINE = "lint-baseline.json"

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemas/JSON/sarif-schema-2.1.0.json")


def _rule_catalog() -> dict[str, str]:
    """id → one-line description for every rule (per-file + project)."""
    from runbookai_tpu.analysis.rules import default_rules
    from runbookai_tpu.analysis.xrules import XRULE_DESCRIPTIONS

    out = {PARSE_RULE_ID: "un-parseable module (file is never analyzed)"}
    for rule in default_rules():
        out[rule.rule_id] = rule.description
    out.update(XRULE_DESCRIPTIONS)
    return dict(sorted(out.items()))


def add_lint_arguments(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("paths", nargs="*", default=None,
                        help="files/directories to analyze "
                             "(default: runbookai_tpu/)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text", dest="fmt",
                        help="finding output format")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline JSON path (default: "
                             f"{DEFAULT_BASELINE} when it exists)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, baselined or not")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from the current tree "
                             "and exit 0")
    parser.add_argument("--changed", action="store_true",
                        help="report only findings in files git sees as "
                             "modified/added/untracked (the whole-program "
                             "index is still built over every path — "
                             "cross-module rules keep their full view)")


def _git_changed_paths(anchor: Path) -> Optional[set[str]]:
    """Repo-relative paths of modified/staged/untracked files, normalized
    like ``Finding.path`` (relative to ``anchor``). None when git is
    unavailable or the anchor is not inside a work tree."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"], cwd=anchor,
            capture_output=True, text=True, timeout=30)
        if top.returncode != 0:
            return None
        toplevel = Path(top.stdout.strip())
        # -uall: without it a brand-new directory collapses to one
        # "?? newpkg/" line and every file inside it would slip past the
        # .py filter — the exact new-package case pre-commit must catch.
        status = subprocess.run(
            ["git", "status", "--porcelain", "-uall"], cwd=anchor,
            capture_output=True, text=True, timeout=30)
        if status.returncode != 0:
            return None
    except (OSError, subprocess.SubprocessError):
        return None
    out: set[str] = set()
    for line in status.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:]
        if " -> " in path:  # rename: keep the new side
            path = path.split(" -> ", 1)[1]
        path = path.strip().strip('"')
        if not path.endswith(".py"):
            continue
        out.add(_rel_path(toplevel / path, anchor))
    return out


def _rows(findings: Sequence[Finding]) -> list[dict]:
    rows = [f.to_json() for f in findings]
    for row, fp in zip(rows, finding_fingerprints(findings)):
        row["fingerprint"] = fp
    return rows


def _sarif(findings: Sequence[Finding]) -> dict:
    catalog = _rule_catalog()
    level = {Severity.ERROR: "error", Severity.WARNING: "warning"}
    results = []
    for f, fp in zip(findings, finding_fingerprints(findings)):
        results.append({
            "ruleId": f.rule,
            "level": level.get(f.severity, "warning"),
            "message": {"text": f.message},
            "partialFingerprints": {"runbookLint/v1": fp},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line),
                               "startColumn": max(1, f.col + 1)},
                },
                "logicalLocations": ([{"fullyQualifiedName": f.symbol}]
                                     if f.symbol else []),
            }],
        })
    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "runbook-lint",
                "informationUri": "docs/lint.md",
                "rules": [{"id": rid,
                           "shortDescription": {"text": desc}}
                          for rid, desc in catalog.items()],
            }},
            "results": results,
        }],
    }


def run_lint(args: argparse.Namespace,
             stdout=None) -> int:
    out = stdout if stdout is not None else sys.stdout
    paths = args.paths or ["runbookai_tpu"]
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(f"lint: no such path: {', '.join(missing)}", file=out)
        return 2

    baseline_path = args.baseline or DEFAULT_BASELINE
    # Finding paths (= baseline keys) anchor to the baseline file's
    # directory — the repo root in-tree — so `runbook lint` matches the
    # committed baseline no matter which cwd it is invoked from. Pure
    # --no-baseline runs stay cwd-relative.
    root = None
    if not args.no_baseline:
        root = Path(baseline_path).resolve().parent

    findings = analyze_paths(paths, root=root)

    if args.update_baseline:
        # Merge-scoped to the analyzed files: a partial-path update must
        # not drop other files' grandfathered keys (write_baseline doc).
        # Normalized like Finding.path so set membership lines up.
        analyzed = {_rel_path(f, root) for f in iter_python_files(paths)}
        counts = write_baseline(baseline_path, findings,
                                analyzed_paths=analyzed)
        print(f"lint: baseline written to {baseline_path} "
              f"({sum(counts.values())} findings across {len(counts)} keys)",
              file=out)
        return 0

    baseline: dict[str, int] = {}
    if not args.no_baseline and (args.baseline or Path(baseline_path).is_file()):
        baseline = load_baseline(baseline_path)
    new = new_findings(findings, baseline)

    scope_note = ""
    if args.changed:
        changed = _git_changed_paths(root or Path.cwd())
        if changed is None:
            print("lint: --changed requires a git work tree", file=out)
            return 2
        before = len(new)
        new = [f for f in new if f.path in changed]
        scope_note = (f" (--changed: {len(new)} of {before} findings in "
                      f"{len(changed)} changed files)")

    if args.fmt == "sarif":
        json.dump(_sarif(new), out, indent=2, sort_keys=True)
        out.write("\n")
    elif args.fmt == "json":
        json.dump({
            "findings": _rows(new),
            "total": len(findings),
            "baselined": len(findings) - len(new) if not args.changed
            else None,
            "new": len(new),
            "errors": sum(f.severity == Severity.ERROR for f in new),
        }, out, indent=2)
        out.write("\n")
    else:
        for f in new:
            print(f.format(), file=out)
        baselined = len(findings) - len(new)
        suffix = f" ({baselined} baselined)" \
            if baselined and not args.changed else ""
        if new:
            print(f"lint: {len(new)} new finding(s){suffix}{scope_note}",
                  file=out)
        else:
            print(f"lint: clean{suffix}{scope_note}", file=out)
    return 1 if new else 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="runbook-lint",
        description="whole-program AST static analysis for JAX/TPU serving "
                    "hazards (RBK001-RBK010; see docs/lint.md)")
    add_lint_arguments(parser)
    return run_lint(parser.parse_args(argv))


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
