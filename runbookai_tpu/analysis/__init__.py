"""``runbook lint`` — AST static analysis for JAX/TPU serving hazards.

The classes of bugs that sink a TPU serving stack — silent recompiles,
host-device syncs in the decode loop, blocking calls under the engine step
lock, drifting metric names — are all statically detectable but otherwise
only surface at runtime on hardware CI never exercises. This package is the
in-tree analyzer that enforces that discipline on every commit:

- dependency-free (stdlib ``ast`` only — no jax import, so the gate runs in
  milliseconds on any machine);
- one visitor pass per file: every rule subscribes to node events on a
  shared walker (``core._Walker``) instead of re-walking the tree;
- findings carry ``file:line:col``, a stable rule id, a severity, and a
  message; ``# runbook: noqa[RULE]`` on the statement suppresses a finding
  in place (append a reason after the bracket — reviewers read it);
- a checked-in baseline (``lint-baseline.json``) grandfathers pre-existing
  findings so the gate only fails on NEW ones, and ``--update-baseline``
  regenerates it deterministically.

Since PR 13 the analyzer is **whole-program**: every run first builds a
project-wide symbol table and call graph (``analysis/project.py`` — still
stdlib ``ast`` only, deterministic output), then runs the per-file rules
with cross-module jit-reachability seeds plus four cross-module rules
(``analysis/xrules.py``).

Rule set (see docs/lint.md for the catalog with bad/good examples):

========  ==================================================================
RBK001    data-dependent Python branching / ``bool()``/``int()``/``float()``
          / ``.item()`` / ``.tolist()`` on traced values inside
          ``@jax.jit``-reachable functions — reachability and traced-ness
          now propagate across module boundaries through the call graph
RBK002    ``jax.block_until_ready`` / ``jax.device_get`` / implicit
          device→host transfer in the engine step/decode loop outside
          sanctioned sync points
RBK003    blocking I/O (``time.sleep``, file/socket/subprocess) while
          holding a lock (``with self._lock:`` scope analysis)
RBK004    shared attributes mutated both inside and outside a lock scope
          (same-module lock-discipline heuristic)
RBK005    metric registrations violating the observability contract
          (``^runbook_[a-z0-9_]+$``; histograms need explicit buckets)
RBK006    ``print`` / ``jax.debug.print`` left in engine/ops/model hot paths
RBK007    lock-order cycles through the call graph, same-instance
          re-acquisition, locks held across ``await``/thread handoffs
RBK008    attributes of engine/fleet/sched/obs/server objects written from
          ≥ 2 thread entry roles without one common lock
RBK009    blocking calls inside ``async def`` bodies on the serving path
RBK010    metric-label values not drawn from a statically bounded set
          (the label-cardinality contract, checked)
========  ==================================================================
"""

from runbookai_tpu.analysis.baseline import (
    baseline_counts,
    load_baseline,
    new_findings,
    write_baseline,
)
from runbookai_tpu.analysis.core import (
    Finding,
    Rule,
    Severity,
    analyze_file,
    analyze_paths,
    analyze_source,
    finding_fingerprints,
    iter_python_files,
)
from runbookai_tpu.analysis.rules import default_rules, rule_by_id

__all__ = [
    "Finding",
    "Rule",
    "Severity",
    "analyze_file",
    "analyze_paths",
    "analyze_source",
    "baseline_counts",
    "default_rules",
    "finding_fingerprints",
    "iter_python_files",
    "load_baseline",
    "new_findings",
    "rule_by_id",
    "write_baseline",
]
