"""Baseline semantics: grandfather pre-existing findings, fail on new ones.

The baseline is a JSON object mapping ``"<path>:<RULE>"`` → count. Keying on
(file, rule) with a count — instead of line numbers — makes the gate robust
to unrelated edits that shift lines: moving a grandfathered finding around a
file never trips CI, *adding one more* of the same rule in the same file
does. Deterministic (sorted keys, trailing newline) so regeneration is a
clean diff.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import Iterable, Optional

from runbookai_tpu.analysis.core import PARSE_RULE_ID, Finding


def baseline_counts(findings: Iterable[Finding]) -> dict[str, int]:
    # RBK000 (un-parseable file) is never grandfathered: a baselined parse
    # error would mean a file that is silently never analyzed at all.
    return dict(sorted(Counter(f.baseline_key for f in findings
                               if f.rule != PARSE_RULE_ID).items()))


def load_baseline(path: str | Path) -> dict[str, int]:
    p = Path(path)
    if not p.is_file():
        return {}
    data = json.loads(p.read_text(encoding="utf-8"))
    if not isinstance(data, dict):
        raise ValueError(f"{p}: baseline must be a JSON object")
    out: dict[str, int] = {}
    for key, value in data.items():
        if not isinstance(value, int) or value < 0:
            raise ValueError(f"{p}: baseline count for {key!r} must be a "
                             f"non-negative integer")
        out[str(key)] = value
    return out


def write_baseline(path: str | Path, findings: Iterable[Finding],
                   analyzed_paths: Optional[set[str]] = None) -> dict[str, int]:
    """Write the baseline; with ``analyzed_paths``, MERGE instead of replace.

    A partial run (``lint some/file.py --update-baseline``) must only
    refresh the keys of the files it actually analyzed — clobbering the
    whole baseline from a narrow path set would un-grandfather every other
    file's debt and fail the next full-tree gate. Keys whose file vanished
    from disk are dropped on any update.
    """
    counts = baseline_counts(findings)
    if analyzed_paths is not None:
        # Key paths are relative to the baseline file's directory (the
        # repo root in-tree), NOT the invoking cwd.
        anchor = Path(path).resolve().parent
        for key, count in load_baseline(path).items():
            key_path = key.rsplit(":", 1)[0]
            if key_path in analyzed_paths \
                    or not (anchor / key_path).exists():
                continue
            counts.setdefault(key, count)
    counts = dict(sorted(counts.items()))
    Path(path).write_text(json.dumps(counts, indent=2, sort_keys=True) + "\n",
                          encoding="utf-8")
    return counts


def new_findings(findings: Iterable[Finding],
                 baseline: dict[str, int]) -> list[Finding]:
    """Findings beyond each key's grandfathered count.

    Within a key the EARLIEST findings (by line) consume the baseline
    budget, so the excess reported is the one furthest into the file — in
    practice the one the new edit introduced.
    """
    by_key: dict[str, list[Finding]] = {}
    for f in findings:
        by_key.setdefault(f.baseline_key, []).append(f)
    out: list[Finding] = []
    for key, group in by_key.items():
        group.sort(key=lambda f: (f.line, f.col))
        # Parse errors are always new — a hand-edited baseline must not be
        # able to grandfather a file out of analysis entirely.
        budget = 0 if key.endswith(f":{PARSE_RULE_ID}") \
            else baseline.get(key, 0)
        out.extend(group[budget:])
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out
