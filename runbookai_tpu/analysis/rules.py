"""The RBK rule set — one class per rule, subscribed to the shared walker.

Every rule documents the runtime failure it prevents, because a lint gate
nobody understands gets noqa'd into irrelevance. docs/lint.md carries the
bad/good examples; keep both in sync when adding a rule.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from runbookai_tpu.analysis.core import (
    HOT_PATH_TAGS,
    ModuleContext,
    Rule,
    Scope,
    Severity,
    dotted_name,
    mentions_traced,
)

# The PR-1 observability contract (utils/metrics.py METRIC_NAME_RE) —
# duplicated as a literal on purpose: the analyzer must not import jax-adjacent
# modules, and a drift between the two regexes is itself caught by
# tests/test_lint.py.
METRIC_NAME_RE = re.compile(r"^runbook_[a-z0-9_]+$")


class DataDependentHostOps(Rule):
    """RBK001 — host branching / host conversion on traced values in jit.

    ``if traced:`` forces a concrete bool → one blocking device sync per
    call AND a retrace per novel shape; ``bool()/int()/float()/.item()/
    .tolist()`` on a traced value are the same sync spelled differently.
    Inside the decode loop that's a ~70ms stall per occurrence on tunneled
    TPU setups — the exact failure class Ragged Paged Attention's
    shape-discipline work exists to prevent.
    """

    rule_id = "RBK001"
    severity = Severity.ERROR
    description = ("data-dependent Python branching or host conversion on a "
                   "traced value inside a @jax.jit-reachable function")

    _CONVERSIONS = frozenset({"bool", "int", "float"})
    _SYNC_METHODS = frozenset({"item", "tolist"})

    def on_branch(self, ctx: ModuleContext, scope: Scope,
                  node: ast.stmt) -> Iterator[tuple[ast.AST, str]]:
        if not scope.in_jit:
            return
        test = node.test  # type: ignore[attr-defined]
        if mentions_traced(test, scope.traced_params):
            kind = "if" if isinstance(node, ast.If) else "while"
            yield (node,
                   f"data-dependent `{kind}` on a traced value inside a "
                   f"jit-reachable function — use jnp.where/lax.cond/"
                   f"lax.while_loop (each concrete branch forces a host "
                   f"sync and a recompile per novel value)")

    def on_call(self, ctx: ModuleContext, scope: Scope,
                node: ast.Call) -> Iterator[tuple[ast.AST, str]]:
        if not scope.in_jit:
            return
        if (isinstance(node.func, ast.Name)
                and node.func.id in self._CONVERSIONS and node.args
                and mentions_traced(node.args[0], scope.traced_params)):
            yield (node,
                   f"`{node.func.id}()` on a traced value inside a "
                   f"jit-reachable function forces a blocking device→host "
                   f"sync at trace time (ConcretizationTypeError on "
                   f"abstract values)")
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in self._SYNC_METHODS
                and mentions_traced(node.func.value, scope.traced_params)):
            yield (node,
                   f"`.{node.func.attr}()` on a traced value inside a "
                   f"jit-reachable function is a device→host transfer; keep "
                   f"values on device or move the conversion to the host "
                   f"caller")


class EngineLoopHostSync(Rule):
    """RBK002 — host syncs in the engine step/decode loop.

    The engine's throughput contract is ONE sanctioned token fetch in the
    decode loop: the async-egress consumption point
    (``EngineCore._fetch_tokens``) of the overlapped pipeline
    (docs/decode_pipeline.md) — every decode path funnels through it.
    Every extra ``block_until_ready`` / ``device_get`` / implicit
    ``np.asarray(jnp...)`` in ``engine/`` modules serializes the pipeline
    behind a device round-trip (~70ms each on tunneled TPU). Sanctioned
    barriers carry ``# runbook: noqa[RBK002] — <reason>`` so the next
    reader knows why the sync is load-bearing; tests/test_lint.py pins the
    full per-function inventory.
    """

    rule_id = "RBK002"
    severity = Severity.ERROR
    description = ("device→host sync (block_until_ready / device_get / "
                   "np.asarray of a jnp value) in an engine/ module outside "
                   "a sanctioned sync point")

    _SYNC_CALLS = frozenset({"jax.block_until_ready", "jax.device_get"})
    _NP_CTORS = frozenset({"np.asarray", "np.array", "numpy.asarray",
                           "numpy.array", "onp.asarray", "onp.array"})

    @staticmethod
    def _contains_jnp(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            name = None
            if isinstance(sub, (ast.Attribute, ast.Name)):
                name = dotted_name(sub)
            if name and (name.startswith("jnp.") or name.startswith("jax.numpy.")):
                return True
        return False

    def on_call(self, ctx: ModuleContext, scope: Scope,
                node: ast.Call) -> Iterator[tuple[ast.AST, str]]:
        if "engine" not in ctx.tags:
            return
        name = dotted_name(node.func)
        if name in self._SYNC_CALLS:
            yield (node,
                   f"`{name}` in an engine module: a blocking device→host "
                   f"sync outside the sanctioned per-dispatch token fetch — "
                   f"annotate sanctioned barriers with "
                   f"`# runbook: noqa[RBK002] — <reason>`")
            return
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready" and not node.args):
            yield (node,
                   "`.block_until_ready()` in an engine module: blocking "
                   "device sync outside the sanctioned token fetch")
            return
        if name in self._NP_CTORS and node.args \
                and self._contains_jnp(node.args[0]):
            yield (node,
                   f"`{name}` of a jnp expression implicitly copies "
                   f"device→host; fetch once via jax.device_get at the "
                   f"sanctioned sync point instead")


class BlockingCallUnderLock(Rule):
    """RBK003 — blocking I/O while holding a lock.

    The engine step lock serializes submit/step/abort: a ``time.sleep`` or
    file/socket/subprocess call inside ``with self._lock:`` stalls every
    live decode for its duration (and an admission storm turns that into
    head-of-line blocking for the whole server).
    """

    rule_id = "RBK003"
    severity = Severity.ERROR
    description = "blocking I/O (sleep/file/socket/subprocess) under a lock"

    _EXACT = frozenset({"time.sleep", "os.system", "os.popen"})
    _PREFIXES = ("subprocess.", "socket.", "requests.", "urllib.",
                 "http.client.", "shutil.")
    _IO_METHODS = frozenset({"read_text", "write_text", "read_bytes",
                             "write_bytes"})

    def on_call(self, ctx: ModuleContext, scope: Scope,
                node: ast.Call) -> Iterator[tuple[ast.AST, str]]:
        if not scope.in_lock:
            return
        name = dotted_name(node.func)
        blocking: Optional[str] = None
        if name in self._EXACT or (name == "sleep"):
            blocking = name
        elif name and name.startswith(self._PREFIXES):
            blocking = name
        elif isinstance(node.func, ast.Name) and node.func.id == "open":
            blocking = "open"
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr in self._IO_METHODS):
            blocking = f".{node.func.attr}"
        if blocking:
            yield (node,
                   f"`{blocking}(...)` while holding a lock blocks every "
                   f"thread contending for it (the engine step lock "
                   f"serializes ALL live decodes); move the I/O outside "
                   f"the `with` scope")


class UnlockedSharedMutation(Rule):
    """RBK004 — attributes mutated both inside and outside lock scopes.

    If a class protects ``self.x`` writes with ``with self._lock:``
    somewhere, an unprotected ``self.x = ...`` elsewhere is (at best) a
    benign race waiting for a refactor to make it malignant. ``__init__``
    and friends are exempt — construction happens-before sharing.
    """

    rule_id = "RBK004"
    severity = Severity.WARNING
    description = ("shared attribute mutated both inside and outside a "
                   "lock scope")

    _CTOR_METHODS = frozenset({"__init__", "__new__", "__post_init__",
                               "__init_subclass__"})

    def __init__(self) -> None:
        # (class, attr) → {"locked": [...nodes], "unlocked": [...nodes]}
        self._writes: dict[tuple[str, str], dict[str, list[ast.AST]]] = {}

    def on_attr_write(self, ctx: ModuleContext, scope: Scope,
                      node: ast.AST, attr: str) -> Iterator[tuple[ast.AST, str]]:
        if scope.class_name is None or scope.func_name is None:
            return
        if not scope.in_lock and scope.func_name in self._CTOR_METHODS:
            return
        rec = self._writes.setdefault((scope.class_name, attr),
                                      {"locked": [], "unlocked": []})
        rec["locked" if scope.in_lock else "unlocked"].append(node)
        return
        yield  # pragma: no cover — generator signature

    def finish(self, ctx: ModuleContext) -> Iterator[tuple[ast.AST, str]]:
        writes, self._writes = self._writes, {}
        for (cls, attr), rec in sorted(writes.items()):
            if rec["locked"] and rec["unlocked"]:
                first = min(rec["unlocked"],
                            key=lambda n: getattr(n, "lineno", 0))
                locked_line = min(getattr(n, "lineno", 0)
                                  for n in rec["locked"])
                yield (first,
                       f"`{cls}.{attr}` is written under a lock (line "
                       f"{locked_line}) but also mutated here without it — "
                       f"take the same lock or document the happens-before")


class MetricContract(Rule):
    """RBK005 — metric registrations must honor the PR-1 contract.

    Names match ``^runbook_[a-z0-9_]+$`` and histograms pass explicit
    buckets. The registry enforces this at runtime; this rule moves the
    failure to lint time, before a bad name ships a dashboard that can
    never be renamed compatibly.
    """

    rule_id = "RBK005"
    severity = Severity.ERROR
    description = ("metric registration violating the naming/bucket "
                   "contract (docs/observability.md)")

    _REGISTRY_METHODS = frozenset({"counter", "gauge", "histogram"})

    def on_call(self, ctx: ModuleContext, scope: Scope,
                node: ast.Call) -> Iterator[tuple[ast.AST, str]]:
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in self._REGISTRY_METHODS):
            return
        first = node.args[0] if node.args else None
        if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
            return  # dynamic names are the registry's runtime problem
        name = first.value
        if not METRIC_NAME_RE.match(name):
            yield (node,
                   f"metric name {name!r} violates the contract "
                   f"`{METRIC_NAME_RE.pattern}` (docs/observability.md)")
        if node.func.attr == "histogram":
            # The registry takes buckets KEYWORD-ONLY; a third positional
            # arg is a runtime TypeError, not a bucket declaration.
            has_buckets = any(kw.arg == "buckets" for kw in node.keywords)
            if not has_buckets:
                yield (node,
                       f"histogram {name!r} registered without explicit "
                       f"buckets — implied defaults drift silently across "
                       f"library versions")


class HotPathPrint(Rule):
    """RBK006 — ``print`` / ``jax.debug.print`` left in serving hot paths.

    A stray print in the decode loop is an unbounded-stdout tax per token
    (and ``jax.debug.print`` inserts a host callback into the compiled
    program). Anything load-bearing routes through utils/trace.py spans.
    """

    rule_id = "RBK006"
    severity = Severity.WARNING
    description = "print/jax.debug.print in engine/ops/model hot paths"

    def on_call(self, ctx: ModuleContext, scope: Scope,
                node: ast.Call) -> Iterator[tuple[ast.AST, str]]:
        if not (ctx.tags & HOT_PATH_TAGS):
            return
        if isinstance(node.func, ast.Name) and node.func.id == "print":
            yield (node,
                   "stray `print` in a serving hot path — route through "
                   "utils/trace.py (Tracer.event/span) or delete")
        elif dotted_name(node.func) == "jax.debug.print":
            yield (node,
                   "`jax.debug.print` compiles a host callback into the "
                   "program — debugging leftover; remove before serving")


def default_rules() -> list[Rule]:
    """Fresh rule instances (RBK004 aggregates per-walk state)."""
    return [DataDependentHostOps(), EngineLoopHostSync(),
            BlockingCallUnderLock(), UnlockedSharedMutation(),
            MetricContract(), HotPathPrint()]


def rule_by_id(rule_id: str) -> Optional[Rule]:
    for rule in default_rules():
        if rule.rule_id == rule_id.upper():
            return rule
    return None
