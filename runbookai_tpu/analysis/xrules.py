"""Whole-program rules RBK007–RBK010, run over :class:`ProjectIndex`.

These are the cross-module failure classes the per-file rules cannot see
(each rule's docstring names the runtime incident it prevents — the PR 2
principle that a gate nobody understands gets noqa'd into irrelevance):

RBK007  lock-order hazards: acquisition-order cycles between lock sites
        (propagated through the call graph), a non-reentrant lock
        re-acquired on the same instance, and locks held across
        ``await`` points or thread handoffs (``run_locked`` /
        ``asyncio.to_thread`` / executor submits).
RBK008  thread-shared state: attributes of engine/fleet/sched/obs/server
        objects written from ≥2 distinct thread entry roles (step loop,
        HTTP handlers, router pull workers, event loop) without one lock
        common to every writing path.
RBK009  blocking calls (``time.sleep``, file/socket I/O, bare
        ``Lock.acquire``) directly inside ``async def`` bodies on the
        serving path — each one freezes every stream the event loop owns.
RBK010  metric-label cardinality: every ``labels(...)`` value must come
        from a statically bounded set (literal, fixed tuple/frozenset
        constant, ``Literal[...]`` param, membership-guarded fallback,
        or a bounded propagation of those) — the checked twin of the
        bounded-``reason``-label convention docs/observability.md pins.

Findings are suppressible with the standard ``# runbook: noqa[RBK00x]``
marker at the flagged line (same lexical semantics as the per-file rules —
each module's noqa map is consulted through its ``ModuleContext``).
All output is deterministically ordered.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from runbookai_tpu.analysis.core import (
    Finding,
    Severity,
    _param_names,
    dotted_name,
)
from runbookai_tpu.analysis.project import (
    FuncNode,
    ProjectIndex,
    _const_collection,
)

# id → one-line description (the SARIF/driver rule metadata; docs/lint.md
# carries the full catalog with bad/good examples).
XRULE_DESCRIPTIONS = {
    "RBK007": ("lock-order hazard: acquisition-order cycle, same-instance "
               "re-acquisition, or a lock held across an await/thread "
               "handoff"),
    "RBK008": ("thread-shared attribute written from >= 2 thread entry "
               "roles without one lock common to every writing path"),
    "RBK009": ("blocking call (sleep / file / socket / bare Lock.acquire) "
               "inside an async def body on the serving path"),
    "RBK010": ("metric label value not drawn from a statically bounded "
               "set (label-cardinality contract)"),
}

# Packages whose objects RBK008 audits (thread-shared serving state).
SHARED_STATE_TAGS = frozenset({"engine", "fleet", "sched", "obs", "server"})

# Packages whose async bodies RBK009 audits (the serving event loops).
ASYNC_PATH_TAGS = frozenset({"engine", "fleet", "server"})


def _finding(fn: FuncNode, node: ast.AST, rule: str, severity: str,
             message: str) -> Optional[Finding]:
    ctx = fn.module.make_ctx()
    if ctx.suppressed(rule, node):
        return None
    return Finding(path=fn.module.path,
                   line=getattr(node, "lineno", 0),
                   col=getattr(node, "col_offset", 0),
                   rule=rule, severity=severity, message=message,
                   symbol=fn.qual)


def _short(lock: str) -> str:
    """Human-readable lock id: drop the package prefix."""
    return lock.split(".", 2)[-1] if lock.count(".") >= 2 else lock


# --------------------------------------------------------------------------- #
# RBK007 — lock-order analysis                                                #
# --------------------------------------------------------------------------- #


def check_lock_order(index: ProjectIndex) -> Iterator[Finding]:
    # Edge set: (held A → acquired B) with a representative site each.
    edges: dict[tuple[str, str], tuple[FuncNode, ast.AST]] = {}

    def _add(a: str, b: str, fn: FuncNode, node: ast.AST) -> None:
        edges.setdefault((a, b), (fn, node))

    for fq in sorted(index.funcs):
        fn = index.funcs[fq]
        entry = fn.entry_locks or frozenset()
        # Lexical nesting inside one function.
        for acq in fn.lock_acqs:
            for held in (*entry, *acq.held):
                if held != acq.lock:
                    _add(held, acq.lock, fn, acq.node)
            # Same-instance re-acquisition: `with self.X:` nested under an
            # already-held `self.X` (threading.Lock is NOT reentrant).
            if acq.self_rooted and acq.lock in acq.held:
                f = _finding(
                    fn, acq.node, "RBK007", Severity.ERROR,
                    f"`{_short(acq.lock)}` re-acquired while already held "
                    f"on the same instance — threading.Lock is not "
                    f"reentrant; this deadlocks the holder")
                if f:
                    yield f
        # Call-mediated: calling g while holding A adds A → every lock g
        # (transitively) acquires.
        for call in fn.calls:
            callee = index.funcs.get(call.callee or "")
            if callee is None:
                continue
            held_here = tuple(dict.fromkeys((*entry, *call.held)))
            if not held_here:
                continue
            for b in sorted(callee.acquires):
                for a in held_here:
                    if a != b:
                        _add(a, b, fn, call.node)
                    elif call.same_instance:
                        f = _finding(
                            fn, call.node, "RBK007", Severity.ERROR,
                            f"call re-enters `{_short(a)}` on the same "
                            f"instance ({callee.qual} acquires it) while "
                            f"it is already held — non-reentrant deadlock")
                        if f:
                            yield f

    # Cycles: strongly connected components of the edge graph with >1 lock.
    order = sorted({n for e in edges for n in e})
    adj: dict[str, list[str]] = {n: [] for n in order}
    for (a, b) in sorted(edges):
        adj[a].append(b)
    sccs = _tarjan(order, adj)
    cyclic = [sorted(s) for s in sccs if len(s) > 1]
    for comp in sorted(cyclic):
        members = set(comp)
        for (a, b) in sorted(edges):
            if a in members and b in members:
                fn, node = edges[(a, b)]
                f = _finding(
                    fn, node, "RBK007", Severity.ERROR,
                    f"lock-order cycle: `{_short(a)}` is held while "
                    f"acquiring `{_short(b)}`, but elsewhere the order "
                    f"reverses (cycle through "
                    f"{', '.join(_short(c) for c in comp)}) — pick one "
                    f"global order or drop to a snapshot-outside-lock "
                    f"pattern")
                if f:
                    yield f

    # Locks held across awaits / thread handoffs.
    for fq in sorted(index.funcs):
        fn = index.funcs[fq]
        for node, lock in fn.awaits_under_lock:
            f = _finding(
                fn, node, "RBK007", Severity.ERROR,
                f"`await` while holding `{_short(lock)}` — a sync lock "
                f"held across a suspension point blocks EVERY other task "
                f"(and thread) contending for it until this coroutine "
                f"resumes; release before awaiting or use run_locked")
            if f:
                yield f
        for node, what, lock in fn.handoffs_under_lock:
            f = _finding(
                fn, node, "RBK007", Severity.ERROR,
                f"`{what}(...)` while holding `{_short(lock)}` hands work "
                f"to another thread with the lock still held — if that "
                f"work (or anything it awaits) needs the same lock, the "
                f"handoff deadlocks; move it outside the `with` scope")
            if f:
                yield f


def _tarjan(nodes: list[str], adj: dict[str, list[str]]) -> list[set[str]]:
    """Iterative Tarjan SCC (deterministic: nodes/edges pre-sorted)."""
    idx: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[set[str]] = []
    counter = [0]

    for root in nodes:
        if root in idx:
            continue
        work: list[tuple[str, int]] = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                idx[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            advanced = False
            for i in range(pi, len(adj[node])):
                nxt = adj[node][i]
                if nxt not in idx:
                    work[-1] = (node, i + 1)
                    work.append((nxt, 0))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], idx[nxt])
            if advanced:
                continue
            if low[node] == idx[node]:
                comp: set[str] = set()
                while True:
                    top = stack.pop()
                    on_stack.discard(top)
                    comp.add(top)
                    if top == node:
                        break
                sccs.append(comp)
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return sccs


# --------------------------------------------------------------------------- #
# RBK008 — cross-file thread-shared-state races                               #
# --------------------------------------------------------------------------- #


def check_shared_state(index: ProjectIndex) -> Iterator[Finding]:
    # (class fq, attr) → [(fn, write)] for role-bearing non-ctor writers.
    writes: dict[tuple[str, str], list] = {}
    for fq in sorted(index.funcs):
        fn = index.funcs[fq]
        if not fn.roles:
            continue
        for w in fn.attr_writes:
            if w.ctor:
                continue
            cls = index.classes.get(w.owner)
            if cls is None or not (cls.module.tags & SHARED_STATE_TAGS):
                continue
            writes.setdefault((w.owner, w.attr), []).append((fn, w))

    for (owner, attr) in sorted(writes):
        writers = writes[(owner, attr)]
        roles: set[str] = set()
        for fn, _w in writers:
            roles |= fn.roles
        if len(roles) < 2:
            continue
        # One lock common to every writing path?
        common: Optional[frozenset[str]] = None
        for fn, w in writers:
            held = frozenset((*(fn.entry_locks or frozenset()), *w.held))
            common = held if common is None else (common & held)
        if common:
            continue
        writers.sort(key=lambda p: (p[0].module.path,
                                    getattr(p[1].node, "lineno", 0)))
        # Anchor at the least-protected write (no lock at all beats a
        # wrong lock for the "start here" signal).
        anchor_fn, anchor_w = min(
            writers,
            key=lambda p: (len((*(p[0].entry_locks or frozenset()),
                                *p[1].held)),
                           p[0].module.path,
                           getattr(p[1].node, "lineno", 0)))
        others = sorted({f"{fn.module.path}:{getattr(w.node, 'lineno', 0)}"
                         for fn, w in writers
                         if (fn, w) != (anchor_fn, anchor_w)})
        cls_short = owner.rsplit(".", 1)[-1]
        f = _finding(
            anchor_fn, anchor_w.node, "RBK008", Severity.WARNING,
            f"`{cls_short}.{attr}` is written from {len(roles)} thread "
            f"entry roles ({', '.join(sorted(roles))}) with no lock "
            f"common to every writing path (also written at "
            f"{', '.join(others[:3])}{', …' if len(others) > 3 else ''}) — "
            f"take one consistent lock or confine the attribute to a "
            f"single thread")
        if f:
            yield f


# --------------------------------------------------------------------------- #
# RBK009 — blocking calls in async bodies                                     #
# --------------------------------------------------------------------------- #


def check_async_blocking(index: ProjectIndex) -> Iterator[Finding]:
    for fq in sorted(index.funcs):
        fn = index.funcs[fq]
        if not (fn.module.tags & ASYNC_PATH_TAGS):
            continue
        if fn.is_async:
            for node, what, _held, _ in fn.blocking:
                f = _finding(
                    fn, node, "RBK009", Severity.ERROR,
                    f"`{what}(...)` directly inside an `async def` body "
                    f"freezes the event loop (every live stream stalls "
                    f"for its duration) — use an async equivalent or "
                    f"move it behind asyncio.to_thread")
                if f:
                    yield f
            # One-hop cross-module view: awaitless sync helpers that block
            # are still executed on the loop when called from async code.
            for call in fn.calls:
                callee = index.funcs.get(call.callee or "")
                if callee is None or callee.is_async:
                    continue
                direct = [b for b in callee.blocking if not b[3]]
                if direct:
                    what = direct[0][1]
                    f = _finding(
                        fn, call.node, "RBK009", Severity.ERROR,
                        f"call runs `{callee.qual}` on the event loop, and "
                        f"its body blocks (`{what}(...)` at "
                        f"{callee.module.path}:"
                        f"{getattr(direct[0][0], 'lineno', 0)}) — wrap the "
                        f"call in asyncio.to_thread or make the helper "
                        f"async")
                    if f:
                        yield f


# --------------------------------------------------------------------------- #
# RBK010 — metric-label cardinality                                           #
# --------------------------------------------------------------------------- #


def _const_dict_values(node: ast.AST) -> bool:
    """A dict literal whose VALUES are all constants (keys may be names:
    ``{PRIORITY_BATCH: "batch"}`` still yields a bounded value set)."""
    return isinstance(node, ast.Dict) \
        and all(isinstance(v, ast.Constant) for v in node.values)


def _return_exprs(node: ast.AST) -> list[ast.AST]:
    """Return-statement values of a function body, excluding nested defs.
    A bare ``return`` contributes a None constant."""
    out: list[ast.AST] = []

    def _walk(n: ast.AST) -> None:
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(child, ast.Return):
                out.append(child.value if child.value is not None
                           else ast.Constant(value=None))
            _walk(child)

    _walk(node)
    return out


class _Boundedness:
    """Decide whether a label-value expression draws from a statically
    bounded set. Conservative: unknown means unbounded."""

    MAX_DEPTH = 8

    def __init__(self, index: ProjectIndex):
        self.index = index

    def _callee_of(self, call: ast.Call, fn: FuncNode) -> Optional[FuncNode]:
        for site in fn.calls:
            if site.node is call:
                return self.index.funcs.get(site.callee or "")
        return None

    def bounded(self, expr: ast.AST, fn: FuncNode, depth: int = 0,
                stack: Optional[frozenset] = None) -> bool:
        if depth > self.MAX_DEPTH:
            return False
        if isinstance(expr, ast.Constant):
            return True
        if isinstance(expr, ast.JoinedStr):
            return all(self.bounded(v.value, fn, depth + 1, stack)
                       for v in expr.values
                       if isinstance(v, ast.FormattedValue))
        if isinstance(expr, ast.IfExp):
            # `x if x in BOUNDED else "other"` — the membership guard IS
            # the allowlist (the server's route-label idiom).
            if self._membership_guarded(expr, fn, depth, stack):
                return self.bounded(expr.orelse, fn, depth + 1, stack)
            return (self.bounded(expr.body, fn, depth + 1, stack)
                    and self.bounded(expr.orelse, fn, depth + 1, stack))
        if isinstance(expr, ast.Call):
            if dotted_name(expr.func) == "str" and len(expr.args) == 1:
                return self.bounded(expr.args[0], fn, depth + 1, stack)
            # D.get(x, default) on a constant-VALUED dict: the result set
            # is the dict's values plus the default (the `class_label`
            # idiom — arbitrary ints in, canonical names out).
            if isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr == "get" \
                    and len(expr.args) in (1, 2):
                recv = expr.func.value
                const = self._resolve_const(recv.id, fn) \
                    if isinstance(recv, ast.Name) else None
                if const is not None and _const_dict_values(const):
                    default_ok = len(expr.args) == 1 or self.bounded(
                        expr.args[1], fn, depth + 1, stack)
                    return default_ok
            # A project function whose every `return` value is bounded
            # (in the callee's own context) returns a bounded value.
            callee = self._callee_of(expr, fn)
            if callee is not None:
                key = (callee.fq, "<returns>")
                if key in (stack or frozenset()):
                    return False
                rstack = (stack or frozenset()) | {key}
                rets = _return_exprs(callee.node)
                return bool(rets) and all(
                    self.bounded(r, callee, depth + 1, rstack) for r in rets)
            return False
        if isinstance(expr, ast.Name):
            return self._name_bounded(expr.id, fn, depth, stack)
        if isinstance(expr, ast.Attribute):
            # Class-level constant (`self.KIND` where KIND = "x" on the
            # class) — anything else on an instance is runtime state.
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                    and fn.cls is not None:
                cls = fn.module.classes.get(fn.cls)
                if cls is not None and expr.attr in cls.consts:
                    const = cls.consts[expr.attr]
                    return isinstance(const, ast.Constant) \
                        or _const_collection(const)
            return False
        return False

    def _membership_guarded(self, expr: ast.IfExp, fn: FuncNode,
                            depth: int, stack) -> bool:
        test = expr.test
        return (isinstance(test, ast.Compare) and len(test.ops) == 1
                and isinstance(test.ops[0], ast.In)
                and ast.dump(test.left) == ast.dump(expr.body)
                and self._collection_bounded(test.comparators[0], fn,
                                             depth + 1))

    def _name_bounded(self, name: str, fn: FuncNode, depth: int,
                      stack) -> bool:
        stack = stack or frozenset()
        key = (fn.fq, name)
        if key in stack:
            return False
        stack = stack | {key}
        # for-loop / comprehension target over a bounded collection.
        if name in fn.for_targets:
            iterable, tup_idx = fn.for_targets[name]
            return self._collection_bounded(iterable, fn, depth + 1,
                                            tuple_index=tup_idx)
        # Local assignments: bounded iff every assignment is.
        if name in fn.local_assigns:
            return all(self.bounded(v, fn, depth + 1, stack)
                       for v in fn.local_assigns[name])
        # Module/class constant.
        const = self._resolve_const(name, fn)
        if const is not None:
            return isinstance(const, ast.Constant) or _const_collection(const)
        # Parameter: Literal[...] annotation, or every resolvable project
        # call site passes a bounded value.
        if name in _param_names(fn.node):
            if self._literal_annotated(name, fn):
                return True
            return self._callsites_bounded(name, fn, depth, stack)
        return False

    def _resolve_const(self, name: str, fn: FuncNode) -> Optional[ast.AST]:
        if fn.cls is not None:
            cls = fn.module.classes.get(fn.cls)
            if cls is not None and name in cls.consts:
                return cls.consts[name]
        if name in fn.module.consts:
            return fn.module.consts[name]
        target = fn.module.imports.get(name)
        if target and "." in target:
            mod_name, _, leaf = target.rpartition(".")
            mod = self.index.modules.get(mod_name)
            if mod is not None and leaf in mod.consts:
                return mod.consts[leaf]
        return None

    def _collection_bounded(self, expr: ast.AST, fn: FuncNode, depth: int,
                            tuple_index: int = -1) -> bool:
        if depth > self.MAX_DEPTH:
            return False
        if isinstance(expr, ast.Call):
            cname = dotted_name(expr.func)
            if cname in ("sorted", "frozenset", "set", "tuple", "list") \
                    and len(expr.args) == 1 and not expr.keywords:
                return self._collection_bounded(expr.args[0], fn, depth + 1,
                                                tuple_index)
            if isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr in ("keys", "items") \
                    and not expr.args:
                # dict.keys()/.items() of a bounded-key dict: the label is
                # bounded when it binds the KEY (items() index 0 or keys()).
                inner = expr.func.value
                if expr.func.attr == "items" and tuple_index not in (0, -1):
                    return False
                return self._collection_bounded(inner, fn, depth + 1)
            return False
        if _const_collection(expr):
            return True
        if isinstance(expr, ast.Name):
            const = self._resolve_const(expr.id, fn)
            if const is not None:
                return _const_collection(const) or self._collection_bounded(
                    const, fn, depth + 1, tuple_index)
            return False
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id == "self" \
                and fn.cls is not None:
            cls = fn.module.classes.get(fn.cls)
            if cls is not None and expr.attr in cls.consts:
                return _const_collection(cls.consts[expr.attr])
        return False

    def _literal_annotated(self, name: str, fn: FuncNode) -> bool:
        args = fn.node.args
        for a in (*args.posonlyargs, *args.args, *args.kwonlyargs):
            if a.arg != name or a.annotation is None:
                continue
            ann = a.annotation
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                try:
                    ann = ast.parse(ann.value, mode="eval").body
                except SyntaxError:
                    return False
            if isinstance(ann, ast.Subscript):
                base = dotted_name(ann.value)
                if base in ("Literal", "typing.Literal"):
                    return True
        return False

    def _callsites_bounded(self, param: str, fn: FuncNode, depth: int,
                           stack) -> bool:
        sites = []
        for other_fq in sorted(self.index.funcs):
            other = self.index.funcs[other_fq]
            for call in other.calls:
                if call.callee == fn.fq and isinstance(call.node, ast.Call):
                    sites.append((other, call.node))
        if not sites:
            return False
        a = fn.node.args
        positional = [p.arg for p in (*a.posonlyargs, *a.args)
                      if p.arg not in ("self", "cls")]
        for other, call in sites:
            exprs = []
            for i, arg in enumerate(call.args):
                if i < len(positional) and positional[i] == param:
                    exprs.append(arg)
            for kw in call.keywords:
                if kw.arg == param:
                    exprs.append(kw.value)
                elif kw.arg is None:
                    return False  # **kwargs forwarding — opaque
            if not exprs:
                # Param not supplied here: bounded only via its default.
                default = self._param_default(param, fn)
                if default is None or not self.bounded(default, fn,
                                                       depth + 1, stack):
                    return False
                continue
            for e in exprs:
                if not self.bounded(e, other, depth + 1, stack):
                    return False
        return True

    @staticmethod
    def _param_default(param: str, fn: FuncNode) -> Optional[ast.AST]:
        a = fn.node.args
        pos = [*a.posonlyargs, *a.args]
        defaults = list(a.defaults)
        for arg, default in zip(reversed(pos), reversed(defaults)):
            if arg.arg == param:
                return default
        for arg, default in zip(a.kwonlyargs, a.kw_defaults):
            if arg.arg == param and default is not None:
                return default
        return None


def check_label_cardinality(index: ProjectIndex) -> Iterator[Finding]:
    judge = _Boundedness(index)
    for fq in sorted(index.funcs):
        fn = index.funcs[fq]
        for site in fn.label_sites:
            bad = [name for name, expr in site.values
                   if not judge.bounded(expr, fn)]
            if not bad:
                continue
            f = _finding(
                fn, site.node, "RBK010", Severity.ERROR,
                f"label value(s) {', '.join(bad)} not drawn from a "
                f"statically bounded set — unbounded label cardinality "
                f"grows the scrape forever and kills the dashboards; use "
                f"a Literal/enum/fixed-tuple allowlist with an 'other' "
                f"fallback (docs/observability.md), or noqa with the "
                f"reason the set is bounded at runtime")
            if f:
                yield f


def run_cross_rules(index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    out.extend(check_lock_order(index))
    out.extend(check_shared_state(index))
    out.extend(check_async_blocking(index))
    out.extend(check_label_cardinality(index))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule, f.message))
    return out
