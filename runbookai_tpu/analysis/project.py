"""Whole-program index: symbols, imports, call graph, per-function facts.

PR 2's analyzer was one visitor pass per file, and its rule set inherited
that horizon: RBK001 stopped at the module boundary and the lock rules saw
only lexical ``with`` scopes. The platform the repo grew into (engine step
thread, HTTP handler threads, router pull workers through
``AsyncEngine.run_locked``, feedback controller, workload monitor) fails in
*cross-module* ways — lock-order cycles, thread-shared state mutated from
different entry points, unbounded metric-label cardinality. This module is
the second phase that makes those failure classes statically visible:

- parse every file once (stdlib ``ast`` only — the analyzer stays
  dependency-free and jax-free);
- build a project symbol table (modules → classes/functions, with a light
  attribute-type inference: ``self.core = EngineCore(...)`` and annotated
  params give method receivers types, so ``self.core.step()`` resolves);
- resolve in-package imports (absolute and relative) into a deterministic
  call graph;
- classify **thread entry roles** (async defs = the event loop,
  ``threading.Thread``/``asyncio.to_thread``/executor targets = worker
  threads, ``do_*`` methods of ``*RequestHandler`` classes = HTTP handler
  threads) and propagate them through the call graph;
- run a guaranteed-held-locks dataflow (intersection over role-bearing
  call paths), so a write in ``EngineCore.submit`` *knows* the caller holds
  ``AsyncEngine._lock`` even though no ``with`` is lexically in sight;
- compute transitive lock-acquisition sets for lock-order analysis;
- propagate jit-reachability and traced params across modules, producing
  the seeds ``core._jit_table`` consumes (the RBK001 upgrade that closes
  docs/lint.md's documented "same module only" gap).

Everything is deterministic: files are processed in sorted path order, all
derived sets are emitted sorted, and no state survives between builds —
``tests/test_lint.py`` shuffles input order and pins byte-identical JSON.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable, Optional

from runbookai_tpu.analysis.core import (
    _LOCK_SEG_RE,
    ModuleContext,
    _jit_decorator_info,
    _is_lock_ctx,
    _noqa_lines,
    _param_names,
    _path_tags,
    dotted_name,
    iter_functions,
    mentions_traced,
)

# Thread-handoff primitives: calling one of these hands a callable to a
# DIFFERENT thread. The first positional arg (or ``target=`` keyword) is a
# role root; calling one while holding a lock is an RBK007 hazard.
_HANDOFF_CALLS = frozenset({
    "asyncio.to_thread", "to_thread", "threading.Thread", "Thread",
})
_HANDOFF_METHODS = frozenset({"submit", "run_in_executor"})

# HTTP-handler detection: do_* methods of classes whose base names end in
# RequestHandler run on per-connection server threads.
_HTTP_METHODS = frozenset({"do_GET", "do_POST", "do_PUT", "do_DELETE",
                           "do_PATCH", "do_HEAD"})

ROLE_EVENT_LOOP = "event-loop"
ROLE_HTTP = "http-handler"


def module_name_for(path: str) -> str:
    """``a/b/c.py`` → ``a.b.c``; ``a/b/__init__.py`` → ``a.b``."""
    parts = path[:-3].split("/") if path.endswith(".py") else path.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


# --------------------------------------------------------------------------- #
# data model                                                                  #
# --------------------------------------------------------------------------- #


@dataclass
class CallSite:
    callee: Optional[str]          # fully-qualified function id, or None
    node: ast.AST
    held: tuple[str, ...]          # lock ids lexically held at the call
    same_instance: bool            # self.m() / local nested call — receiver
    # is the same object as the caller's `self`


@dataclass
class LockAcq:
    lock: str                      # lock id
    node: ast.AST
    held: tuple[str, ...]          # lock ids already held (lexically)
    self_rooted: bool              # context expr starts at `self.`


@dataclass
class AttrWrite:
    owner: str                     # fully-qualified class id
    attr: str
    node: ast.AST
    held: tuple[str, ...]          # lexical locks at the write
    ctor: bool                     # written in __init__-family method


@dataclass
class LabelSite:
    node: ast.Call                 # the `.labels(...)` call
    values: list[tuple[str, ast.AST]]  # (label display name, value expr)


@dataclass
class FuncNode:
    fq: str                        # "<module>.<qual>"
    qual: str                      # module-local qualname ("Cls.meth")
    module: "ModuleInfo"
    cls: Optional[str]             # enclosing class LOCAL name
    node: ast.AST
    is_async: bool
    calls: list[CallSite] = field(default_factory=list)
    lock_acqs: list[LockAcq] = field(default_factory=list)
    awaits_under_lock: list[tuple[ast.AST, str]] = field(default_factory=list)
    handoffs_under_lock: list[tuple[ast.AST, str, str]] = field(
        default_factory=list)      # (node, primitive name, held lock id)
    blocking: list[tuple[ast.AST, str, tuple[str, ...], bool]] = field(
        default_factory=list)      # (node, what, held, in_async_body)
    attr_writes: list[AttrWrite] = field(default_factory=list)
    label_sites: list[LabelSite] = field(default_factory=list)
    local_types: dict[str, str] = field(default_factory=dict)
    local_assigns: dict[str, list[ast.AST]] = field(default_factory=dict)
    for_targets: dict[str, tuple[ast.AST, int]] = field(default_factory=dict)
    # name -> (iterable expr, index in tuple target or -1)
    nested: dict[str, str] = field(default_factory=dict)  # local def name → fq
    # computed in link phase:
    roles: set[str] = field(default_factory=set)
    entry_locks: Optional[frozenset[str]] = None   # None = no tracked caller
    acquires: set[str] = field(default_factory=set)

    @property
    def params(self) -> list[str]:
        return _param_names(self.node)


@dataclass
class ClassInfo:
    fq: str
    local: str
    module: "ModuleInfo"
    node: ast.ClassDef
    bases: list[str] = field(default_factory=list)   # unresolved dotted names
    methods: dict[str, FuncNode] = field(default_factory=dict)
    attr_types: dict[str, str] = field(default_factory=dict)  # attr → class fq
    consts: dict[str, ast.AST] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    name: str
    path: str
    source: str
    tree: ast.Module
    tags: frozenset[str]
    is_package: bool = False  # an __init__.py (module name == package name)
    imports: dict[str, str] = field(default_factory=dict)  # local → fq target
    classes: dict[str, ClassInfo] = field(default_factory=dict)
    funcs: dict[str, FuncNode] = field(default_factory=dict)  # top-level only
    all_funcs: dict[str, FuncNode] = field(default_factory=dict)  # qual → node
    consts: dict[str, ast.AST] = field(default_factory=dict)
    ctx: Optional[ModuleContext] = None   # for noqa suppression

    def make_ctx(self) -> ModuleContext:
        if self.ctx is None:
            self.ctx = ModuleContext(
                path=self.path, source=self.source, tree=self.tree,
                tags=self.tags, noqa=_noqa_lines(self.source), jit_info={})
        return self.ctx


class ProjectIndex:
    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}
        self.funcs: dict[str, FuncNode] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.role_roots: list[tuple[str, str]] = []   # (func fq, role)
        self.parse_failures: list[str] = []           # paths that won't parse

    # ------------------------------------------------------------ resolution

    def resolve(self, module: ModuleInfo, name: str) -> Optional[str]:
        """A bare name in ``module`` → fully-qualified symbol/module id."""
        if name in module.funcs:
            return module.funcs[name].fq
        if name in module.classes:
            return module.classes[name].fq
        return module.imports.get(name)

    def class_of(self, fq: Optional[str]) -> Optional[ClassInfo]:
        return self.classes.get(fq) if fq else None

    def method(self, cls_fq: str, name: str,
               _seen: Optional[set[str]] = None) -> Optional[FuncNode]:
        """Resolve a method through statically-known project bases (MRO-ish,
        left-to-right depth-first)."""
        seen = _seen if _seen is not None else set()
        if cls_fq in seen:
            return None
        seen.add(cls_fq)
        cls = self.classes.get(cls_fq)
        if cls is None:
            return None
        if name in cls.methods:
            return cls.methods[name]
        for base in cls.bases:
            resolved = self.resolve(cls.module, base.split(".")[0])
            if resolved is None:
                continue
            base_fq = resolved + base[len(base.split(".")[0]):] \
                if "." in base else resolved
            hit = self.method(base_fq, name, seen)
            if hit is not None:
                return hit
        return None

    def attr_type(self, cls_fq: str, attr: str) -> Optional[str]:
        cls = self.classes.get(cls_fq)
        while cls is not None:
            if attr in cls.attr_types:
                return cls.attr_types[attr]
            nxt = None
            for base in cls.bases:
                resolved = self.resolve(cls.module, base.split(".")[0])
                if resolved in self.classes:
                    nxt = self.classes[resolved]
                    break
            cls = nxt
        return None

    # -------------------------------------------------------------- jit seeds

    def jit_seeds(self) -> dict[str, dict[str, frozenset[str]]]:
        """path → {module-local qualname → traced param names} for functions
        made jit-reachable by CROSS-module edges.

        Fixed point over the project call graph, mirroring the in-module
        closure in ``core._jit_table``: a function becomes jit-reachable
        when a jit-reachable caller anywhere in the project calls it, and a
        param becomes traced only when some such call site feeds it an
        expression that mentions a traced value.
        """
        reachable: dict[str, set[str]] = {}   # func fq → traced params
        statics: dict[str, frozenset[str]] = {}
        for fq in sorted(self.funcs):
            fn = self.funcs[fq]
            info = _jit_decorator_info(fn.node) if isinstance(
                fn.node, (ast.FunctionDef, ast.AsyncFunctionDef)) else None
            statics[fq] = info if info is not None else frozenset()
            if info is not None:
                reachable[fq] = set(_param_names(fn.node)) - set(info)

        def _positional(fn: FuncNode) -> list[str]:
            a = fn.node.args
            return [p.arg for p in (*a.posonlyargs, *a.args)
                    if p.arg not in ("self", "cls")]

        changed = True
        while changed:
            changed = False
            for fq in sorted(reachable):
                fn = self.funcs.get(fq)
                if fn is None:
                    continue
                traced = frozenset(reachable[fq])
                for call in fn.calls:
                    callee = self.funcs.get(call.callee or "")
                    if callee is None or callee.fq == fq:
                        continue
                    if not isinstance(call.node, ast.Call):
                        continue
                    params = _positional(callee)
                    hits: set[str] = set()
                    for idx, arg in enumerate(call.node.args):
                        if idx < len(params) and mentions_traced(arg, traced):
                            hits.add(params[idx])
                    for kw in call.node.keywords:
                        if kw.arg and mentions_traced(kw.value, traced):
                            hits.add(kw.arg)
                    hits -= set(statics.get(callee.fq, frozenset()))
                    cur = reachable.get(callee.fq)
                    if cur is None:
                        reachable[callee.fq] = set(hits)
                        changed = True
                    elif not hits <= cur:
                        cur |= hits
                        changed = True
        out: dict[str, dict[str, frozenset[str]]] = {}
        for fq in sorted(reachable):
            fn = self.funcs.get(fq)
            if fn is None or _jit_decorator_info(fn.node) is not None:
                continue  # directly decorated — the per-file table has it
            out.setdefault(fn.module.path, {})[fn.qual] = frozenset(
                reachable[fq])
        return out


# --------------------------------------------------------------------------- #
# phase 1: per-module scan                                                    #
# --------------------------------------------------------------------------- #


def _const_collection(node: ast.AST) -> bool:
    """A literal collection of constants (the "statically bounded set"
    RBK010 accepts: fixed tuple/list/set/frozenset/dict-of-constant-keys,
    possibly wrapped in frozenset()/tuple()/set()/list()/sorted())."""
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return all(isinstance(e, ast.Constant) for e in node.elts)
    if isinstance(node, ast.Dict):
        return all(isinstance(k, ast.Constant) for k in node.keys if k)
    if isinstance(node, ast.Call) and not node.keywords:
        name = dotted_name(node.func)
        if name in ("frozenset", "tuple", "set", "list", "sorted") \
                and len(node.args) == 1:
            return _const_collection(node.args[0])
    return False


class _ModuleScanner(ast.NodeVisitor):
    """Collect a module's symbols, imports and constants (pass 1a)."""

    def __init__(self, info: ModuleInfo):
        self.info = info

    def scan(self) -> None:
        mod = self.info
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    if alias.asname:
                        mod.imports[alias.asname] = alias.name
                    else:
                        head = alias.name.split(".")[0]
                        mod.imports[head] = head
            elif isinstance(stmt, ast.ImportFrom):
                base = stmt.module or ""
                if stmt.level:  # relative import → anchor at this package
                    pkg = mod.name.split(".")
                    # A package __init__ IS its package: `from .b import x`
                    # there drops level-1 components, a plain module drops
                    # `level` (its own name first).
                    drop = stmt.level - 1 if mod.is_package else stmt.level
                    pkg = pkg[: len(pkg) - drop] if drop else pkg
                    base = ".".join(pkg + ([stmt.module] if stmt.module else []))
                for alias in stmt.names:
                    if alias.name == "*":
                        continue
                    mod.imports[alias.asname or alias.name] = \
                        f"{base}.{alias.name}" if base else alias.name
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                mod.consts[stmt.targets[0].id] = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                    and isinstance(stmt.target, ast.Name):
                mod.consts[stmt.target.id] = stmt.value


def _annotation_class(annotation: Optional[ast.AST]) -> Optional[str]:
    """Dotted class name out of a (possibly string/Optional-wrapped)
    annotation, or None."""
    if annotation is None:
        return None
    if isinstance(annotation, ast.Constant) and isinstance(annotation.value, str):
        try:
            annotation = ast.parse(annotation.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(annotation, ast.Subscript):
        name = dotted_name(annotation.value)
        if name in ("Optional", "typing.Optional"):
            return _annotation_class(annotation.slice)
        return None
    return dotted_name(annotation)


class _FuncScanner:
    """Collect one function's facts: calls, locks, writes, label sites.

    Recursive statement walk that carries the lexical lock stack; nested
    ``def``s get their OWN FuncNode (their bodies run later, with no lock
    held), matching the per-file walker's scoping rules.
    """

    def __init__(self, index: ProjectIndex, fn: FuncNode):
        self.index = index
        self.fn = fn
        self.held: list[str] = []
        self.sync_held: list[str] = []  # subset of `held` from sync `with`

    # ------------------------------------------------------ type inference

    def _expr_type(self, expr: ast.AST) -> Optional[str]:
        """Best-effort class id of an expression's value."""
        if isinstance(expr, ast.Call):
            target = self._callable_target(expr.func)
            if target in self.index.classes:
                return target
            return None
        if isinstance(expr, ast.Name):
            if expr.id == "self" and self.fn.cls is not None:
                return f"{self.fn.module.name}.{self.fn.cls}"
            return self.fn.local_types.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self._expr_type(expr.value)
            if base is not None:
                return self.index.attr_type(base, expr.attr)
            return None
        return None

    def _callable_target(self, func: ast.AST) -> Optional[str]:
        """Resolve a call's target expression to a function/class fq id."""
        if isinstance(func, ast.Name):
            if func.id in self.fn.nested:
                return self.fn.nested[func.id]
            return self.index.resolve(self.fn.module, func.id)
        if isinstance(func, ast.Attribute):
            base = func.value
            # self.m(...) / typed_receiver.m(...)
            base_type = self._expr_type(base)
            if base_type is not None:
                hit = self.index.method(base_type, func.attr)
                if hit is not None:
                    return hit.fq
                return None
            # module_alias.f(...) or pkg.mod.f(...)
            dotted = dotted_name(func)
            if dotted is None:
                return None
            head, _, rest = dotted.partition(".")
            target = self.index.resolve(self.fn.module, head)
            if target is None:
                return None
            full = f"{target}.{rest}" if rest else target
            if full in self.index.funcs or full in self.index.classes:
                return full
            return None
        return None

    # ------------------------------------------------------------- walking

    def scan(self, body: list[ast.stmt]) -> None:
        # Pre-pass: param annotation types.
        args = self.fn.node.args
        for a in (*args.posonlyargs, *args.args, *args.kwonlyargs):
            cls = _annotation_class(a.annotation)
            if cls:
                resolved = self.index.resolve(self.fn.module,
                                              cls.split(".")[0])
                if resolved:
                    tail = cls[len(cls.split(".")[0]):]
                    full = resolved + tail
                    if full in self.index.classes:
                        self.fn.local_types[a.arg] = full
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs scanned as their own FuncNode
        if isinstance(stmt, ast.ClassDef):
            return  # nested classes scanned separately
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            # `async with lock:` acquisitions are asyncio locks — holding
            # one across an await is their normal operation, so they join
            # the order/handoff analysis but not the sync-held set that
            # feeds the await-under-lock check.
            is_sync = isinstance(stmt, ast.With)
            acquired: list[str] = []
            for item in stmt.items:
                self._exprs_in(item.context_expr)
                if _is_lock_ctx(item):
                    lock = self._lock_id(item.context_expr)
                    if lock is not None:
                        self.fn.lock_acqs.append(LockAcq(
                            lock=lock, node=stmt,
                            held=tuple(self.held),
                            self_rooted=self._is_self_rooted(
                                item.context_expr)))
                        acquired.append(lock)
            self.held.extend(acquired)
            if is_sync:
                self.sync_held.extend(acquired)
            try:
                for s in stmt.body:
                    self._stmt(s)
            finally:
                for _ in acquired:
                    self.held.pop()
                    if is_sync:
                        self.sync_held.pop()
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            value = stmt.value
            if value is not None:
                self._exprs_in(value)
            for target in targets:
                self._record_write(stmt, target, value)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            # Loop targets feed RBK010 boundedness (`for reason in REASONS:`).
            self._comp_target(stmt.target, stmt.iter)
        # Generic: visit child statements, collect expressions. Except
        # handlers and match cases are NOT ast.stmt — their bodies must
        # still be walked as statements or `with lock:` inside an except
        # block silently loses lock tracking.
        for _name, val in ast.iter_fields(stmt):
            vals = val if isinstance(val, list) else [val]
            for v in vals:
                if isinstance(v, ast.stmt):
                    self._stmt(v)
                elif isinstance(v, ast.ExceptHandler):
                    if v.type is not None:
                        self._exprs_in(v.type)
                    for s in v.body:
                        self._stmt(s)
                elif isinstance(v, getattr(ast, "match_case", ())):
                    for s in v.body:
                        self._stmt(s)
                elif isinstance(v, ast.AST):
                    self._exprs_in(v)

    def _record_write(self, stmt: ast.stmt, target: ast.AST,
                      value: Optional[ast.AST]) -> None:
        if isinstance(target, ast.Tuple):
            for el in target.elts:
                self._record_write(stmt, el, None)
            return
        if isinstance(target, ast.Name):
            if value is not None:
                self.fn.local_assigns.setdefault(target.id, []).append(value)
                t = self._expr_type(value)
                if t is not None:
                    self.fn.local_types.setdefault(target.id, t)
            return
        if not isinstance(target, ast.Attribute):
            return
        self._exprs_in(target.value)
        owner = self._expr_type(target.value)
        if owner is None:
            return
        is_ctor = self.fn.qual.split(".")[-1] in (
            "__init__", "__new__", "__post_init__", "__init_subclass__") \
            and isinstance(target.value, ast.Name) \
            and target.value.id == "self"
        self.fn.attr_writes.append(AttrWrite(
            owner=owner, attr=target.attr, node=stmt,
            held=tuple(self.held), ctor=is_ctor))
        # Attribute-type inference for `self.x = <typed expr>` in ctors.
        if value is not None and isinstance(target.value, ast.Name) \
                and target.value.id == "self" and self.fn.cls is not None:
            t = self._expr_type(value)
            cls = self.index.classes.get(
                f"{self.fn.module.name}.{self.fn.cls}")
            if t is not None and cls is not None:
                cls.attr_types.setdefault(target.attr, t)

    def _exprs_in(self, node: ast.AST) -> None:
        # Manual walk so lambda bodies can be PRUNED: a lambda runs later
        # (often on another thread — `to_thread(lambda: ...)` is RBK009's
        # own recommended remediation), so calls inside one must not be
        # attributed to the enclosing function's lock/async context.
        stack = [node]
        while stack:
            sub = stack.pop()
            if isinstance(sub, ast.Lambda):
                continue
            stack.extend(reversed(list(ast.iter_child_nodes(sub))))
            if isinstance(sub, ast.Call):
                self._call(sub)
            elif isinstance(sub, ast.Await):
                if self.sync_held and self.fn.is_async:
                    self.fn.awaits_under_lock.append(
                        (sub, self.sync_held[-1]))
            elif isinstance(sub, (ast.ListComp, ast.SetComp, ast.DictComp,
                                  ast.GeneratorExp)):
                for gen in sub.generators:
                    self._comp_target(gen.target, gen.iter)

    def _comp_target(self, target: ast.AST, iterable: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.fn.for_targets[target.id] = (iterable, -1)
        elif isinstance(target, ast.Tuple):
            for i, el in enumerate(target.elts):
                if isinstance(el, ast.Name):
                    self.fn.for_targets[el.id] = (iterable, i)

    def _call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        # labels(...) sites → RBK010.
        if isinstance(node.func, ast.Attribute) and node.func.attr == "labels":
            values: list[tuple[str, ast.AST]] = []
            for i, arg in enumerate(node.args):
                values.append((f"#{i}", arg))
            for kw in node.keywords:
                values.append((kw.arg or "**", kw.value))
            self.fn.label_sites.append(LabelSite(node=node, values=values))
        # Thread handoffs: role roots + RBK007 under-lock hazard.
        handoff = None
        target_expr: Optional[ast.AST] = None
        if name in _HANDOFF_CALLS:
            handoff = name
            target_expr = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in _HANDOFF_METHODS:
            # `.submit` only counts on executor-ish receivers — the engine
            # has its own `submit(request)` that never changes threads.
            recv = (dotted_name(node.func.value) or "").lower()
            if node.func.attr == "run_in_executor" \
                    or any(seg in recv for seg in ("executor", "pool", "tpe")):
                handoff = node.func.attr
                idx = 1 if node.func.attr == "run_in_executor" else 0
                if len(node.args) > idx:
                    target_expr = node.args[idx]
        if handoff is not None:
            if target_expr is not None:
                target = self._func_ref(target_expr)
                if target is not None:
                    role = f"worker:{self.index.funcs[target].qual}" \
                        if handoff in ("to_thread", "asyncio.to_thread",
                                       "submit", "run_in_executor") \
                        else f"thread:{self.index.funcs[target].qual}"
                    self.index.role_roots.append((target, role))
            if self.held:
                self.fn.handoffs_under_lock.append(
                    (node, handoff, self.held[-1]))
        # run_locked is the engine's own handoff seam.
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "run_locked" and self.held:
            self.fn.handoffs_under_lock.append(
                (node, "run_locked", self.held[-1]))
        # Blocking calls (for RBK009 and xrule context).
        blocking = self._blocking_kind(node)
        if blocking is not None:
            self.fn.blocking.append(
                (node, blocking, tuple(self.held), self.fn.is_async))
        # Call-graph edge.
        target = self._callable_target(node.func)
        if target in self.index.classes:
            ctor = self.index.method(target, "__init__")
            target = ctor.fq if ctor is not None else None
        same_instance = False
        if isinstance(node.func, ast.Name) \
                and node.func.id in self.fn.nested:
            same_instance = True
        elif isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            same_instance = True
        self.fn.calls.append(CallSite(
            callee=target if target in self.index.funcs else None,
            node=node, held=tuple(self.held), same_instance=same_instance))

    _BLOCK_EXACT = frozenset({"time.sleep", "os.system", "os.popen",
                              "sleep"})
    _BLOCK_PREFIXES = ("subprocess.", "socket.", "requests.", "urllib.",
                       "http.client.", "shutil.")
    _BLOCK_METHODS = frozenset({"read_text", "write_text", "read_bytes",
                                "write_bytes"})

    def _blocking_kind(self, node: ast.Call) -> Optional[str]:
        name = dotted_name(node.func)
        if name in self._BLOCK_EXACT:
            return name
        if name and name.startswith(self._BLOCK_PREFIXES):
            return name
        if isinstance(node.func, ast.Name) and node.func.id == "open":
            return "open"
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in self._BLOCK_METHODS:
                return f".{node.func.attr}"
            if node.func.attr == "acquire":
                recv = dotted_name(node.func.value)
                if recv is not None and _is_lock_name(recv) \
                        and not any(kw.arg == "timeout" for kw in node.keywords) \
                        and not node.args:
                    return f"{recv}.acquire"
        return None

    def _func_ref(self, expr: ast.AST) -> Optional[str]:
        """Resolve a function REFERENCE (not call) to a project function."""
        if isinstance(expr, ast.Name):
            if expr.id in self.fn.nested:
                return self.fn.nested[expr.id]
            target = self.index.resolve(self.fn.module, expr.id)
            return target if target in self.index.funcs else None
        if isinstance(expr, ast.Attribute):
            base_type = self._expr_type(expr.value)
            if base_type is not None:
                hit = self.index.method(base_type, expr.attr)
                return hit.fq if hit is not None else None
        return None

    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Call):
            expr = expr.func
        if isinstance(expr, ast.Attribute):
            owner = self._expr_type(expr.value)
            if owner is not None:
                return f"{owner}.{expr.attr}"
        name = dotted_name(expr)
        if name is None:
            return None
        return f"{self.fn.module.name}:{name}"

    @staticmethod
    def _is_self_rooted(expr: ast.AST) -> bool:
        if isinstance(expr, ast.Call):
            expr = expr.func
        while isinstance(expr, ast.Attribute):
            expr = expr.value
        return isinstance(expr, ast.Name) and expr.id == "self"


def _is_lock_name(dotted: str) -> bool:
    return any(_LOCK_SEG_RE.search(seg) for seg in dotted.lower().split("."))


def _module_pseudo_def(tree: ast.Module) -> ast.FunctionDef:
    """Wrap a module's top-level statements in a synthetic zero-arg def so
    the function scanner can walk them. Nested real defs/classes are
    skipped by the scanner as usual (they have their own FuncNodes)."""
    fn = ast.FunctionDef(
        name="<module>",
        args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                           kwonlyargs=[], kw_defaults=[], kwarg=None,
                           defaults=[]),
        body=list(tree.body) or [ast.Pass()],
        decorator_list=[], returns=None, type_comment=None)
    fn.lineno, fn.col_offset = 1, 0
    fn.end_lineno, fn.end_col_offset = 1, 0
    return fn


# --------------------------------------------------------------------------- #
# build + link                                                                #
# --------------------------------------------------------------------------- #


def build_index(files: Iterable[tuple]) -> ProjectIndex:
    """``(display_path, source)`` or ``(display_path, source, module_name)``
    entries → linked :class:`ProjectIndex`.

    The optional explicit ``module_name`` decouples import resolution from
    the DISPLAY path (which stays whatever the baseline/output anchor
    produced): ``analyze_paths`` derives it from the file's on-disk
    package root, so `runbook lint /abs/checkout/runbookai_tpu` links the
    same call graph as an in-repo run. Files that fail to parse are
    recorded in ``parse_failures`` and skipped (the per-file phase reports
    them as RBK000).
    """
    index = ProjectIndex()
    for entry in sorted(files):
        path, source = entry[0], entry[1]
        name = entry[2] if len(entry) > 2 and entry[2] else \
            module_name_for(path)
        try:
            tree = ast.parse(source)
        except SyntaxError:
            index.parse_failures.append(path)
            continue
        mod = ModuleInfo(name=name, path=path,
                         source=source, tree=tree, tags=_path_tags(path),
                         is_package=path.endswith("__init__.py"))
        if mod.name in index.modules:
            continue  # duplicate module name (shadowed path) — first wins
        index.modules[mod.name] = mod

    # pass 1a: symbols, imports, constants, class skeletons.
    for name in sorted(index.modules):
        mod = index.modules[name]
        _ModuleScanner(mod).scan()
        for qual, cls_local, node in iter_functions(mod.tree):
            fn = FuncNode(fq=f"{mod.name}.{qual}", qual=qual, module=mod,
                          cls=cls_local,
                          node=node,
                          is_async=isinstance(node, ast.AsyncFunctionDef))
            mod.all_funcs[qual] = fn
            index.funcs[fn.fq] = fn
            if "." not in qual:
                mod.funcs[qual] = fn
        # Module-level code gets a pseudo-function so import-time facts
        # (a top-level `labels(...)` registration, a module-scope `with
        # lock:`) are scanned like everything else — an unbounded label
        # at import time must not land silently.
        pseudo = FuncNode(fq=f"{mod.name}.<module>", qual="<module>",
                          module=mod, cls=None,
                          node=_module_pseudo_def(mod.tree), is_async=False)
        mod.all_funcs["<module>"] = pseudo
        index.funcs[pseudo.fq] = pseudo
        for stmt in ast.walk(mod.tree):
            if not isinstance(stmt, ast.ClassDef):
                continue
            # Only top-level and one-deep nested classes get ids; nested
            # classes key on their bare name (collisions: first wins).
            ci = ClassInfo(fq=f"{mod.name}.{stmt.name}", local=stmt.name,
                           module=mod, node=stmt,
                           bases=[b for b in
                                  (dotted_name(x) for x in stmt.bases) if b])
            index.classes.setdefault(ci.fq, ci)
            mod.classes.setdefault(stmt.name, ci)
            for item in stmt.body:
                if isinstance(item, ast.Assign) and len(item.targets) == 1 \
                        and isinstance(item.targets[0], ast.Name):
                    ci.consts[item.targets[0].id] = item.value
        # Attach methods to classes (one level of nesting).
        for qual, fn in mod.all_funcs.items():
            parts = qual.split(".")
            if len(parts) >= 2 and parts[-2] in mod.classes \
                    and fn.cls == parts[-2]:
                mod.classes[parts[-2]].methods.setdefault(parts[-1], fn)

    # pass 1b: nested-def visibility (local name → fq), then body scans.
    for name in sorted(index.modules):
        mod = index.modules[name]
        for qual, fn in mod.all_funcs.items():
            for other_qual in mod.all_funcs:
                if other_qual.startswith(qual + ".") \
                        and "." not in other_qual[len(qual) + 1:]:
                    fn.nested[other_qual.rsplit(".", 1)[-1]] = \
                        f"{mod.name}.{other_qual}"
    # Two scan rounds: round 1 populates ctor attr types (self.core =
    # EngineCore(...)) on the ClassInfos, round 2 re-scans with receiver
    # types visible so `self.core.step()` resolves to EngineCore.step.
    # Per-function facts are reset between rounds; attr_types persist.
    for _round in (1, 2):
        index.role_roots = []
        for name in sorted(index.modules):
            mod = index.modules[name]
            for qual in sorted(mod.all_funcs):
                fn = mod.all_funcs[qual]
                fn.calls, fn.lock_acqs = [], []
                fn.awaits_under_lock, fn.handoffs_under_lock = [], []
                fn.blocking, fn.attr_writes, fn.label_sites = [], [], []
                fn.local_types, fn.local_assigns, fn.for_targets = {}, {}, {}
                _FuncScanner(index, fn).scan(fn.node.body)

    # HTTP-handler and event-loop role roots.
    for name in sorted(index.modules):
        mod = index.modules[name]
        for qual in sorted(mod.all_funcs):
            fn = mod.all_funcs[qual]
            if fn.is_async:
                index.role_roots.append((fn.fq, ROLE_EVENT_LOOP))
            leaf = qual.split(".")[-1]
            if leaf in _HTTP_METHODS and fn.cls is not None:
                cls = mod.classes.get(fn.cls)
                if cls is not None and any(
                        b.split(".")[-1].endswith("RequestHandler")
                        for b in cls.bases):
                    index.role_roots.append((fn.fq, ROLE_HTTP))

    _link(index)
    return index


def _link(index: ProjectIndex) -> None:
    """Role propagation, guaranteed-held-locks dataflow, transitive
    acquisition sets — the fixed points the cross rules read."""
    # Roles: BFS from roots along call edges. Worker-thread targets do NOT
    # inherit the spawner's role (they run on their own thread).
    worklist: list[str] = []
    for fq, role in sorted(set(index.role_roots)):
        fn = index.funcs.get(fq)
        if fn is not None and role not in fn.roles:
            fn.roles.add(role)
            worklist.append(fq)
    while worklist:
        fq = worklist.pop()
        fn = index.funcs[fq]
        for call in fn.calls:
            callee = index.funcs.get(call.callee or "")
            if callee is None or callee.is_async:
                # Async callees always run on the event loop regardless of
                # the caller's thread (they are event-loop roots already).
                continue
            if not fn.roles <= callee.roles:
                callee.roles |= fn.roles
                worklist.append(callee.fq)

    # Guaranteed-held locks: intersection over role-bearing call paths.
    # entry_locks(root) = {}; edge f→g at site with H held refines
    # entry(g) ∩= entry(f) ∪ H. Monotone decreasing → terminates.
    for fq, _role in sorted(set(index.role_roots)):
        fn = index.funcs.get(fq)
        if fn is not None:
            fn.entry_locks = frozenset() if fn.entry_locks is None \
                else fn.entry_locks
    changed = True
    while changed:
        changed = False
        for fq in sorted(index.funcs):
            fn = index.funcs[fq]
            if fn.entry_locks is None or not fn.roles:
                continue
            base = fn.entry_locks
            for call in fn.calls:
                callee = index.funcs.get(call.callee or "")
                if callee is None:
                    continue
                at_site = frozenset(base | set(call.held))
                if callee.entry_locks is None:
                    callee.entry_locks = at_site
                    changed = True
                else:
                    refined = callee.entry_locks & at_site
                    if refined != callee.entry_locks:
                        callee.entry_locks = refined
                        changed = True

    # Transitive lock acquisitions (for lock-order edges through calls).
    for fq in sorted(index.funcs):
        fn = index.funcs[fq]
        fn.acquires = {a.lock for a in fn.lock_acqs}
    changed = True
    while changed:
        changed = False
        for fq in sorted(index.funcs):
            fn = index.funcs[fq]
            for call in fn.calls:
                callee = index.funcs.get(call.callee or "")
                if callee is None:
                    continue
                if not callee.acquires <= fn.acquires:
                    fn.acquires |= callee.acquires
                    changed = True
