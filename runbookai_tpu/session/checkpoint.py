"""Investigation checkpoint store.

Parity target: reference ``src/session/checkpoint.ts`` (``CheckpointStore``
:133; metadata + snapshots :22-104; max 50 per investigation :127) with the
CLI surface ``runbook checkpoint list/show/delete`` (cli.tsx:2353-2430).
Snapshots capture the FSM state so investigations are resumable after a crash
or preemption (SURVEY.md §5.3/5.4).
"""

from __future__ import annotations

import json
import time
import uuid
from dataclasses import asdict, dataclass
from pathlib import Path
from typing import Any, Optional

MAX_CHECKPOINTS_PER_INVESTIGATION = 50


@dataclass
class CheckpointMeta:
    checkpoint_id: str
    investigation_id: str
    phase: str
    created_at: float
    label: str = ""


class CheckpointStore:
    def __init__(self, root: str | Path = ".runbook/checkpoints"):
        self.root = Path(root)

    def _dir(self, investigation_id: str) -> Path:
        return self.root / investigation_id

    # ------------------------------------------------------------------ save

    def save(self, investigation_id: str, snapshot: dict[str, Any],
             phase: str = "", label: str = "") -> CheckpointMeta:
        meta = CheckpointMeta(
            checkpoint_id=f"cp-{int(time.time())}-{uuid.uuid4().hex[:6]}",
            investigation_id=investigation_id,
            phase=phase or str(snapshot.get("phase", "")),
            created_at=time.time(),
            label=label,
        )
        d = self._dir(investigation_id)
        d.mkdir(parents=True, exist_ok=True)
        (d / f"{meta.checkpoint_id}.json").write_text(json.dumps({
            "meta": asdict(meta), "snapshot": snapshot,
        }, indent=2, default=str))
        self._prune(investigation_id)
        return meta

    def save_machine(self, machine, label: str = "") -> CheckpointMeta:
        """Checkpoint an InvestigationStateMachine directly."""
        snapshot = machine.get_summary()
        snapshot["hypothesis_detail"] = {
            hid: {
                "statement": h.statement, "priority": h.priority, "depth": h.depth,
                "parent_id": h.parent_id, "status": h.status,
                "confidence": h.confidence, "children": h.children,
                "evidence": h.evidence,
            }
            for hid, h in machine.hypotheses.items()
        }
        return self.save(machine.incident_id, snapshot,
                         phase=machine.phase.value, label=label)

    def _prune(self, investigation_id: str) -> None:
        files = sorted(self._dir(investigation_id).glob("cp-*.json"))
        while len(files) > MAX_CHECKPOINTS_PER_INVESTIGATION:
            files.pop(0).unlink()

    # ------------------------------------------------------------------ read

    def list(self, investigation_id: Optional[str] = None) -> list[CheckpointMeta]:
        metas: list[CheckpointMeta] = []
        if not self.root.exists():
            return metas
        dirs = [self._dir(investigation_id)] if investigation_id else sorted(
            p for p in self.root.iterdir() if p.is_dir())
        for d in dirs:
            for f in sorted(d.glob("cp-*.json")):
                try:
                    raw = json.loads(f.read_text())["meta"]
                    metas.append(CheckpointMeta(**raw))
                except (json.JSONDecodeError, KeyError, TypeError):
                    continue
        return metas

    def show(self, checkpoint_id: str) -> Optional[dict[str, Any]]:
        if not self.root.exists():
            return None
        for f in self.root.rglob(f"{checkpoint_id}.json"):
            return json.loads(f.read_text())
        return None

    def delete(self, checkpoint_id: str) -> bool:
        if not self.root.exists():
            return False
        for f in self.root.rglob(f"{checkpoint_id}.json"):
            f.unlink()
            return True
        return False

    def latest(self, investigation_id: str) -> Optional[dict[str, Any]]:
        files = sorted(self._dir(investigation_id).glob("cp-*.json")) \
            if self._dir(investigation_id).exists() else []
        if not files:
            return None
        return json.loads(files[-1].read_text())
