"""SLO monitor: configured latency objectives evaluated at scrape time.

AIBrix's lesson (PAPERS.md) is that SLO-aware routing/scheduling is only
as good as the live latency-vs-SLO signal underneath it; this module IS
that signal, computed from the PR 1 histograms the engine already
observes — no second measurement path, no per-request overhead.

Objectives are configured under ``llm.slo`` (docs/CONFIG.md) as
``<metric>_p<quantile>_ms`` targets over the engine histograms::

    llm:
      slo:
        ttft_p95_ms: 500
        tpot_p95_ms: 40
        e2e_p99_ms: 30000

Exported series (ONLY when at least one objective is configured — an
unconfigured deployment scrapes no ``runbook_slo_*`` at all):

- ``runbook_slo_target_ms{objective=...}`` — the configured target;
- ``runbook_slo_current_ms{objective=...}`` — the histogram's current
  percentile (bucket-interpolated; the series is absent until the
  histogram has observations);
- ``runbook_slo_burn_ratio{objective=...}`` — current / target; > 1 means
  the objective is burning. The sched/feedback.py controller consumes
  the same objective WINDOWED (bucket-snapshot diffs via
  :meth:`SLOMonitor.histogram`), not this lifetime gauge;
- ``runbook_slo_violations_total{objective=...}`` — evaluations (scrapes
  and ``/healthz`` probes) that observed the objective breached. A rate
  over it is "fraction of recent looks that saw a breach", not a request
  count.

All three gauges are scrape-time callbacks over the live histograms —
one source of truth, zero steady-state cost.
"""

from __future__ import annotations

import re
from typing import Any, Optional

from runbookai_tpu.utils import metrics as metrics_mod

# objective key = "<metric>_p<quantile>_ms" over these histograms.
OBJECTIVE_HISTOGRAMS = {
    "ttft": "runbook_ttft_seconds",
    "tpot": "runbook_tpot_seconds",
    "e2e": "runbook_e2e_seconds",
}
_OBJECTIVE_RE = re.compile(r"^(ttft|tpot|e2e)_p(\d{2})_ms$")


def parse_objective(key: str) -> tuple[str, float]:
    """``"ttft_p95_ms"`` -> ("runbook_ttft_seconds", 95.0); raises on an
    unknown spelling so a typo'd config fails at startup, not silently."""
    m = _OBJECTIVE_RE.match(key)
    if not m:
        raise ValueError(
            f"unknown SLO objective {key!r} (expected "
            f"<ttft|tpot|e2e>_p<quantile>_ms, e.g. ttft_p95_ms)")
    return OBJECTIVE_HISTOGRAMS[m.group(1)], float(m.group(2))


class SLOMonitor:
    """Evaluates ``{objective_key: target_ms}`` against the registry's
    latency histograms; registers the ``runbook_slo_*`` series on
    construction (never when ``targets`` is empty)."""

    def __init__(self, targets: dict[str, float],
                 registry: Optional[metrics_mod.MetricsRegistry] = None):
        self.registry = registry or metrics_mod.get_registry()
        self.objectives: dict[str, dict[str, Any]] = {}
        for key, target_ms in targets.items():
            hist_name, quantile = parse_objective(key)
            if target_ms is None:
                continue
            if float(target_ms) <= 0:
                raise ValueError(f"SLO target {key} must be > 0 ms")
            self.objectives[key] = {"hist": hist_name, "q": quantile,
                                    "target_ms": float(target_ms)}
        if not self.objectives:
            return  # no objectives -> no series, no registration
        reg = self.registry
        self._g_target = reg.gauge(
            "runbook_slo_target_ms",
            "Configured latency objective (llm.slo)", labels=("objective",))
        self._g_current = reg.gauge(
            "runbook_slo_current_ms",
            "Current bucket-interpolated percentile of the objective's "
            "histogram (absent until it has observations)",
            labels=("objective",))
        self._g_burn = reg.gauge(
            "runbook_slo_burn_ratio",
            "current/target per objective; > 1 means the objective is "
            "burning", labels=("objective",))
        self._c_violations = reg.counter(
            "runbook_slo_violations_total",
            "Evaluations (scrapes + /healthz probes) that observed the "
            "objective breached", labels=("objective",))
        for key in self.objectives:
            # runbook: noqa[RBK010] — objective label: regex-validated
            # <ttft|tpot|e2e>_p<q>_ms spellings from llm.slo, fixed at load.
            self._g_target.labels(objective=key).set_function(
                lambda k=key: self.objectives[k]["target_ms"])
            # Materialize the violation series at 0: "never breached" must
            # scrape as an explicit zero so rate() works from first breach.
            # runbook: noqa[RBK010] — objective label: regex-validated
            # <ttft|tpot|e2e>_p<q>_ms spellings from llm.slo, fixed at load.
            self._c_violations.labels(objective=key).inc(0.0)
            # current/burn raise (-> series dropped) while the histogram
            # is empty: "no data" must scrape as absence, not as 0 (a
            # burn_ratio of 0 would read as a comfortably-met SLO).
            # runbook: noqa[RBK010] — objective label: regex-validated
            # <ttft|tpot|e2e>_p<q>_ms spellings from llm.slo, fixed at load.
            self._g_current.labels(objective=key).set_function(
                lambda k=key: self._current_ms_or_raise(k))
            # runbook: noqa[RBK010] — objective label: regex-validated
            # <ttft|tpot|e2e>_p<q>_ms spellings from llm.slo, fixed at load.
            self._g_burn.labels(objective=key).set_function(
                lambda k=key: self._burn_or_raise(k))

    # ------------------------------------------------------------- internals

    def _histogram(self, key: str) -> Optional[metrics_mod.Histogram]:
        metric = self.registry.get(self.objectives[key]["hist"])
        return metric if isinstance(metric, metrics_mod.Histogram) else None

    def histogram(self, key: str) -> Optional[metrics_mod.Histogram]:
        """The live histogram behind an objective (None until the engine
        registers it). Public so consumers that need WINDOWED views —
        the sched/feedback controller diffs bucket snapshots per
        decision window — can reach the source series."""
        return self._histogram(key)

    def current_ms(self, key: str) -> Optional[float]:
        """The objective's live percentile in ms (None = no data yet)."""
        hist = self._histogram(key)
        if hist is None:
            return None
        value = hist.percentile(self.objectives[key]["q"])
        return None if value is None else value * 1e3

    def _current_ms_or_raise(self, key: str) -> float:
        value = self.current_ms(key)
        if value is None:
            raise LookupError(f"{key}: histogram empty")
        return value

    def _burn_or_raise(self, key: str) -> float:
        burn = self._current_ms_or_raise(key) / self.objectives[key]["target_ms"]
        if burn > 1.0:
            # runbook: noqa[RBK010] — objective label: regex-validated
            # <ttft|tpot|e2e>_p<q>_ms spellings from llm.slo, fixed at load.
            self._c_violations.labels(objective=key).inc()
        return burn

    # ------------------------------------------------------------------ API

    def evaluate(self) -> dict[str, dict[str, Any]]:
        """One evaluation pass for ``/healthz`` / bench: per objective,
        target, current, burn ratio, and breached (None current = the
        histogram has no observations yet). Counts breaches into
        ``runbook_slo_violations_total`` like a scrape does."""
        out: dict[str, dict[str, Any]] = {}
        for key, obj in self.objectives.items():
            current = self.current_ms(key)
            burn = (current / obj["target_ms"]
                    if current is not None else None)
            breached = burn is not None and burn > 1.0
            if breached:
                # runbook: noqa[RBK010] — objective label: regex-validated
                # <ttft|tpot|e2e>_p<q>_ms spellings from llm.slo, fixed at load.
                self._c_violations.labels(objective=key).inc()
            out[key] = {
                "target_ms": obj["target_ms"],
                "current_ms": round(current, 3) if current is not None else None,
                "burn_ratio": round(burn, 4) if burn is not None else None,
                "breached": breached,
            }
        return out

    @classmethod
    def from_config(cls, slo_cfg: Any,
                    registry: Optional[metrics_mod.MetricsRegistry] = None,
                    ) -> Optional["SLOMonitor"]:
        """Build from an ``llm.slo`` config block (utils/config.SLOConfig
        or any object with a ``targets()`` dict). None when no objective
        is set — the caller keeps serving with zero SLO surface."""
        if slo_cfg is None:
            return None
        targets = (slo_cfg.targets() if hasattr(slo_cfg, "targets")
                   else dict(slo_cfg))
        if not targets:
            return None
        return cls(targets, registry=registry)
