"""Layered YAML config with ``${ENV}`` interpolation.

Parity target: reference ``src/utils/config.ts`` (zod ``ConfigSchema`` :211,
``loadConfig`` :221 with CWD→$HOME search path, ``${ENV_VAR}`` resolution
:252-269, ``validateConfig`` :292) and ``src/config/services.ts`` (infra
inventory schemas). zod becomes pydantic. New here: the ``llm.provider:
jax-tpu`` block carries the TPU serving parameters (model path, mesh shape,
dtype, max_seq, KV page size, batch caps) that have no reference counterpart.
"""

from __future__ import annotations

import os
import re
from pathlib import Path
from typing import Any, Literal, Optional

import yaml
from pydantic import BaseModel, ConfigDict, Field

CONFIG_DIR = ".runbook"
CONFIG_FILE = "config.yaml"
SERVICES_FILE = "services.yaml"

_ENV_RE = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*)\}")


def _interpolate(value: Any) -> Any:
    """Recursively resolve ``${ENV_VAR}`` in strings (unset vars -> '')."""
    if isinstance(value, str):
        return _ENV_RE.sub(lambda m: os.environ.get(m.group(1), ""), value)
    if isinstance(value, list):
        return [_interpolate(v) for v in value]
    if isinstance(value, dict):
        return {k: _interpolate(v) for k, v in value.items()}
    return value


# --------------------------------------------------------------------------- #
# llm / engine                                                                #
# --------------------------------------------------------------------------- #


class MeshConfig(BaseModel):
    """Logical device mesh for the serving engine.

    Axis sizes multiply to the device count. ``data`` batches independent
    sequences (eval DP), ``model`` shards attention heads / MLP (Megatron TP
    over ICI).
    """

    data: int = 1
    model: int = 1

    @property
    def device_count(self) -> int:
        return self.data * self.model


class DisaggConfig(BaseModel):
    """Prefill/decode disaggregation (``llm.fleet.disagg``): dedicate the
    first ``prefill_replicas`` fleet replicas to a prefill tier whose KV
    pages hand off to the decode tier at first-token time. Requires
    ``dp_replicas >= 2`` and must leave at least one decode replica —
    validated at load. See docs/SERVING.md."""

    model_config = ConfigDict(extra="forbid")

    enabled: bool = False
    # Replicas 0..n-1 form the prefill tier; the rest decode.
    prefill_replicas: int = Field(1, ge=1)
    # Prompts with fewer full pages than this skip the prefill tier (the
    # warm round-trip costs more than the tail prefill it saves).
    min_prompt_pages: int = Field(1, ge=1)


class SupervisorConfig(BaseModel):
    """Replica supervision (``llm.fleet.supervisor`` →
    chaos/supervisor.FleetSupervisor): heartbeat-driven detection of
    dead/wedged replicas, in-flight failover through the router retry
    path, online replica rebuild and hysteresis-guarded rejoin. Off by
    default. ``wedge_timeout_s`` MUST exceed the worst-case compile a
    step can legitimately hold the engine lock for — a too-small value
    fails over replicas that are merely compiling. See
    docs/robustness.md."""

    model_config = ConfigDict(extra="forbid")

    enabled: bool = False
    poll_interval_s: float = Field(0.25, gt=0)
    wedge_timeout_s: float = Field(60.0, gt=0)
    rejoin_hysteresis_s: float = Field(1.0, gt=0)
    max_consecutive_rebuilds: int = Field(3, ge=1)


class FleetRouterConfig(BaseModel):
    """Engine-fleet router policy (engine/fleet.FleetConfig; only read
    when ``dp_replicas > 1``). See docs/SERVING.md."""

    # Prefix-affinity placement on/off (off = pure least-loaded).
    affinity: bool = True
    # Max live-load excess (requests) a prefix-matching replica may carry
    # over the least-loaded one and still win. None = one batch's worth.
    affinity_load_slack: Optional[int] = None
    # Shed (503) when EVERY replica's waiting queue is at least this deep.
    # None = never shed.
    shed_queue_depth: Optional[int] = None
    # Cross-replica retries after a pool-pressure abort. None = each
    # other replica once.
    max_retries: Optional[int] = None
    # Fleet-wide KV page sharing: on an affinity miss, pull the prompt's
    # prefix pages from the replica that holds them (epoch-guarded,
    # digest-checked) instead of re-prefilling. Disaggregation implies it.
    kv_share: bool = False
    # Minimum full-page deficit worth a pull.
    kv_share_min_pages: int = Field(1, ge=1)
    # Cross-replica retry backoff (docs/SERVING.md "Failure handling"):
    # attempt k waits min(max, base * 2**(k-1)) with seeded jitter.
    retry_backoff_base: float = Field(0.05, ge=0)
    retry_backoff_max: float = Field(2.0, gt=0)
    # Prefill/decode tier split (docs/SERVING.md "Disaggregated tiers").
    disagg: DisaggConfig = Field(default_factory=DisaggConfig)
    # Replica supervision (docs/robustness.md).
    supervisor: SupervisorConfig = Field(default_factory=SupervisorConfig)


class TenantPolicyConfig(BaseModel):
    """Limits for one tenant key (``llm.tenants.keys.<name>``) or the
    anonymous pool (``llm.tenants.default``). Unset limit = unenforced.
    Enforced by the OpenAI server BEFORE enqueue (sched/tenants.py): a
    throttled request gets 429 + Retry-After and never consumes an
    engine slot."""

    model_config = ConfigDict(extra="forbid")

    # Requests per minute (token bucket, capacity = one minute's worth).
    rate_limit_rpm: Optional[float] = Field(None, gt=0)
    # Tokens per minute (prompt + completion; worst case reserved at
    # admission, unused part refunded when the completion size is known).
    token_budget_per_min: Optional[float] = Field(None, gt=0)
    # Estimated KV pages the tenant may hold IN FLIGHT (reserved at
    # admission from prompt + n*max_tokens, released when the request
    # settles). A concurrency ledger, not a per-minute rate: it stops a
    # long-context tenant from crowding the page pool while staying
    # inside its token budget. None = unenforced.
    kv_page_limit: Optional[int] = Field(None, gt=0)
    # Pin this tenant to one served model group (``llm.models`` entry
    # name): requests without a ``model`` field route to the pinned
    # group, and an explicit different model is refused 403 — the
    # tenant-affine placement half of multi-model serving. Only
    # meaningful with ``llm.models`` set (validated).
    model: Optional[str] = None
    # Scheduling class of this tenant's requests; the x-priority header
    # can DEMOTE a request (never promote past this class).
    priority: Literal["interactive", "batch"] = "interactive"
    # The secret that selects this tenant (Authorization: Bearer /
    # x-api-key). SET THIS: the tenant's NAME (the llm.tenants.keys map
    # key) appears verbatim in /tenants, `runbook tenants` and the
    # runbook_tenant_* metric labels — with api_key unset, the name
    # itself is matched as the bearer token, which is only acceptable
    # for non-secret identifiers.
    api_key: Optional[str] = None


class TenantsConfig(BaseModel):
    """Per-tenant (API-key) admission control (``llm.tenants``). Off by
    default: the server then has zero tenant surface. Unknown/anonymous
    keys share the ``default`` policy's ONE bucket set (bounded state —
    arbitrary caller keys must not allocate server memory)."""

    model_config = ConfigDict(extra="forbid")

    enabled: bool = False
    default: TenantPolicyConfig = Field(default_factory=TenantPolicyConfig)
    # Tenant NAME -> policy. The name is the public identifier (metric
    # labels, /tenants, CLI); the matching secret is the policy's
    # api_key (falling back to the name itself when unset — only for
    # non-secret identifiers).
    keys: dict[str, TenantPolicyConfig] = Field(default_factory=dict)


class SchedConfig(BaseModel):
    """Engine scheduling policy (``llm.sched`` → sched/wdrr.py +
    sched/feedback.py). See docs/SERVING.md "Scheduling and tenancy"."""

    model_config = ConfigDict(extra="forbid")

    # "wdrr": weighted-deficit (stride) interleave of priority classes —
    # a batch flood cannot starve interactive admits, and interactive
    # load cannot starve batch. "priority": the classic strict
    # priority-then-FCFS sort.
    policy: Literal["wdrr", "priority"] = "wdrr"
    # Admission share weights of the two canonical classes (wdrr only).
    interactive_weight: float = Field(8.0, gt=0)
    batch_weight: float = Field(1.0, gt=0)
    # SLO feedback loop: adapt the mixed-dispatch prefill share from the
    # live TPOT p95 burn ratio (requires llm.slo.tpot_p95_ms; fails at
    # load without it). Off = bit-for-bit today's engine.
    feedback: bool = False
    feedback_interval_steps: int = Field(32, ge=1)
    # Burn thresholds: shrink the prefill share above shrink_at, grow it
    # back below grow_at (hysteresis band between them).
    feedback_shrink_at: float = Field(1.0, gt=0)
    feedback_grow_at: float = Field(0.7, gt=0)
    # The share never shrinks below this fraction of the configured
    # mixed budget's prefill side (clamped to one ragged block).
    feedback_min_fraction: float = Field(0.25, gt=0, le=1.0)


class SLOConfig(BaseModel):
    """Latency objectives (``llm.slo``) evaluated at scrape time against
    the engine's serving histograms (utils/slo.py). All targets are
    milliseconds; unset = no objective, and with NO objective set the
    process exports no ``runbook_slo_*`` series at all. A typo'd key or
    non-positive target fails here, at load — a silently-ignored typo
    would read as "SLO monitoring active" while exporting nothing."""

    model_config = ConfigDict(extra="forbid")

    ttft_p95_ms: Optional[float] = Field(None, gt=0)
    ttft_p99_ms: Optional[float] = Field(None, gt=0)
    tpot_p95_ms: Optional[float] = Field(None, gt=0)
    tpot_p99_ms: Optional[float] = Field(None, gt=0)
    e2e_p95_ms: Optional[float] = Field(None, gt=0)
    e2e_p99_ms: Optional[float] = Field(None, gt=0)

    def targets(self) -> dict[str, float]:
        """The configured objectives only (utils/slo.SLOMonitor input)."""
        return {k: v for k, v in self.model_dump().items()
                if v is not None}


class WorkloadDescriptorConfig(BaseModel):
    """A tuner workload descriptor spelled in config
    (``llm.obs.workload``) — the drift reference when no serving plan is
    pinned. Fields mirror ``autotune.cost_model.Workload`` exactly, so
    the same dict feeds ``runbook tune``."""

    model_config = ConfigDict(extra="forbid")

    prompt_len: int = Field(512, ge=1)
    output_len: int = Field(128, ge=1)
    concurrency: int = Field(8, ge=1)
    guided_share: float = Field(0.0, ge=0.0, le=1.0)
    spec_hit_rate: float = Field(0.0, ge=0.0)

    def to_descriptor(self) -> dict[str, Any]:
        return self.model_dump()


class TsdbConfig(BaseModel):
    """Embedded telemetry time-series store (``llm.obs.tsdb`` →
    ``runbookai_tpu/obs/tsdb.py``): a bounded ring-buffer history over
    every exported ``runbook_*`` series, sampled from the live metrics
    registry every ``interval_s``. Powers ``GET /debug/query`` /
    ``runbook query`` (PromQL-lite), the ``/healthz`` ``history``
    block, incident-bundle lookback windows and the soak gate's
    query-expressed invariants. ``enabled: false`` removes every
    ``runbook_tsdb_*`` series and every surface on top."""

    model_config = ConfigDict(extra="forbid")

    enabled: bool = True
    # Registry sweep cadence (seconds).
    interval_s: float = Field(1.0, gt=0)
    # Per-series ring horizon: samples older than this are pruned.
    retention_s: float = Field(600.0, gt=0)
    # Cap on distinct stored series; new series past it are dropped
    # (and counted in the /healthz history block).
    max_series: int = Field(2048, ge=16)
    # Pre-open lookback window embedded in incident bundles' `history`
    # section (seconds of detector-input signals before the open).
    lookback_s: float = Field(60.0, gt=0)


class ObsConfig(BaseModel):
    """Continuous workload fingerprinting + drift detection
    (``llm.obs`` → ``runbookai_tpu/obs``). On by default: the layer is
    read-only (one O(1) tap per finished request; everything else is
    scrape-time), changes no plan and moves no traffic, so enabling it
    cannot perturb served bytes. ``enabled: false`` removes every
    ``runbook_workload_*`` / ``runbook_plan_stale`` /
    ``runbook_replica_health`` series and the ``/debug/workload``
    surface reports itself disabled."""

    model_config = ConfigDict(extra="forbid")

    enabled: bool = True
    # Sliding fingerprint window (seconds) and its sample bound.
    window_s: float = Field(300.0, gt=0)
    max_samples: int = Field(4096, ge=16)
    # Drift score above which runbook_plan_stale{model} scrapes 1 — the
    # retune trigger (docs/observability.md has the PromQL alert).
    drift_threshold: float = Field(0.35, gt=0, le=1.0)
    # Rotated on-disk fingerprint history (None = no persistence):
    # one JSON per interval with window provenance, oldest pruned past
    # history_max_files.
    history_dir: Optional[str] = None
    history_max_files: int = Field(64, ge=1)
    history_interval_s: float = Field(60.0, ge=0)
    # Drift reference when no serving plan is pinned (plan provenance
    # wins when llm.plan / llm.models[].plan is set).
    workload: Optional[WorkloadDescriptorConfig] = None
    # Incident detection + black-box capture (obs/detect.py,
    # obs/incident.py): fold the exported signals (SLO burn, drift,
    # replica health, supervisor states, router sheds/stale pulls,
    # queue-wait percentiles) into an incident lifecycle with hysteresis
    # and capture a content-hashed evidence bundle on every open.
    # Surfaced on GET /debug/incidents, the /healthz `incidents` block,
    # `runbook incident list|show` and runbook_incident_*{signal}.
    incidents_enabled: bool = True
    # Bundle directory (None = detect + surface, but capture nothing).
    incident_dir: Optional[str] = None
    # Rotation bound: oldest bundles pruned past this count.
    incident_max_bundles: int = Field(16, ge=1)
    incident_poll_interval_s: float = Field(1.0, gt=0)
    # Hysteresis (both directions) for the level-shaped signals: a
    # breach must persist incident_open_s before an incident opens, and
    # an open incident must stay clear for incident_resolve_s before it
    # resolves. Event-shaped signals (replica_failure, router_stale)
    # keep their own constants — see obs/detect.default_policies.
    incident_open_s: float = Field(5.0, ge=0)
    incident_resolve_s: float = Field(10.0, ge=0)
    # Embedded metric history + PromQL-lite query surface
    # (obs/tsdb.py, obs/query.py).
    tsdb: TsdbConfig = Field(default_factory=TsdbConfig)


# Keys a model-group entry owns (or that cannot nest): a group's
# ``overrides`` must not rewrite them behind the entry's back — replica
# accounting, plan validation and adapter resolution all read the ENTRY
# fields (enforced at load by validate_config AND at build by
# fleet/build.derive_group_llm).
RESERVED_GROUP_OVERRIDE_KEYS = frozenset((
    "model", "model_path", "tokenizer_path", "plan", "dp_replicas",
    "lora_adapters", "models", "tenants",
))


class ModelGroupConfig(BaseModel):
    """One served model group of a multi-model fleet (``llm.models``).

    Each group is a full replica set built from its own derived
    ``LLMConfig``: the base ``llm`` block supplies every unspecified
    knob, the group's ``plan`` (if any) fills the gaps a serving-plan
    artifact pins, and ``overrides`` beats both — the same
    explicit-beats-plan precedence as a single-model ``llm.plan``
    (docs/CONFIG.md "Multi-model fleets")."""

    model_config = ConfigDict(extra="forbid")

    # Served model id: what OpenAI requests put in "model", what the
    # /v1/models catalog lists, what metric labels carry.
    name: str
    # Model catalog config name (models/llama.CONFIGS). Default: name.
    model: Optional[str] = None
    # Weights / tokenizer for this group (None = base llm values, which
    # usually means discovery/random-init per group model name).
    model_path: Optional[str] = None
    tokenizer_path: Optional[str] = None
    # Serving-plan artifact sizing THIS group's per-replica budget
    # (slots/pages/dispatch knobs) — per-model plans from `runbook tune`.
    plan: Optional[str] = None
    # Replicas dedicated to this group (global fleet indices are
    # assigned contiguously across groups, in list order).
    dp_replicas: int = Field(1, ge=1)
    # Multi-LoRA adapters served WITHIN this group: adapter name ->
    # HF PEFT dir. Adapter names resolve in the group's namespace and
    # are listed under the group in /v1/models.
    adapters: dict[str, str] = Field(default_factory=dict)
    # llm.* field overrides for this group only (page_size, num_pages,
    # max_batch_slots, kv_cache_dtype, ...). Keys are validated against
    # LLMConfig at load; values win over the group plan AND the base.
    overrides: dict[str, Any] = Field(default_factory=dict)


class LLMConfig(BaseModel):
    provider: Literal["jax-tpu", "mock"] = "mock"
    model: str = "llama3-8b-instruct"
    # Path to weights (HF safetensors dir) — None means random init (CI, no-egress).
    model_path: Optional[str] = None
    tokenizer_path: Optional[str] = None
    dtype: Literal["bfloat16", "float32", "int8"] = "bfloat16"
    max_seq_len: int = 8192
    max_new_tokens: int = 1024
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled; composes with top_p
    # Multi-LoRA serving: adapter name -> HF PEFT directory. Adapters load
    # at startup into one stacked tree; requests (or OpenAI calls whose
    # "model" equals an adapter name) select per-row adapters.
    lora_adapters: dict[str, str] = Field(default_factory=dict)
    lora_rank: int = 8
    lora_targets: tuple[str, ...] = ("wq", "wv")
    # Draft-model speculative decoding: name a small in-family config
    # (e.g. "llama3-1b-bench" drafting for 8B) and optionally its weights.
    # The draft runs k-1 greedy steps in one dispatch; the target verifies
    # in one T=k forward. None = prompt-lookup speculation only.
    draft_model: Optional[str] = None
    draft_model_path: Optional[str] = None
    # Decode attention implementation: "auto" picks the Pallas kernels on
    # TPU and the XLA gather path elsewhere; explicit values override (e.g.
    # force "xla" when debugging a Mosaic issue on hardware).
    attn_impl: Literal["auto", "pallas", "xla"] = "auto"
    # Quantized-matmul implementation (int8 weights only): "pallas" streams
    # int8 tiles through ops/qmm_pallas.py — structural half-bytes on the
    # decode weight reads; "auto" picks it on TPU for int8 weights.
    qmm_impl: Literal["auto", "pallas", "xla"] = "auto"
    # KV cache precision: "auto" follows the activation dtype (bf16);
    # "fp8" (float8_e4m3) halves pool bytes — double the pooled tokens
    # per chip — at ~1e-2 relative K/V error.
    # "int8": values + per-token absmax scales, XLA path (best accuracy
    # at 1 byte/value on hardware without fast fp8); "fp8": raw e4m3
    # pages, composes with the Pallas kernels and the page-split mesh.
    # "bf16" pins a bfloat16 pool even on float32 activations (the plan
    # artifact spelling — identical to "auto" on bf16 deployments).
    kv_cache_dtype: Literal["auto", "bf16", "fp8", "int8"] = "auto"
    # Serving-plan artifact (runbook tune; runbookai_tpu/autotune/plan.py):
    # path to a schema-versioned plan JSON whose engine block supplies the
    # serving knobs below. Precedence: any key you set EXPLICITLY in this
    # file still wins over the plan; unset keys take the plan's values
    # instead of the defaults (docs/autotune.md, docs/CONFIG.md).
    plan: Optional[str] = None
    # Paged KV cache (engine):
    page_size: int = 16  # tokens per KV page
    num_pages: int = 2048  # page pool size (static for XLA)
    # Host-RAM spill tier: retain up to this many evicted prefix-cache
    # pages in host memory so re-sent prompts re-admit them instead of
    # re-prefilling (engine/kv_cache.HostSpillTier). 0 = disabled. Host
    # bytes ≈ pages × page_size × kv_bytes_per_token
    # (memory_plan.ServingPlan.host_spill_bytes) — budget against host
    # RAM, not HBM.
    kv_spill_pages: int = Field(0, ge=0)
    max_batch_slots: int = 8  # concurrent sequences in the decode batch
    prefill_chunk: int = 512  # prefill processed in chunks of this many tokens
    decode_steps: int = 8  # decode tokens per device dispatch (host-sync amortization)
    mesh: MeshConfig = Field(default_factory=MeshConfig)
    # Data-parallel engine fleet (engine/fleet.py): build this many engine
    # replicas, each on its own device slice, behind the prefix-affinity
    # router. Slots/pages above are PER REPLICA. Requires mesh.data/model
    # = 1 (a replica is a single-slice engine).
    dp_replicas: int = 1
    fleet: FleetRouterConfig = Field(default_factory=FleetRouterConfig)
    # Multi-model fleet (runbookai_tpu/fleet/): partition replicas into
    # named model groups, each built from its own derived LLMConfig (base
    # llm block + group plan + group overrides), served behind ONE
    # OpenAI endpoint that routes on the request's "model" field. Empty
    # (the default) = exactly today's single-model fleet, bit for bit.
    # With models set, dp_replicas/fleet.disagg/mesh>1 on the BASE block
    # are refused at load (each group sizes its own replicas; tiering
    # within groups is a later composition) — see validate_config.
    models: list[ModelGroupConfig] = Field(default_factory=list)
    # Latency SLOs evaluated at scrape time (utils/slo.py): exported as
    # runbook_slo_{target_ms,current_ms,burn_ratio,violations_total} and
    # an "slo" block in /healthz. No objectives set = no SLO series.
    slo: SLOConfig = Field(default_factory=SLOConfig)
    # Priority-class scheduling + SLO feedback (runbookai_tpu/sched/).
    sched: SchedConfig = Field(default_factory=SchedConfig)
    # Per-tenant (API-key) token budgets and rate limits, enforced by
    # the OpenAI server before enqueue (runbookai_tpu/sched/tenants.py).
    tenants: TenantsConfig = Field(default_factory=TenantsConfig)
    # Continuous workload fingerprinting + plan-drift detection
    # (runbookai_tpu/obs): runbook_workload_* / runbook_plan_stale /
    # runbook_replica_health series, /debug/workload, `runbook workload`.
    obs: ObsConfig = Field(default_factory=ObsConfig)
    guided_json: bool = True  # token-level JSON grammar masks for complete()


# --------------------------------------------------------------------------- #
# providers / incident / knowledge / safety / agent (reference parity blocks) #
# --------------------------------------------------------------------------- #


class AWSProviderConfig(BaseModel):
    enabled: bool = False
    profile: Optional[str] = None
    role_arn: Optional[str] = None
    regions: list[str] = Field(default_factory=lambda: ["us-east-1"])
    accounts: list[dict[str, Any]] = Field(default_factory=list)
    simulated: bool = False  # fixture-backed provider set (no cloud credentials)
    fixtures_path: Optional[str] = None


class KubernetesProviderConfig(BaseModel):
    enabled: bool = False
    contexts: list[str] = Field(default_factory=list)
    simulated: bool = False
    fixtures_path: Optional[str] = None


class GitProviderConfig(BaseModel):
    enabled: bool = False
    token: Optional[str] = None
    base_url: Optional[str] = None
    repos: list[str] = Field(default_factory=list)
    simulated: bool = False  # fixture-backed github_query (no token)


class OperabilityContextConfig(BaseModel):
    enabled: bool = False
    adapter: Literal["http", "sourcegraph", "entireio", "runbook-context", "custom"] = "http"
    base_url: Optional[str] = None
    token: Optional[str] = None
    capabilities: list[str] = Field(default_factory=list)


class ProvidersConfig(BaseModel):
    aws: AWSProviderConfig = Field(default_factory=AWSProviderConfig)
    kubernetes: KubernetesProviderConfig = Field(default_factory=KubernetesProviderConfig)
    github: GitProviderConfig = Field(default_factory=GitProviderConfig)
    gitlab: GitProviderConfig = Field(default_factory=GitProviderConfig)
    operability_context: OperabilityContextConfig = Field(
        default_factory=OperabilityContextConfig
    )


class PagerDutyConfig(BaseModel):
    enabled: bool = False
    api_key: Optional[str] = None
    simulated: bool = False


class OpsgenieConfig(BaseModel):
    enabled: bool = False
    api_key: Optional[str] = None
    simulated: bool = False


class SlackConfig(BaseModel):
    enabled: bool = False
    # Gateway transport. http is the default: socket mode needs slack_sdk
    # (an app-level token + websocket), which this build gates at startup —
    # defaulting to socket would make bare `slack-gateway` invocations exit.
    mode: Literal["socket", "http"] = "http"
    bot_token: Optional[str] = None
    signing_secret: Optional[str] = None
    app_token: Optional[str] = None
    default_channel: Optional[str] = None
    allowed_channels: list[str] = Field(default_factory=list)
    allowed_users: list[str] = Field(default_factory=list)
    require_thread: bool = False


class DatadogConfig(BaseModel):
    enabled: bool = False
    api_key: Optional[str] = None
    app_key: Optional[str] = None
    site: str = "datadoghq.com"
    simulated: bool = False


class PrometheusConfig(BaseModel):
    enabled: bool = False
    base_url: Optional[str] = None
    simulated: bool = False


class IncidentConfig(BaseModel):
    pagerduty: PagerDutyConfig = Field(default_factory=PagerDutyConfig)
    opsgenie: OpsgenieConfig = Field(default_factory=OpsgenieConfig)
    slack: SlackConfig = Field(default_factory=SlackConfig)


class ObservabilityConfig(BaseModel):
    datadog: DatadogConfig = Field(default_factory=DatadogConfig)
    prometheus: PrometheusConfig = Field(default_factory=PrometheusConfig)
    cloudwatch_enabled: bool = False


class KnowledgeSourceConfig(BaseModel):
    type: Literal["filesystem", "confluence", "google-drive"] = "filesystem"
    name: str = "default"
    path: Optional[str] = None  # filesystem
    base_url: Optional[str] = None  # confluence
    space: Optional[str] = None
    labels: list[str] = Field(default_factory=list)
    folder_id: Optional[str] = None  # google drive
    token: Optional[str] = None


class EmbedderConfig(BaseModel):
    """JAX bge-base encoder settings (replaces reference OpenAI embedder,
    ``src/knowledge/indexer/embedder.ts:20-22``: 1536-d text-embedding-3-small,
    batch 100 → 768-d bge-base-en-v1.5, on-device batch)."""

    enabled: bool = True
    model: str = "bge-base-en-v1.5"
    model_path: Optional[str] = None  # HF dir; None -> random init (tests)
    dim: int = 768
    batch_size: int = 64
    max_length: int = 512
    # LRU cap on the in-memory md5→embedding cache (entries). Bounds a
    # days-long indexer process; ~dim·4 bytes per entry.
    cache_max_entries: int = 4096


class KnowledgeConfig(BaseModel):
    sources: list[KnowledgeSourceConfig] = Field(default_factory=list)
    db_path: str = f"{CONFIG_DIR}/knowledge.db"
    embedder: EmbedderConfig = Field(default_factory=EmbedderConfig)
    # Hybrid fusion constants (reference hybrid-search.ts:17-19):
    rrf_k: int = 60
    fts_weight: float = 0.4
    vector_weight: float = 0.6


class SafetyConfig(BaseModel):
    """Reference ``config.yaml`` safety block + ``approval.ts`` policy knobs."""

    require_approval: list[str] = Field(default_factory=lambda: ["high", "critical"])
    auto_approve_low_risk: bool = True
    max_mutations_per_session: int = 5
    cooldown_seconds: int = 60
    approval_timeout_seconds: int = 300


class AgentConfig(BaseModel):
    max_iterations: int = 10  # free-form loop (agent.ts:48)
    max_investigation_iterations: int = 20  # FSM loop (state-machine.ts:206)
    max_hypotheses: int = 10
    max_hypothesis_depth: int = 4
    context_threshold_tokens: int = 100_000
    explain_mode: bool = False
    parallel_tool_calls: bool = True
    tool_cache_ttl_seconds: int = 300
    tool_cache_size: int = 100
    # Optional pre-discovery of AWS inventory/health into the system prompt
    # (reference infra-context.ts:597 factory — off by default: it spends
    # tool calls before the first iteration).
    infra_context: bool = False


class ClaudeIntegrationConfig(BaseModel):
    enabled: bool = False
    session_store: Literal["local", "s3"] = "local"
    session_store_path: str = f"{CONFIG_DIR}/claude-sessions"
    s3_bucket: Optional[str] = None


class IntegrationsConfig(BaseModel):
    claude: ClaudeIntegrationConfig = Field(default_factory=ClaudeIntegrationConfig)


class Config(BaseModel):
    llm: LLMConfig = Field(default_factory=LLMConfig)
    providers: ProvidersConfig = Field(default_factory=ProvidersConfig)
    incident: IncidentConfig = Field(default_factory=IncidentConfig)
    observability: ObservabilityConfig = Field(default_factory=ObservabilityConfig)
    knowledge: KnowledgeConfig = Field(default_factory=KnowledgeConfig)
    safety: SafetyConfig = Field(default_factory=SafetyConfig)
    agent: AgentConfig = Field(default_factory=AgentConfig)
    integrations: IntegrationsConfig = Field(default_factory=IntegrationsConfig)
    runbook_dir: str = CONFIG_DIR  # session/audit/scratchpad root


# --------------------------------------------------------------------------- #
# services.yaml (infra inventory)                                             #
# --------------------------------------------------------------------------- #


class ServiceEntry(BaseModel):
    name: str
    type: str = "service"
    team: Optional[str] = None
    tier: Optional[int] = None
    tags: list[str] = Field(default_factory=list)
    depends_on: list[str] = Field(default_factory=list)
    aws: dict[str, Any] = Field(default_factory=dict)
    observability: dict[str, Any] = Field(default_factory=dict)


class ServicesConfig(BaseModel):
    accounts: list[dict[str, Any]] = Field(default_factory=list)
    services: list[ServiceEntry] = Field(default_factory=list)


# --------------------------------------------------------------------------- #
# loading                                                                     #
# --------------------------------------------------------------------------- #


def _search_paths(filename: str, cwd: Optional[Path] = None) -> list[Path]:
    cwd = cwd or Path.cwd()
    return [cwd / CONFIG_DIR / filename, Path.home() / CONFIG_DIR / filename]


def load_config(path: Optional[str | Path] = None, cwd: Optional[Path] = None) -> Config:
    """Load + validate config. Search order: explicit path, CWD/.runbook,
    $HOME/.runbook; missing file -> defaults (mock provider, everything off)."""
    candidates = [Path(path)] if path else _search_paths(CONFIG_FILE, cwd)
    for p in candidates:
        if p.is_file():
            raw = yaml.safe_load(p.read_text()) or {}
            return Config.model_validate(_interpolate(raw))
    return Config()


def load_services(path: Optional[str | Path] = None, cwd: Optional[Path] = None) -> ServicesConfig:
    candidates = [Path(path)] if path else _search_paths(SERVICES_FILE, cwd)
    for p in candidates:
        if p.is_file():
            raw = yaml.safe_load(p.read_text()) or {}
            return ServicesConfig.model_validate(_interpolate(raw))
    return ServicesConfig()


def save_config(config: Config, path: str | Path) -> None:
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(yaml.safe_dump(config.model_dump(mode="json"), sort_keys=False))


def set_config_value(config: Config, dotted_key: str, value: str) -> Config:
    """``runbook config --set a.b.c=v`` nested sets (reference cli.tsx:1587)."""
    data = config.model_dump()
    node = data
    parts = dotted_key.split(".")
    for part in parts[:-1]:
        nxt = node.get(part)
        if not isinstance(nxt, dict):
            nxt = {}
            node[part] = nxt
        node = nxt
    parsed: Any = value
    try:
        parsed = yaml.safe_load(value)
    except yaml.YAMLError:
        pass
    node[parts[-1]] = parsed
    return Config.model_validate(data)


def _validate_models(config: Config) -> list[str]:
    """``llm.models`` (multi-model fleet) pre-flight checks: unique
    served names, adapter names that cannot shadow a group, overrides
    that actually name LLMConfig fields, per-group plans that exist and
    match their group's model, and base-block knobs that do not compose
    with model groups. Tenant model pins must name a served group."""
    problems: list[str] = []
    groups = config.llm.models
    if not groups:
        for name, policy in config.llm.tenants.keys.items():
            if policy.model:
                problems.append(
                    f"llm.tenants.keys.{name}.model={policy.model!r} "
                    f"needs llm.models (there is no model catalog to "
                    f"pin the tenant to)")
        return problems
    if config.llm.dp_replicas != 1:
        problems.append(
            "llm.models and llm.dp_replicas do not compose: each group "
            "sizes its own replicas via models[].dp_replicas")
    if config.llm.mesh.device_count > 1:
        problems.append(
            "llm.models requires llm.mesh.data/model = 1 (each group "
            "replica owns its own device slice; TP within a group is a "
            "later composition)")
    if config.llm.fleet.disagg.enabled:
        problems.append(
            "llm.models and llm.fleet.disagg do not compose yet "
            "(prefill/decode tiering is per-fleet, not per-group)")
    served: set[str] = set()
    for i, group in enumerate(groups):
        where = f"llm.models[{i}] ({group.name!r})"
        if group.name in served:
            problems.append(f"{where}: duplicate served model name")
        served.add(group.name)
        bad = set(group.overrides) - set(LLMConfig.model_fields)
        if bad:
            problems.append(
                f"{where}: overrides name unknown llm.* keys "
                f"{sorted(bad)}")
        reserved = RESERVED_GROUP_OVERRIDE_KEYS & set(group.overrides)
        if reserved:
            problems.append(
                f"{where}: overrides cannot set {sorted(reserved)} — "
                f"these are group-entry fields (set them on the entry "
                f"itself)")
        if group.plan:
            if not Path(group.plan).is_file():
                problems.append(f"{where}: plan does not exist: "
                                f"{group.plan}")
            else:
                from runbookai_tpu.autotune.plan import load_plan

                try:
                    plan = load_plan(group.plan)
                except ValueError as e:
                    problems.append(f"{where}: plan: {e}")
                else:
                    want = group.model or group.name
                    if plan.model != want:
                        problems.append(
                            f"{where}: plan was tuned for model "
                            f"{plan.model!r} but the group serves "
                            f"{want!r}")
    adapters = {name for g in groups for name in g.adapters}
    shadowing = adapters & served
    for name in sorted(shadowing):
        problems.append(
            f"llm.models: adapter name {name!r} shadows a served model "
            f"group (the request's model field could mean either)")
    seen_adapters: set[str] = set()
    for group in groups:
        dup = seen_adapters & set(group.adapters)
        for name in sorted(dup):
            problems.append(
                f"llm.models: adapter name {name!r} appears in more "
                f"than one group (adapter-as-model requests would be "
                f"ambiguous)")
        seen_adapters |= set(group.adapters)
    for name, policy in config.llm.tenants.keys.items():
        if policy.model and policy.model not in served:
            problems.append(
                f"llm.tenants.keys.{name}.model={policy.model!r} is not "
                f"a served model group (served: {sorted(served)})")
    return problems


def validate_config(config: Config) -> list[str]:
    """Return human-readable problems (reference validateConfig :292)."""
    problems: list[str] = []
    if config.llm.provider == "jax-tpu" and config.llm.model_path:
        if not Path(config.llm.model_path).exists():
            problems.append(f"llm.model_path does not exist: {config.llm.model_path}")
    if config.llm.plan:
        if not Path(config.llm.plan).is_file():
            problems.append(f"llm.plan does not exist: {config.llm.plan}")
        else:
            from runbookai_tpu.autotune.plan import load_plan

            try:
                plan = load_plan(config.llm.plan)
            except ValueError as e:
                problems.append(f"llm.plan: {e}")
            else:
                if plan.model != config.llm.model:
                    problems.append(
                        f"llm.plan was tuned for model {plan.model!r} but "
                        f"llm.model is {config.llm.model!r}")
    for src in config.knowledge.sources:
        if src.type == "filesystem" and src.path and not Path(src.path).exists():
            problems.append(f"knowledge source path does not exist: {src.path}")
        if src.type == "confluence" and not src.base_url:
            problems.append(f"confluence source {src.name!r} missing base_url")
    mesh = config.llm.mesh
    if mesh.data < 1 or mesh.model < 1:
        problems.append("llm.mesh axes must be >= 1")
    if config.llm.dp_replicas < 1:
        problems.append("llm.dp_replicas must be >= 1")
    if config.llm.dp_replicas > 1 and mesh.device_count > 1:
        problems.append(
            "llm.dp_replicas > 1 requires llm.mesh.data/model = 1 "
            "(each fleet replica owns its own device slice)")
    disagg = config.llm.fleet.disagg
    if disagg.enabled:
        if config.llm.dp_replicas < 2:
            problems.append(
                "llm.fleet.disagg needs llm.dp_replicas >= 2 (one prefill "
                "replica and at least one decode replica)")
        elif disagg.prefill_replicas >= config.llm.dp_replicas:
            problems.append(
                f"llm.fleet.disagg.prefill_replicas="
                f"{disagg.prefill_replicas} leaves no decode tier in a "
                f"dp_replicas={config.llm.dp_replicas} fleet")
    problems.extend(_validate_models(config))
    if (config.llm.sched.feedback
            and config.llm.slo.tpot_p95_ms is None):
        problems.append(
            "llm.sched.feedback: true requires llm.slo.tpot_p95_ms — the "
            "controller's input signal (sched/feedback.py)")
    sched = config.llm.sched
    if sched.feedback_grow_at > sched.feedback_shrink_at:
        # MixedBudgetController refuses this at engine build; the
        # pre-flight validator must catch it first, not a serve crash.
        problems.append(
            f"llm.sched.feedback_grow_at={sched.feedback_grow_at} must "
            f"be <= feedback_shrink_at={sched.feedback_shrink_at} "
            f"(the hysteresis band would be inverted)")
    slack = config.incident.slack
    if (slack.enabled and slack.app_token
            and "mode" not in slack.model_fields_set):
        problems.append(
            "incident.slack: app_token is set but mode is defaulted to "
            "'http' — socket-mode deployments must now set mode: socket "
            "explicitly (the default changed from 'socket')")
    return problems
