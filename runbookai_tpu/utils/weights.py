"""Real-weights discovery: the on-ramp from random-init to measured quality.

Bench and eval run random-init weights in the no-egress build environment —
identical compute, but the QUALITY axis (eval pass@1, speculation
acceptance) is meaningless until a real checkpoint is in play. VERDICT r4
next-round #3 asks for (a) automatic pickup of a real checkpoint the moment
one exists and (b) an explicit marker in every bench/eval artifact until
then, so "quality: unmeasured" is stated rather than implied.

Protocol once weights exist (see docs/WEIGHTS.md for the full recipe):

    export RUNBOOK_WEIGHTS=/path/to/checkpoints   # dir of dirs, or one model
    python bench.py                               # picks them up, marks it
    runbook eval --live                           # pass@1 against threshold 0.7

``RUNBOOK_WEIGHTS`` may point at a single HF/orbax checkpoint directory or
at a parent directory containing one subdirectory per model config name.
Reference: scoring threshold from the reference's ``src/eval/scoring.ts``
(pass at total >= 0.7) and ``docs/INVESTIGATION_EVAL.md``.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional

ENV_VAR = "RUNBOOK_WEIGHTS"
QUALITY_UNMEASURED = "unmeasured (random weights)"


def discover_weights(model_name: Optional[str] = None,
                     configured: Optional[str] = None) -> Optional[str]:
    """Resolve a real-weights path, or None to random-init.

    An explicitly configured path (``llm.model_path`` in config) wins;
    otherwise ``$RUNBOOK_WEIGHTS`` is tried — first as a parent holding a
    ``<model_name>/`` subdirectory, then as the checkpoint dir itself.
    """
    if configured and Path(configured).exists():
        return str(configured)
    root = os.environ.get(ENV_VAR)
    if not root:
        return None
    p = Path(root)
    if model_name and (p / model_name).exists():
        return str(p / model_name)
    return str(p) if p.exists() else None


def quality_marker(weights_path: Optional[str]) -> str:
    """The honesty string carried in every bench/eval artifact."""
    if weights_path:
        return f"real weights: {weights_path}"
    return QUALITY_UNMEASURED
