"""Serving-grade metrics: Counters, Gauges, fixed-bucket Histograms, and
Prometheus text exposition — dependency-free (no prometheus_client in the
image), thread-safe, one process-wide registry.

Production LLM serving treats per-request latency histograms and cache/pool
gauges as the control signals for routing and autoscaling (AIBrix,
arXiv:2504.03648); this module is the in-tree layer every subsystem reports
through:

- engine (``engine/engine.py``): TTFT/TPOT/e2e/queue-wait histograms, KV-pool
  and scheduler gauges, and the legacy step-counter dict re-exported as
  counters (scrape-time callbacks — the dict stays the ``/healthz`` contract
  and the single source of truth; nothing is double-counted).
- server (``server/openai_api.py``): per-route request/latency metrics and
  the ``GET /metrics`` exposition endpoint.
- agent (``agent/parallel_executor.py``, ``agent/agent.py``): per-tool
  latency/error counters and LLM token-usage counters.

Contracts (enforced here, pinned by ``tests/test_metrics.py``):

- every metric name matches ``^runbook_[a-z0-9_]+$`` (no dashboard drift);
- histograms declare explicit, strictly increasing buckets;
- registration is get-or-create: re-registering a name returns the existing
  metric (engines are rebuilt freely in tests) but a type/label mismatch is
  an error, never silent aliasing.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from typing import Callable, Iterable, Optional, Sequence

METRIC_NAME_RE = re.compile(r"^runbook_[a-z0-9_]+$")
_LABEL_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

# Shared bucket layouts (seconds). Callers may pass their own; these keep the
# in-tree instrumentation consistent so PromQL templates transfer.
TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                10.0, 30.0, 60.0)
TPOT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5)
E2E_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
               120.0, 300.0, 600.0)
QUEUE_WAIT_BUCKETS = TTFT_BUCKETS
REQUEST_LATENCY_BUCKETS = E2E_BUCKETS
TOOL_LATENCY_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                        10.0, 30.0, 60.0, 120.0)
# Token counts per mixed prefill+decode dispatch (powers of two up to the
# largest plausible mixed_token_budget) — a count histogram, not seconds.
MIXED_TOKENS_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                        512.0, 1024.0, 2048.0, 4096.0)


def percentile_from_counts(bounds: Sequence[float],
                           counts: Sequence[float],
                           q: float) -> Optional[float]:
    """q-th percentile (0–100) from per-bucket observation counts — the
    ONE bucket-interpolation implementation. ``bounds`` are a
    histogram's finite upper bounds; ``counts`` carries one entry per
    finite bucket plus the trailing ``+Inf`` overflow (the
    :meth:`Histogram.bucket_counts` layout). Linear interpolation inside
    the winning bucket; the overflow clamps to the last finite bound.
    None when the window is empty.

    Every windowed-percentile consumer goes through here: lifetime and
    windowed :class:`Histogram` percentiles, the sched/feedback burn
    windows and obs/incident queue-wait readings (via
    :class:`HistogramWindow`), and obs/query's ``histogram_quantile()``
    over stored bucket snapshots — pinned by the parity test in
    tests/test_tsdb.py so the implementations cannot re-diverge.
    """
    counts = list(counts)
    total = sum(counts)
    if total == 0:
        return None
    target = max(1.0, math.ceil(q / 100.0 * total))
    cum = 0.0
    lower = 0.0
    for i, upper in enumerate(bounds):
        c = counts[i]
        if cum + c >= target:
            return lower + (upper - lower) * ((target - cum) / c)
        cum += c
        lower = upper
    return float(bounds[-1])


class HistogramWindow:
    """Bucket-snapshot-diff windowing over one :class:`Histogram`
    labelset — the shared spelling of "percentile of the observations
    since the last decision point" (previously hand-rolled in parallel
    by ``sched/feedback.MixedBudgetController.burn`` and
    ``obs/incident.IncidentMonitor``).

    Semantics, chosen so both call sites keep their behavior:

    - the mark advances only when a window is CONSUMED (``advance``
      returned counts), so sparse traffic accumulates until it carries
      at least ``min_obs`` observations instead of being dropped;
    - a histogram reset under us (any bucket count going backwards —
      bench warmup, tests) resyncs the mark and yields None rather than
      a garbage negative window;
    - ``prime_zero=True`` makes the first window read everything
      observed so far (the feedback controller's first decision);
      the default primes at the current counts, so the first call only
      sets the mark (the incident monitor's first poll is absent).
    """

    def __init__(self, hist: "Histogram", key: tuple[str, ...] = (), *,
                 prime_zero: bool = False):
        self.hist = hist
        self.key = tuple(key)
        self._mark: Optional[list[float]] = None
        self._prime_zero = bool(prime_zero)

    def advance(self, min_obs: int = 1) -> Optional[list[float]]:
        """Per-bucket counts of the observations since the last consumed
        window, or None (too few, reset, or an unprimed first call)."""
        counts = self.hist.bucket_counts(self.key)
        if self._mark is None:
            if self._prime_zero:
                self._mark = [0.0] * len(counts)
            else:
                self._mark = counts
                return None
        if any(now < then for now, then in zip(counts, self._mark)):
            self._mark = counts
            return None
        window = [now - then for now, then in zip(counts, self._mark)]
        if sum(window) < max(1, int(min_obs)):
            return None
        self._mark = counts
        return window

    def percentile(self, q: float,
                   min_obs: int = 1) -> Optional[float]:
        """``advance()`` + interpolate in one call (the incident
        monitor's queue-wait reading)."""
        window = self.advance(min_obs)
        if window is None:
            return None
        return percentile_from_counts(self.hist.buckets, window, q)


def _escape_label_value(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _format_value(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Child:
    """Bound (metric, labelset) handle: ``metric.labels(route="x").inc()``."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "_Metric", key: tuple[str, ...]):
        self._metric = metric
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        self._metric._inc(self._key, amount)

    def dec(self, amount: float = 1.0) -> None:
        self._metric._inc(self._key, -amount)

    def set(self, value: float) -> None:
        self._metric._set(self._key, value)

    def observe(self, value: float) -> None:
        self._metric._observe(self._key, value)

    def set_function(self, fn: Callable[[], float]) -> "_Child":
        """Sample ``fn()`` at scrape time for THIS labelset (the labeled
        twin of ``_Metric.set_function`` — per-replica engine gauges bind
        one callback per replica label). Re-binding a labelset replaces
        its previous callback."""
        self._metric._set_key_function(self._key, fn)
        return self

    @property
    def value(self) -> float:
        """Stored value of THIS labelset (the labeled twin of
        ``Counter.value`` — fleet health snapshots read their own
        model's series, never a cross-group total)."""
        with self._metric._lock:
            return self._metric._values.get(self._key, 0.0)


class _Metric:
    type = "untyped"

    def __init__(self, name: str, help_text: str,
                 labels: Sequence[str] = ()):
        if not METRIC_NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} must match {METRIC_NAME_RE.pattern}")
        for label in labels:
            if not _LABEL_NAME_RE.match(label) or label == "le":
                raise ValueError(f"bad label name {label!r} for {name}")
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labels)
        self._lock = threading.Lock()
        self._values: dict[tuple[str, ...], float] = {}
        self._fn: Optional[Callable[[], float]] = None
        # Per-labelset scrape-time callbacks (labeled set_function): each
        # key's callback shadows any stored value for that key.
        self._key_fns: dict[tuple[str, ...], Callable[[], float]] = {}

    # ------------------------------------------------------------- labelling

    def labels(self, *values, **kv) -> _Child:
        if values and kv:
            raise ValueError("pass label values positionally or by name")
        if kv:
            try:
                values = tuple(kv[name] for name in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"{self.name} expects labels {self.labelnames}") from e
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects {len(self.labelnames)} label values")
        return _Child(self, tuple(str(v) for v in values))

    def _check_unlabeled(self) -> None:
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; use .labels()")

    def set_function(self, fn: Callable[[], float]) -> "_Metric":
        """Sample ``fn()`` at scrape time instead of storing a value.

        Re-binding replaces the previous callback (an engine rebuilt in the
        same process takes over its gauges; the old engine is released).
        Unlabeled metrics only.
        """
        self._check_unlabeled()
        self._fn = fn
        return self

    def _set_key_function(self, key: tuple[str, ...],
                          fn: Callable[[], float]) -> None:
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects {len(self.labelnames)} label values")
        with self._lock:
            self._key_fns[key] = fn

    def clear_functions(self) -> None:
        """Drop every scrape-time callback (labeled and unlabeled). A
        rebuilt fleet calls this before re-binding so replica labelsets
        from a larger previous fleet don't keep scraping dead engines."""
        self._fn = None
        with self._lock:
            self._key_fns.clear()

    # ---------------------------------------------------------------- values

    def _inc(self, key: tuple[str, ...], amount: float) -> None:
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def _set(self, key: tuple[str, ...], value: float) -> None:
        with self._lock:
            self._values[key] = float(value)

    def _observe(self, key: tuple[str, ...], value: float) -> None:
        raise ValueError(f"{self.name} ({self.type}) does not observe()")

    def _callback_value(self) -> Optional[float]:
        if self._fn is None:
            return None
        try:
            return float(self._fn())
        except Exception:  # noqa: BLE001 — a dead engine must not 500 /metrics
            return None

    # -------------------------------------------------------------- sampling

    def samples(self) -> list[tuple[str, tuple[tuple[str, str], ...], float]]:
        """``(name_suffix, ((label, value), ...), value)`` triples."""
        out: list[tuple[str, tuple[tuple[str, str], ...], float]] = []
        cb = self._callback_value()
        if cb is not None:
            out.append(("", (), cb))
        with self._lock:
            items = sorted(self._values.items())
            key_fns = sorted(self._key_fns.items())
        # Callbacks run OUTSIDE the metric lock: they read live engine
        # state and must never deadlock a scrape against an engine step.
        seen: set[tuple[str, ...]] = set()
        for key, fn in key_fns:
            # A bound callback owns its labelset even when it raises: the
            # series is dropped, never replaced by a stale stored value
            # masquerading as live data.
            seen.add(key)
            try:
                value = float(fn())
            except Exception:  # noqa: BLE001 — dead engine must not 500 /metrics
                continue
            out.append(("", tuple(zip(self.labelnames, key)), value))
        for key, value in items:
            if key in seen:
                continue  # the callback shadows any stored value
            out.append(("", tuple(zip(self.labelnames, key)), value))
        if not out and not self.labelnames:
            out.append(("", (), 0.0))
        return out

    def reset(self) -> None:
        with self._lock:
            self._values.clear()


class Counter(_Metric):
    type = "counter"

    def inc(self, amount: float = 1.0) -> None:
        self._check_unlabeled()
        if amount < 0:
            raise ValueError("counters only increase")
        self._inc((), amount)

    def _inc(self, key: tuple[str, ...], amount: float) -> None:
        if amount < 0:
            raise ValueError("counters only increase")
        super()._inc(key, amount)

    def _set(self, key, value) -> None:
        raise ValueError(f"{self.name} is a counter; use inc()")

    @property
    def value(self) -> float:
        cb = self._callback_value()
        if cb is not None:
            return cb
        with self._lock:
            return self._values.get((), 0.0)

    def total(self) -> float:
        """Sum across every label set (equals ``value`` when unlabeled) —
        the public 'how many in all' accessor, so callers never read the
        private per-labelset storage."""
        cb = self._callback_value()
        if cb is not None:
            return cb
        with self._lock:
            return float(sum(self._values.values()))


class Gauge(_Metric):
    type = "gauge"

    def set(self, value: float) -> None:
        self._check_unlabeled()
        self._set((), value)

    def inc(self, amount: float = 1.0) -> None:
        self._check_unlabeled()
        self._inc((), amount)

    def dec(self, amount: float = 1.0) -> None:
        self._check_unlabeled()
        self._inc((), -amount)

    @property
    def value(self) -> float:
        cb = self._callback_value()
        if cb is not None:
            return cb
        with self._lock:
            return self._values.get((), 0.0)


class Histogram(_Metric):
    """Fixed-bucket histogram: cumulative ``le`` buckets + sum + count.

    Buckets are upper bounds in ascending order; an implicit ``+Inf`` bucket
    is always appended. Explicit buckets are REQUIRED — a histogram whose
    buckets are implied defaults drifts silently when the library changes.
    """

    type = "histogram"

    def __init__(self, name: str, help_text: str,
                 buckets: Sequence[float], labels: Sequence[str] = ()):
        super().__init__(name, help_text, labels)
        buckets = tuple(float(b) for b in buckets)
        if not buckets:
            raise ValueError(f"{name}: histograms require explicit buckets")
        if any(b != b or b in (float("inf"), float("-inf")) for b in buckets):
            raise ValueError(f"{name}: buckets must be finite")
        if any(b2 <= b1 for b1, b2 in zip(buckets, buckets[1:])):
            raise ValueError(f"{name}: buckets must be strictly increasing")
        self.buckets = buckets
        # key -> [per-bucket counts..., +Inf count, sum]
        self._hist: dict[tuple[str, ...], list[float]] = {}

    def observe(self, value: float) -> None:
        self._check_unlabeled()
        self._observe((), value)

    def _observe(self, key: tuple[str, ...], value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            state = self._hist.get(key)
            if state is None:
                state = self._hist[key] = [0.0] * (len(self.buckets) + 2)
            state[idx] += 1
            state[-1] += value

    def _inc(self, key, amount) -> None:
        raise ValueError(f"{self.name} is a histogram; use observe()")

    def _set(self, key, value) -> None:
        raise ValueError(f"{self.name} is a histogram; use observe()")

    def _set_key_function(self, key, fn) -> None:
        raise ValueError(f"{self.name} is a histogram; use observe()")

    def _state(self, key: tuple[str, ...] = ()) -> tuple[list[float], float, float]:
        with self._lock:
            state = list(self._hist.get(key)
                         or [0.0] * (len(self.buckets) + 2))
        counts = state[:-1]
        return counts, sum(counts), state[-1]

    @property
    def count(self) -> float:
        return self._state()[1]

    @property
    def sum(self) -> float:
        return self._state()[2]

    def percentile(self, q: float,
                   key: tuple[str, ...] = ()) -> Optional[float]:
        """Approximate q-th percentile (linear interpolation inside the
        bucket; the ``+Inf`` bucket clamps to the last finite bound).
        Accuracy is bounded by bucket width — good enough for tail-latency
        tracking (``bench.py`` p95s), not for exact SLO math."""
        return self._interpolate(self._state(key)[0], q)

    def _interpolate(self, counts: list[float], q: float) -> Optional[float]:
        return percentile_from_counts(self.buckets, counts, q)

    def bucket_counts(self, key: tuple[str, ...] = ()) -> list[float]:
        """Per-bucket observation counts (finite buckets + the ``+Inf``
        overflow) — a snapshot for windowed percentiles."""
        with self._lock:
            state = list(self._hist.get(key)
                         or [0.0] * (len(self.buckets) + 2))
        return state[:-1]

    def percentile_since(self, q: float, baseline: Sequence[float],
                         key: tuple[str, ...] = ()) -> Optional[float]:
        """q-th percentile of the observations made SINCE ``baseline``
        (a prior :meth:`bucket_counts` snapshot) — the windowed view a
        feedback controller needs: a process-lifetime percentile takes
        hours of bad samples to move after a day of good ones. None when
        the window is empty (or the histogram was reset under us)."""
        counts = [max(0.0, now - then)
                  for now, then in zip(self.bucket_counts(key), baseline)]
        return self._interpolate(counts, q)

    def samples(self):
        out = []
        with self._lock:
            items = sorted(self._hist.items())
        for key, state in items:
            base = tuple(zip(self.labelnames, key))
            cum = 0.0
            for i, upper in enumerate(self.buckets):
                cum += state[i]
                out.append(("_bucket",
                            base + (("le", _format_value(upper)),), cum))
            cum += state[len(self.buckets)]
            out.append(("_bucket", base + (("le", "+Inf"),), cum))
            out.append(("_sum", base, state[-1]))
            out.append(("_count", base, cum))
        if not items and not self.labelnames:
            for upper in self.buckets:
                out.append(("_bucket", (("le", _format_value(upper)),), 0.0))
            out.append(("_bucket", (("le", "+Inf"),), 0.0))
            out.append(("_sum", (), 0.0))
            out.append(("_count", (), 0.0))
        return out

    def reset(self) -> None:
        with self._lock:
            self._hist.clear()


class MetricsRegistry:
    """Named metric store with get-or-create registration and exposition."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _register(self, cls, name: str, help_text: str,
                  labels: Sequence[str], **kw) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"{name} already registered as {existing.type}")
                if existing.labelnames != tuple(labels):
                    raise ValueError(
                        f"{name} already registered with labels "
                        f"{existing.labelnames}")
                want = kw.get("buckets")
                if want is not None and tuple(
                        float(b) for b in want) != existing.buckets:
                    raise ValueError(
                        f"{name} already registered with buckets "
                        f"{existing.buckets}")
                return existing
            metric = cls(name, help_text, labels=labels, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help_text: str,
                labels: Sequence[str] = ()) -> Counter:
        return self._register(Counter, name, help_text, labels)

    def gauge(self, name: str, help_text: str,
              labels: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge, name, help_text, labels)

    def histogram(self, name: str, help_text: str, *,
                  buckets: Sequence[float],
                  labels: Sequence[str] = ()) -> Histogram:
        return self._register(Histogram, name, help_text, labels,
                              buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def __iter__(self) -> Iterable[_Metric]:
        with self._lock:
            return iter(sorted(self._metrics.values(),
                               key=lambda m: m.name))

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: list[str] = []
        for metric in self:
            lines.append(f"# HELP {metric.name} {_escape_help(metric.help)}")
            lines.append(f"# TYPE {metric.name} {metric.type}")
            for suffix, labels, value in metric.samples():
                if labels:
                    body = ",".join(
                        f'{k}="{_escape_label_value(v)}"' for k, v in labels)
                    lines.append(f"{metric.name}{suffix}{{{body}}} "
                                 f"{_format_value(value)}")
                else:
                    lines.append(
                        f"{metric.name}{suffix} {_format_value(value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Flat JSON-friendly view (``/healthz`` extensions, tooling).

        Counters/gauges map to numbers (labeled children keyed by
        ``name{a=b,...}``); histograms map to {count, sum, p50, p95, p99}.
        """
        out: dict = {}
        for metric in self:
            if isinstance(metric, Histogram):
                keys = {()} if not metric.labelnames else set()
                with metric._lock:
                    keys |= set(metric._hist)
                for key in sorted(keys):
                    counts, total, s = metric._state(key)
                    name = metric.name
                    if key:
                        body = ",".join(f"{k}={v}" for k, v
                                        in zip(metric.labelnames, key))
                        name = f"{name}{{{body}}}"
                    out[name] = {
                        "count": total, "sum": round(s, 6),
                        "p50": metric.percentile(50, key),
                        "p95": metric.percentile(95, key),
                        "p99": metric.percentile(99, key),
                    }
                continue
            for _suffix, labels, value in metric.samples():
                name = metric.name
                if labels:
                    body = ",".join(f"{k}={v}" for k, v in labels)
                    name = f"{name}{{{body}}}"
                out[name] = value
        return out

    def reset(self) -> None:
        """Zero every metric's stored state (tests, bench warmup)."""
        for metric in self:
            metric.reset()


REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every in-tree subsystem reports through."""
    return REGISTRY
