"""Tracing: host-side span JSONL + device (XProf) profiling hooks.

SURVEY.md §5.1 — the reference has no tracer; its only "trace" is per-tool
``durationMs`` plus the scratchpad JSONL. The TPU build adds the real thing:

- :class:`Tracer` — nested host spans appended as JSONL (one object per
  span: ts, name, ms, depth, meta). Cheap enough to leave on in production;
  a disabled tracer costs one ``if``.
- :func:`annotate` — ``jax.profiler.TraceAnnotation`` passthrough so engine
  dispatches (prefill/decode/spec) show up on the XProf/TensorBoard device
  timeline with meaningful names.
- :func:`device_trace` — context manager around
  ``jax.profiler.start_trace``/``stop_trace`` for capturing a device profile
  of any region (``RUNBOOK_DEVICE_TRACE=<logdir>`` wraps a whole CLI run).

Enable globally with ``RUNBOOK_TRACE=<file.jsonl>`` (or ``1`` for the
default ``.runbook/trace/<pid>.jsonl``) or by passing a Tracer explicitly.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import warnings
from pathlib import Path
from typing import Any, Iterator, Optional


# Default byte cap per trace file before rotation: a 1800s soak at full
# span volume stays bounded on disk instead of growing the JSONL forever.
# One rotated generation (<file>.1) is kept; RUNBOOK_TRACE_MAX_MB
# overrides (0 = unbounded).
DEFAULT_TRACE_MAX_BYTES = 256 * 1024 * 1024


class Tracer:
    """Appends nested span records to a JSONL file.

    Thread-safe: the process-wide tracer is shared across server request
    threads and the engine loop, so span depth is tracked per-thread and
    each record is written whole under a lock.

    Size-bounded: when a write would push the file past ``max_bytes``,
    the current file rotates to ``<path>.1`` (replacing any previous
    generation) and a fresh file begins — at most ~2× the cap on disk,
    with the rotation counted in ``runbook_trace_rotations_total`` so a
    soak run's dashboards see the trail turning over.
    """

    def __init__(self, path: Optional[str | Path], enabled: bool = True,
                 max_bytes: Optional[int] = DEFAULT_TRACE_MAX_BYTES):
        self.enabled = enabled and path is not None
        self.path = Path(path) if path else None
        self.max_bytes = max_bytes if max_bytes else None
        self._local = threading.local()
        self._lock = threading.Lock()
        self._fh = None
        self._bytes = 0
        self._rotations = 0
        self._warned = False
        if self.enabled:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._fh = open(self.path, "a", buffering=1)  # line-buffered
            try:
                self._bytes = self.path.stat().st_size
            except OSError:
                self._bytes = 0

    @property
    def _depth(self) -> int:
        return getattr(self._local, "depth", 0)

    @_depth.setter
    def _depth(self, value: int) -> None:
        self._local.depth = value

    # -------------------------------------------------------------- context

    def set_context(self, **fields: Any) -> None:
        """Attach per-thread fields to every span/event this thread writes
        until :meth:`clear_context` — e.g. the server sets
        ``request_id=<x-request-id>`` for the handler thread so a JSONL
        trace line can be joined to its request's metrics."""
        ctx = getattr(self._local, "ctx", None)
        if ctx is None:
            ctx = self._local.ctx = {}
        ctx.update(fields)

    def clear_context(self) -> None:
        self._local.ctx = {}

    def _ctx(self) -> Optional[dict[str, Any]]:
        ctx = getattr(self._local, "ctx", None)
        return dict(ctx) if ctx else None

    def _write(self, rec: dict[str, Any]) -> None:
        try:
            line = json.dumps(rec) + "\n"
            rotated = False
            with self._lock:
                if self._fh is None:
                    return  # closed deliberately: silence, not a warning
                if (self.max_bytes is not None and self._bytes > 0
                        and self._bytes + len(line) > self.max_bytes):
                    # Rotate the live file to ``<path>.1`` (replacing any
                    # previous generation) and start fresh — the swap must
                    # be atomic against the other writer threads, and it
                    # runs once per ``max_bytes`` of trace volume, so the
                    # bounded stall is the price of a bounded footprint.
                    self._fh.flush()
                    self._fh.close()
                    os.replace(self.path,
                               self.path.with_name(self.path.name + ".1"))
                    self._fh = self.path.open("a", buffering=1)
                    self._bytes = 0
                    self._rotations += 1
                    rotated = True
                self._fh.write(line)
                self._bytes += len(line)
            if rotated:
                # Metric outside the write lock (RBK003: the registry has
                # its own lock and scrape callbacks must not nest under
                # the tracer's).
                from runbookai_tpu.utils import metrics as metrics_mod

                metrics_mod.get_registry().counter(
                    "runbook_trace_rotations_total",
                    "Trace JSONL rotations at the byte cap").inc()
        except (OSError, ValueError) as e:
            # Disk gone / fh poisoned: stop tracing, keep serving — but
            # never silently (operators must learn their trail went dark).
            # Disable under the same lock close() takes (the with-block
            # above already released it on the exception path), so the
            # enabled flag has one consistent writer discipline.
            with self._lock:
                self.enabled = False
            if not self._warned:
                self._warned = True
                warnings.warn(
                    f"tracing disabled: could not write {self.path} "
                    f"({type(e).__name__}: {e})", RuntimeWarning,
                    stacklevel=3)

    @contextlib.contextmanager
    def span(self, name: str, **meta: Any) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        self._depth += 1
        depth = self._depth
        try:
            yield
        finally:
            self._depth -= 1
            rec = {"ts": time.time(), "name": name, "depth": depth,
                   "ms": round((time.perf_counter() - t0) * 1e3, 3)}
            ctx = self._ctx()
            if ctx:
                rec["ctx"] = ctx
            if meta:
                rec["meta"] = meta
            self._write(rec)

    def event(self, name: str, **meta: Any) -> None:
        """Zero-duration marker."""
        if not self.enabled:
            return
        rec = {"ts": time.time(), "name": name, "depth": self._depth + 1, "ms": 0.0}
        ctx = self._ctx()
        if ctx:
            rec["ctx"] = ctx
        if meta:
            rec["meta"] = meta
        self._write(rec)

    def close(self) -> None:
        """Flush and release the line-buffered handle; tracing stays off."""
        with self._lock:
            if self._fh:
                self._fh.flush()
                self._fh.close()
                self._fh = None
                self.enabled = False


_NULL = Tracer(None, enabled=False)
_global: Optional[Tracer] = None


def get_tracer() -> Tracer:
    """Process-wide tracer, configured from ``RUNBOOK_TRACE`` on first use."""
    global _global
    if _global is None:
        env = os.environ.get("RUNBOOK_TRACE", "")
        if not env:
            _global = _NULL
        else:
            path = (Path(".runbook") / "trace" / f"{os.getpid()}.jsonl"
                    if env == "1" else Path(env))
            max_bytes: Optional[int] = DEFAULT_TRACE_MAX_BYTES
            cap_env = os.environ.get("RUNBOOK_TRACE_MAX_MB", "")
            if cap_env:
                try:
                    mb = float(cap_env)
                    max_bytes = int(mb * 1024 * 1024) if mb > 0 else None
                except ValueError:
                    pass  # malformed cap keeps the default
            try:
                _global = Tracer(path, max_bytes=max_bytes)
            except OSError:
                _global = _NULL
    return _global


def set_tracer(tracer: Optional[Tracer]) -> None:
    global _global
    _global = tracer if tracer is not None else _NULL


def annotate(name: str):
    """Named region on the XProf device timeline (no-op off-profile)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def device_trace(logdir: str | Path) -> Iterator[None]:
    """Capture an XProf device profile of the enclosed region."""
    import jax

    jax.profiler.start_trace(str(logdir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def try_device_trace(logdir: str | Path) -> Iterator[bool]:
    """Probe-gated :func:`device_trace`: yields True when the capture
    started, False when ``jax.profiler`` (or its backend plumbing) is
    unavailable — the enclosed work runs either way, so on-demand
    profiling (``runbook profile``, ``bench.py --profile``) degrades to a
    clean skip on dependency-free CPU CI instead of crashing the run."""
    started = False
    try:
        import jax

        jax.profiler.start_trace(str(logdir))
        started = True
    except Exception:  # noqa: BLE001 — any capture failure means "skip"
        pass
    try:
        yield started
    finally:
        if started:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001 — a failed stop must not
                pass  # poison the run whose work already completed


def read_spans(path: str | Path) -> list[dict[str, Any]]:
    """Load a span JSONL (for tooling/tests)."""
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _percentile(sorted_ms: list[float], q: float) -> float:
    """Exact nearest-rank-with-interpolation percentile of a sorted list."""
    if not sorted_ms:
        return 0.0
    pos = (len(sorted_ms) - 1) * q / 100.0
    lo = int(pos)
    hi = min(lo + 1, len(sorted_ms) - 1)
    return sorted_ms[lo] + (sorted_ms[hi] - sorted_ms[lo]) * (pos - lo)


# Span name -> dispatch-kind counter. One engine span = one device
# dispatch of that kind, so a trace JSONL alone reconstructs the PR-4
# counters (`runbook_prefill_dispatch_total` / `runbook_decode_dispatch_
# total` / `runbook_mixed_dispatch_total`) — engine.decode_spec is a
# decode dispatch that happened to verify a speculative draft.
_DISPATCH_SPANS = {
    "engine.prefill": "prefill_steps",
    "engine.decode": "decode_dispatches",
    "engine.decode_spec": "decode_dispatches",
    "engine.mixed": "mixed_steps",
}


def dispatch_counters(spans: list[dict[str, Any]]) -> dict[str, int]:
    """Dispatch-kind counts recovered from a span JSONL — lets a tune
    run's measured refinement (or any banked bench arm) be sanity-checked
    from its trace alone: a config that claims mixed dispatch but traces
    zero ``engine.mixed`` spans did not serve the config it claims."""
    out = {"prefill_steps": 0, "decode_dispatches": 0, "mixed_steps": 0}
    for rec in spans:
        key = _DISPATCH_SPANS.get(str(rec.get("name", "")))
        if key is not None:
            out[key] += 1
    return out


def summarize_spans(spans: list[dict[str, Any]]) -> dict[str, dict[str, Any]]:
    """Per-span-name latency summary: count, p50/p95/max/total ms.

    The analysis half of ``runbook metrics --trace``: joins with the
    Prometheus side through span names (engine.decode, server.request, ...)
    and per-record ``ctx.request_id``.
    """
    by_name: dict[str, list[float]] = {}
    for rec in spans:
        by_name.setdefault(str(rec.get("name", "?")), []).append(
            float(rec.get("ms", 0.0)))
    out: dict[str, dict[str, Any]] = {}
    for name in sorted(by_name):
        ms = sorted(by_name[name])
        out[name] = {
            "count": len(ms),
            "p50_ms": round(_percentile(ms, 50), 3),
            "p95_ms": round(_percentile(ms, 95), 3),
            "max_ms": round(ms[-1], 3),
            "total_ms": round(sum(ms), 3),
        }
    return out
