"""Request-lifecycle timelines stitched from trace JSONL.

The tracer (:mod:`runbookai_tpu.utils.trace`) writes flat span/event
records; this module joins them back into ONE request's story — enqueue →
router placement → admit → prefill chunks → decode windows → finish/abort
— keyed by the correlation ids the serving stack already propagates:

- the caller's ``x-request-id`` rides as ``ctx.request_id`` on server
  spans, ``meta.trace_id`` on the engine's lifecycle events
  (``engine.enqueue`` / ``engine.admit`` / ``engine.request``) and on the
  fleet router's ``router.place`` / ``router.shed`` /
  ``router.page_pull`` events;
- the engine-internal request id (``r{i}-…`` when fleeted) appears as
  ``meta.request`` on lifecycle events and inside ``meta.requests`` on
  dispatch spans (``engine.prefill`` / ``engine.decode`` /
  ``engine.decode_spec`` / ``engine.mixed``) — a dp fleet's retries each
  contribute their own engine request, so a timeline shows the aborted
  attempt AND the replica that finally served it.

``runbook timeline <request-id> --trace <file>`` renders the tree;
:func:`lifecycle_summary` powers the queue-wait / router-placement block
of ``runbook metrics --trace``.
"""

from __future__ import annotations

from typing import Any, Optional

# Dispatch spans that carry a meta.requests attribution list.
DISPATCH_SPANS = ("engine.prefill", "engine.decode", "engine.decode_spec",
                  "engine.mixed")

# Fleet-wide incident markers (obs/incident.py): not owned by any one
# request, but stitched into every timeline they overlap — a dp retry
# during an incident must be visible in one view.
INCIDENT_EVENTS = ("incident.open", "incident.resolve")
_DISPATCH_LABEL = {
    "engine.prefill": "prefill chunk",
    "engine.decode": "decode window",
    "engine.decode_spec": "decode window (spec-verify)",
    "engine.mixed": "mixed dispatch",
}


def _meta(rec: dict[str, Any]) -> dict[str, Any]:
    meta = rec.get("meta")
    return meta if isinstance(meta, dict) else {}


def _ctx(rec: dict[str, Any]) -> dict[str, Any]:
    ctx = rec.get("ctx")
    return ctx if isinstance(ctx, dict) else {}


def _start_ts(rec: dict[str, Any]) -> float:
    """Span records are written at CLOSE (ts = end); order by start."""
    return float(rec.get("ts", 0.0)) - float(rec.get("ms", 0.0)) / 1e3


def resolve_engine_requests(spans: list[dict[str, Any]],
                            request_id: str) -> set[str]:
    """Engine-internal request ids owned by ``request_id``.

    The query id may itself BE an engine id (bench/tests trace without a
    server in front), or an ``x-request-id`` that one or more engine
    requests carried as ``trace_id`` (fleet retries → several)."""
    rids = {request_id}
    for rec in spans:
        meta = _meta(rec)
        if meta.get("trace_id") == request_id and "request" in meta:
            rids.add(str(meta["request"]))
    return rids


def build_timeline(spans: list[dict[str, Any]],
                   request_id: str) -> Optional[dict[str, Any]]:
    """Stitch one request's records into an ordered event list.

    Returns None when no record references the id. Each event carries
    ``rel_ms`` (offset from the request's first record), the raw span
    name, duration, and the interesting meta fields."""
    rids = resolve_engine_requests(spans, request_id)
    picked: list[dict[str, Any]] = []
    for rec in spans:
        name = str(rec.get("name", ""))
        meta = _meta(rec)
        owns = (
            _ctx(rec).get("request_id") == request_id
            or meta.get("trace_id") == request_id
            or str(meta.get("request")) in rids
            or (name in DISPATCH_SPANS
                and any(str(r) in rids
                        for r in (meta.get("requests") or ())))
        )
        if owns:
            picked.append(rec)
    if not picked:
        return None
    picked.sort(key=_start_ts)
    t0 = _start_ts(picked[0])
    events: list[dict[str, Any]] = []
    finish: Optional[dict[str, Any]] = None
    replicas: set[int] = set()
    for rec in picked:
        name = str(rec.get("name", ""))
        meta = _meta(rec)
        ev: dict[str, Any] = {
            "name": name,
            "rel_ms": round((_start_ts(rec) - t0) * 1e3, 3),
            "ms": float(rec.get("ms", 0.0)),
        }
        if "replica" in meta:
            ev["replica"] = meta["replica"]
            replicas.add(int(meta["replica"]))
        if name in DISPATCH_SPANS:
            ev["label"] = _DISPATCH_LABEL[name]
            for key in ("batch", "tokens", "k", "prefill_rows"):
                if key in meta:
                    ev[key] = meta[key]
        elif name == "engine.enqueue":
            ev["label"] = "enqueue"
            ev["request"] = meta.get("request")
            ev["prompt_tokens"] = meta.get("prompt_tokens")
        elif name == "engine.admit":
            ev["label"] = "admit"
            ev["request"] = meta.get("request")
            ev["cached_tokens"] = meta.get("cached_tokens")
            ev["queue_ms"] = meta.get("queue_ms")
            ev["cls"] = meta.get("cls")
        elif name == "router.place":
            hit = meta.get("affinity")
            ev["label"] = (f"router.place → replica {meta.get('replica')}"
                           + (" (affinity hit)" if hit else ""))
            ev["affinity"] = hit
        elif name == "router.page_pull":
            # Cross-replica KV pull / prefill→decode handoff: the span
            # that proves the request rode staged pages instead of a
            # re-prefill (replica = destination, src = the page source).
            ev["label"] = (f"page pull ← replica {meta.get('src')} "
                           f"({meta.get('pages')} pages, "
                           f"{meta.get('pull_ms')} ms)")
            ev["src"] = meta.get("src")
            ev["pages"] = meta.get("pages")
            ev["pull_ms"] = meta.get("pull_ms")
        elif name == "router.shed":
            ev["label"] = "router.shed (all replicas saturated)"
        elif name == "engine.request":
            ev["label"] = f"finish: {meta.get('reason')}"
            ev["request"] = meta.get("request")
            ev["reason"] = meta.get("reason")
            ev["generated"] = meta.get("generated")
            if "ttft_ms" in meta:
                ev["ttft_ms"] = meta["ttft_ms"]
            finish = ev
        elif name == "server.request":
            ev["label"] = (f"server.request {_meta(rec).get('route', '')}"
                           .strip())
        else:
            ev["label"] = name
        events.append(ev)
    last = max(ev["rel_ms"] + ev["ms"] for ev in events)
    # Incident span band: fleet-wide incident.open/resolve markers
    # overlapping this request's window ride into the timeline (with a
    # small slack so an open that preceded the request by a beat still
    # shows), labeled so the operator sees the request's dispatches AND
    # the incident they ran inside in one view.
    incidents: set[str] = set()
    t_end = t0 + last / 1e3
    for rec in spans:
        name = str(rec.get("name", ""))
        if name not in INCIDENT_EVENTS:
            continue
        ts = float(rec.get("ts", 0.0))
        if not (t0 - 1.0 <= ts <= t_end + 1.0):
            continue
        meta = _meta(rec)
        inc_id = str(meta.get("incident", "?"))
        incidents.add(inc_id)
        ev = {
            "name": name,
            "rel_ms": round((ts - t0) * 1e3, 3),
            "ms": 0.0,
            "incident": inc_id,
            "signal": meta.get("signal"),
        }
        if name == "incident.open":
            ev["label"] = (f"⚠ incident open: {meta.get('signal')} "
                           f"({inc_id}, {meta.get('severity', '?')})")
        else:
            dur = meta.get("duration_s")
            ev["label"] = (f"✓ incident resolve: {meta.get('signal')} "
                           f"({inc_id}"
                           + (f", {dur}s" if dur is not None else "")
                           + ")")
        events.append(ev)
    events.sort(key=lambda e: e["rel_ms"])
    return {
        "request_id": request_id,
        "engine_requests": sorted(rids - {request_id}),
        "replicas": sorted(replicas),
        "incidents": sorted(incidents),
        "total_ms": round(last, 3),
        "finish": ({"reason": finish.get("reason"),
                    "generated": finish.get("generated"),
                    "ttft_ms": finish.get("ttft_ms")}
                   if finish else None),
        "events": events,
    }


def render_timeline(tl: dict[str, Any], max_events: int = 60) -> str:
    """ASCII span tree of a built timeline (``runbook timeline``).

    Long decode phases collapse: when the event list exceeds
    ``max_events``, the middle dispatch windows are elided into one
    summary line so the enqueue/placement/admit head and the finish tail
    stay readable."""
    head = [f"request {tl['request_id']} — {tl['total_ms']:.1f} ms total"]
    if tl["engine_requests"]:
        head.append(f"  engine ids: {', '.join(tl['engine_requests'])}")
    if tl["replicas"]:
        head.append("  replicas: "
                    + ", ".join(str(r) for r in tl["replicas"]))
    if tl.get("incidents"):
        head.append("  incidents: " + ", ".join(tl["incidents"]))
    events = tl["events"]
    shown: list[Any] = list(events)
    if len(events) > max_events:
        keep_head = max_events // 2
        keep_tail = max_events - keep_head
        elided = events[keep_head:-keep_tail]
        dispatch_ms = sum(e["ms"] for e in elided)
        shown = (events[:keep_head]
                 + [{"_elided": len(elided), "_ms": dispatch_ms}]
                 + events[-keep_tail:])
    lines = head
    for i, ev in enumerate(shown):
        branch = "└─" if i == len(shown) - 1 else "├─"
        if "_elided" in ev:
            lines.append(f"{branch} … {ev['_elided']} more dispatch "
                         f"windows ({ev['_ms']:.1f} ms)")
            continue
        extras = []
        for key in ("k", "batch", "tokens", "prefill_rows", "generated",
                    "cached_tokens", "queue_ms", "cls", "prompt_tokens",
                    "ttft_ms"):
            if ev.get(key) is not None:
                extras.append(f"{key}={ev[key]}")
        if ev.get("replica") is not None and "router" not in ev["name"]:
            extras.append(f"replica={ev['replica']}")
        dur = f" {ev['ms']:.1f}ms" if ev["ms"] else ""
        suffix = f"  [{', '.join(extras)}]" if extras else ""
        lines.append(f"{branch} +{ev['rel_ms']:9.1f}ms  "
                     f"{ev['label']}{dur}{suffix}")
    return "\n".join(lines)


def lifecycle_summary(spans: list[dict[str, Any]]) -> dict[str, Any]:
    """Population view of the lifecycle events for
    ``runbook metrics --trace``: queue-wait distribution (from
    ``engine.admit``'s ``queue_ms``) and router placement counts — both
    previously invisible in the per-span duration summary (events have
    ``ms=0`` so their latency story lives in meta, not duration)."""
    from runbookai_tpu.utils.trace import _percentile

    queue_ms: list[float] = []
    by_class: dict[str, list[float]] = {}
    placements: dict[str, int] = {}
    affinity_hits = 0
    sheds = 0
    admits = 0
    for rec in spans:
        name = str(rec.get("name", ""))
        meta = _meta(rec)
        if name == "engine.admit":
            admits += 1
            if meta.get("queue_ms") is not None:
                queue_ms.append(float(meta["queue_ms"]))
                # Per-priority-class breakdown (the admit event carries
                # its class since the sched/ layer landed): the
                # starvation picture — batch may legitimately wait,
                # interactive must not.
                cls = str(meta.get("cls") or "unknown")
                by_class.setdefault(cls, []).append(float(meta["queue_ms"]))
        elif name == "router.place":
            replica = str(meta.get("replica", "?"))
            placements[replica] = placements.get(replica, 0) + 1
            if meta.get("affinity"):
                affinity_hits += 1
        elif name == "router.shed":
            sheds += 1
    queue_ms.sort()

    def _dist(values: list[float]) -> dict[str, Any]:
        values = sorted(values)
        return {
            "count": len(values),
            "p50": round(_percentile(values, 50), 3),
            "p95": round(_percentile(values, 95), 3),
            "max": round(values[-1], 3) if values else 0.0,
        }

    out: dict[str, Any] = {
        "admissions": admits,
        "queue_wait_ms": _dist(queue_ms),
    }
    if by_class:
        out["queue_wait_ms_by_class"] = {
            cls: _dist(values) for cls, values in sorted(by_class.items())}
    if placements or sheds:
        total = sum(placements.values())
        out["router"] = {
            "placements": {k: placements[k] for k in sorted(placements)},
            "affinity_hits": affinity_hits,
            "affinity_hit_ratio": (round(affinity_hits / total, 4)
                                   if total else 0.0),
            "sheds": sheds,
        }
    return out
