"""Tokenization: real tokenizers replacing the reference's chars/4 estimate.

Parity target: reference ``src/utils/tokens.ts`` (``estimateTokens`` :14 is a
chars/4 heuristic; truncation :46). The TPU build serves models in-tree, so a
real tokenizer is both available and required. Two implementations:

- :class:`HFTokenizer` — wraps a ``tokenizer.json`` (HuggingFace ``tokenizers``
  Rust lib) from a local model directory (Llama-3 BPE, bge WordPiece).
- :class:`ByteTokenizer` — deterministic byte-level fallback (vocab = 256 bytes
  + specials) used when no tokenizer file exists (no-egress CI, random-init
  benches). Produces real token streams with the same API so the engine,
  chat template, and guided decoding are exercised identically.
"""

from __future__ import annotations

from functools import lru_cache
from pathlib import Path
from typing import Optional, Sequence

# Special token names shared by both tokenizers. The byte tokenizer assigns
# them ids above 255; HF tokenizers resolve them from their vocab when present.
SPECIAL_TOKENS = [
    "<|begin_of_text|>",
    "<|end_of_text|>",
    "<|start_header_id|>",
    "<|end_header_id|>",
    "<|eot_id|>",
    "<|pad|>",
]


class ByteTokenizer:
    """UTF-8 byte tokenizer with Llama-3-style special tokens."""

    def __init__(self) -> None:
        self._special_to_id = {tok: 256 + i for i, tok in enumerate(SPECIAL_TOKENS)}
        self._id_to_special = {v: k for k, v in self._special_to_id.items()}
        self.vocab_size = 256 + len(SPECIAL_TOKENS)
        self.bos_id = self._special_to_id["<|begin_of_text|>"]
        self.eos_id = self._special_to_id["<|end_of_text|>"]
        self.eot_id = self._special_to_id["<|eot_id|>"]
        self.pad_id = self._special_to_id["<|pad|>"]

    def token_to_id(self, token: str) -> Optional[int]:
        return self._special_to_id.get(token)

    def encode(self, text: str, allow_special: bool = True) -> list[int]:
        if not allow_special:
            return list(text.encode("utf-8"))
        ids: list[int] = []
        i = 0
        while i < len(text):
            matched = False
            if text[i] == "<":
                for tok, tid in self._special_to_id.items():
                    if text.startswith(tok, i):
                        ids.append(tid)
                        i += len(tok)
                        matched = True
                        break
            if not matched:
                ids.extend(text[i].encode("utf-8"))
                i += 1
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        out: list[str] = []
        buf = bytearray()
        for tid in ids:
            if tid < 256:
                buf.append(tid)
            else:
                if buf:
                    out.append(buf.decode("utf-8", errors="replace"))
                    buf = bytearray()
                out.append(self._id_to_special.get(tid, ""))
        if buf:
            out.append(buf.decode("utf-8", errors="replace"))
        return "".join(out)

    # Single-token byte decode used by guided decoding to walk candidates.
    def id_to_bytes(self, tid: int) -> bytes:
        if tid < 256:
            return bytes([tid])
        return self._id_to_special.get(tid, "").encode("utf-8")

    @property
    def special_ids(self) -> frozenset[int]:
        """Control tokens — never admissible as grammar *content* (their
        id_to_bytes expansion is markup like ``<|eot_id|>``, not text)."""
        return frozenset(self._id_to_special)


@lru_cache(maxsize=1)
def _gpt2_byte_decoder() -> dict[str, int]:
    """Inverse of GPT-2's bytes_to_unicode: vocab char -> raw byte.

    Byte-level BPE tokenizers (GPT-2, Llama-3, Qwen2) store each raw byte
    as a printable unicode char in vocab strings; mapping back recovers the
    exact byte sequence of a single token, even when it is half of a
    multi-byte UTF-8 character."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("¡"), ord("¬") + 1))
          + list(range(ord("®"), ord("ÿ") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


class HFTokenizer:
    """Wraps a local ``tokenizer.json`` via the HuggingFace ``tokenizers`` lib."""

    def __init__(self, path: str | Path):
        from tokenizers import Tokenizer as _Tok  # deferred heavy import

        p = Path(path)
        if p.is_dir():
            p = p / "tokenizer.json"
        self._tok = _Tok.from_file(str(p))
        self.vocab_size = self._tok.get_vocab_size()
        self.bos_id = self._find_id(["<|begin_of_text|>", "<s>", "[CLS]"])
        self.eos_id = self._find_id(
            ["<|end_of_text|>", "<|endoftext|>", "</s>", "[SEP]"])
        # End-of-turn: Llama-3 <|eot_id|>, ChatML (Qwen2) <|im_end|>.
        self.eot_id = (self._find_id(["<|eot_id|>", "<|im_end|>"])
                       or self.eos_id)
        self.pad_id = self._find_id(["<|pad|>", "<pad>", "[PAD]"]) or 0

    def _find_id(self, candidates: list[str]) -> Optional[int]:
        for c in candidates:
            tid = self._tok.token_to_id(c)
            if tid is not None:
                return tid
        return None

    def token_to_id(self, token: str) -> Optional[int]:
        return self._tok.token_to_id(token)

    def encode(self, text: str, allow_special: bool = True) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False).ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=False)

    # Single-token byte decode used by guided decoding to walk candidates
    # and by streaming's incremental UTF-8 decoder. Byte-level BPE vocab
    # strings (Llama-3/GPT-2 style) map char-by-char through the inverted
    # bytes_to_unicode table, so a multi-byte character SPLIT ACROSS TOKENS
    # round-trips exactly; decode([tid]) would yield U+FFFD per half-token.
    def id_to_bytes(self, tid: int) -> bytes:
        token = self._tok.id_to_token(tid)
        if token is None:
            return b""
        dec = _gpt2_byte_decoder()
        if all(ch in dec for ch in token):
            return bytes(dec[ch] for ch in token)
        # Non-byte-level vocab (sentencepiece "▁" style) or special token.
        return self._tok.decode([tid], skip_special_tokens=False).encode("utf-8")

    @property
    def special_ids(self) -> frozenset[int]:
        """ALL added/control tokens (Llama-3 ships ~250 reserved specials) —
        none may be admitted as grammar content: their byte expansion is
        markup like ``<|start_header_id|>`` that a string automaton would
        otherwise accept."""
        try:
            ids = set(self._tok.get_added_tokens_decoder())
        except AttributeError:  # older `tokenizers` releases
            ids = set()
        for tid in (self.bos_id, self.eos_id, self.eot_id, self.pad_id):
            if tid is not None:
                ids.add(tid)
        return frozenset(ids)


Tokenizer = ByteTokenizer | HFTokenizer


def load_tokenizer(path: Optional[str | Path]) -> Tokenizer:
    """Load a real tokenizer when a path is given, else the byte fallback."""
    if path:
        p = Path(path)
        f = p / "tokenizer.json" if p.is_dir() else p
        if f.is_file():
            return HFTokenizer(f)
    return ByteTokenizer()


def estimate_tokens(text: str, tokenizer: Optional[Tokenizer] = None) -> int:
    """Token count — exact when a tokenizer is supplied, chars/4 otherwise
    (the reference's only option, ``tokens.ts:14``)."""
    if tokenizer is not None:
        return len(tokenizer.encode(text))
    return max(1, len(text) // 4)


def truncate_to_tokens(text: str, max_tokens: int, tokenizer: Optional[Tokenizer] = None) -> str:
    """Truncate to a token budget, appending a marker (``tokens.ts:46``)."""
    if estimate_tokens(text, tokenizer) <= max_tokens:
        return text
    marker = "\n... [truncated]"
    if tokenizer is not None:
        ids = tokenizer.encode(text)
        return tokenizer.decode(ids[:max_tokens]) + marker
    return text[: max_tokens * 4] + marker
