"""Force the jax CPU platform with a virtual multi-device host mesh.

Sharding/parallelism code is validated without TPU hardware on a virtual
CPU mesh (``--xla_force_host_platform_device_count``, SURVEY.md §4). The
environment's TPU plugin overrides the ``JAX_PLATFORMS`` env var, so the
platform must also be forced through ``jax.config`` — and all of it must
happen before the jax backend initializes. Shared by ``tests/conftest.py``
and ``__graft_entry__.dryrun_multichip`` so the workaround can't drift.
"""

from __future__ import annotations

import os
import re

_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_platform(n_devices: int) -> None:
    """Make jax run on CPU with at least ``n_devices`` virtual devices.

    Must be called before the jax backend initializes; raises RuntimeError
    if jax already came up on another platform or with too few devices
    (env-var and config overrides are no-ops after initialization).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    match = re.search(rf"{_FLAG}=(\d+)", flags)
    if match is None:
        os.environ["XLA_FLAGS"] = (flags + f" {_FLAG}={n_devices}").strip()
    elif int(match.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = (
            flags[: match.start()] + f"{_FLAG}={n_devices}" + flags[match.end():]
        )
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

    devices = jax.devices()
    if devices[0].platform != "cpu" or len(devices) < n_devices:
        raise RuntimeError(
            f"force_cpu_platform: jax initialized before the override could "
            f"take effect (platform={devices[0].platform}, "
            f"{len(devices)} devices, need >= {n_devices} cpu). "
            f"Run in a fresh process."
        )
