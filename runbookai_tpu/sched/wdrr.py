"""Weighted-deficit (stride) scheduling of the engine's waiting queue.

The engine's classic admission order was strict priority then FCFS: a
steady interactive load starves batch forever, and — since every HTTP
request used to arrive at the same priority — a batch flood FIFO-starves
interactive. Stride scheduling fixes both with one mechanism: each class
owns a *pass* value advancing by ``stride = UNIT / weight`` per admitted
request, and admission always takes the head of the class with the
smallest pass. Over any window, class admits converge to the weight
ratio (8:1 interactive:batch by default) while staying FCFS within a
class — the weighted-deficit queue ROADMAP item 4 names.

Two properties the engine relies on:

- **Ordering is pure, admission advances.** :meth:`order` simulates the
  interleave over local pass copies (the engine may admit only a prefix
  of the order when slots/pages run out); only :meth:`commit` — called
  per ACTUAL admission — advances the persisted pass. A request the
  engine could not admit never charges its class.
- **No credit hoarding across idle.** A class absent (or idle) for a
  while re-joins at the floor of the active classes' passes, like a
  stride task joining at the global virtual time — otherwise a batch
  tier quiet for an hour would bank an hour of credit and flood the
  next thousand slots, exactly the latency spike this scheduler exists
  to prevent.
"""

from __future__ import annotations

from typing import Optional

from runbookai_tpu.sched import PRIORITY_BATCH, PRIORITY_INTERACTIVE

# Default class weights: interactive admits ~8 requests for every batch
# admit under contention. Batch still progresses (1 in 9) — never starves.
DEFAULT_WEIGHTS: dict[int, float] = {
    PRIORITY_BATCH: 1.0,
    PRIORITY_INTERACTIVE: 8.0,
}

# Stride numerator. Any positive constant works (only stride RATIOS
# matter); a highly-composite value keeps common weights' strides exact
# in binary floating point.
_STRIDE_UNIT = 840.0


class WeightedDeficitScheduler:
    """Per-class stride state + the waiting-list interleave.

    ``weights`` maps priority class → relative admission share. Unknown
    positive classes scale linearly above the largest configured weight
    (monotone: a higher class never gets a smaller share), unknown
    non-positive classes weigh 1.0 — so arbitrary caller ints stay legal
    engine priorities without any config.
    """

    def __init__(self, weights: Optional[dict[int, float]] = None):
        self.weights = dict(DEFAULT_WEIGHTS if weights is None else weights)
        for cls, w in self.weights.items():
            if not w > 0:
                raise ValueError(
                    f"class {cls} weight must be > 0, got {w}")
        self._pass: dict[int, float] = {}

    def weight_of(self, priority: int) -> float:
        w = self.weights.get(priority)
        if w is not None:
            return w
        if priority <= 0:
            return 1.0
        top = max(self.weights.values(), default=1.0)
        return top * priority

    def _stride(self, priority: int) -> float:
        return _STRIDE_UNIT / self.weight_of(priority)

    def _normalize(self, active: list[int]) -> None:
        """Bound every active class's banked credit to ONE stride.

        The virtual time is the LEADER's pass (the most-served active
        class); in a steady interleave every class's pass stays within
        one max-stride of it, so any class further behind — idle for a
        while, or never seen — is carrying banked credit from a period
        it wasn't competing in. Clamp it up to ``leader − max_stride``:
        a returning class gets at most one immediate admit (its fair
        in-rotation deficit), never a burst proportional to its idle
        time. (Clamping to the minimum KNOWN pass instead would be a
        no-op for a previously-served class whose stale pass IS the
        minimum — the hoarding bug this replaces.)"""
        known = [self._pass[c] for c in active if c in self._pass]
        leader = max(known) if known else 0.0
        floor = leader - max(self._stride(c) for c in active)
        for c in active:
            self._pass[c] = max(self._pass.get(c, floor), floor)

    def order(self, waiting: list) -> list:
        """Interleave ``waiting`` by class stride, FCFS (arrival time)
        within a class. Pure with respect to admission state: only the
        normalization clamp touches the persisted passes — simulation
        runs on local copies, so ordering twice equals ordering once."""
        if len(waiting) < 2:
            return list(waiting)
        buckets: dict[int, list] = {}
        for req in sorted(waiting, key=lambda r: r.arrival_time):
            buckets.setdefault(req.priority, []).append(req)
        if len(buckets) == 1:
            return next(iter(buckets.values()))
        self._normalize(list(buckets))
        local = {c: self._pass[c] for c in buckets}
        heads = {c: 0 for c in buckets}
        out: list = []
        while len(out) < len(waiting):
            # Smallest pass admits next; ties go to the higher class so a
            # cold start (all passes equal) serves interactive first.
            c = min((cls for cls in buckets if heads[cls] < len(buckets[cls])),
                    key=lambda cls: (local[cls], -cls))
            out.append(buckets[c][heads[c]])
            heads[c] += 1
            local[c] += self._stride(c)
        return out

    def commit(self, priority: int) -> None:
        """Advance the admitted request's class pass (call once per
        ACTUAL admission, after :meth:`order` chose it)."""
        self._pass[priority] = (self._pass.get(priority, 0.0)
                                + self._stride(priority))

    def snapshot(self) -> dict:
        """Live pass/weight state per class (debug surface)."""
        return {
            "weights": {str(c): w for c, w in sorted(self.weights.items())},
            "pass": {str(c): round(p, 3)
                     for c, p in sorted(self._pass.items())},
        }
